"""Per-request lifecycle timelines: the raw material of "why was THIS
request slow".

A `RequestTimeline` is a bounded host-side list of timestamped
lifecycle events for ONE serving request (enqueued, admitted,
prefill_start, first_token, per-tick commits, terminal state). The
engine appends events from its scheduler thread between jit
boundaries — timelines never add traced work, so the one-decode-compile
contract and greedy token identity are untouched (the parity test pins
both).

`phases()` derives the latency waterfall the debug endpoints and the
`fstpu_request_phase_seconds{phase}` histograms expose:

- ``queue_wait_s``: submit → prefill_start (admission wait + any paged
  block-exhaustion deferral);
- ``prefill_s``: prefill_start → first_token (the bucketed prefill
  dispatch, i.e. TTFT minus queue wait);
- ``decode_s``: first_token → terminal (the decode-tick share);
- ``decode_stall_s``: decode_s minus the wall time of the ticks that
  actually committed tokens to this request — time the request sat
  live in a lane while the engine was NOT inside its decode dispatch
  (host scheduling, other lanes' prefills, serve-loop idle waits).

The first three phases telescope: their sum equals ``total_s`` (the
submit → terminal wall clock) by construction, which is the acceptance
check `GET /debug/requests/<id>` is pinned against. Missing marks (a
request rejected or cancelled before admission) degrade gracefully:
the absent phases read 0 and queue_wait absorbs the whole latency.

Pure stdlib; timestamps come from the caller's clock (the engine's
injectable monotonic clock), so tests drive deterministic waterfalls.
"""

from __future__ import annotations

import time
from typing import List, Optional, Tuple

#: terminal lifecycle event names (mirror serving.engine's states);
#: "evacuated" ends the SOURCE replica's timeline when a live lane is
#: exported during drain (docs/fault_tolerance.md "Preemption
#: runbook") — the adopting replica's timeline continues the request
TERMINAL_EVENTS = ("finished", "cancelled", "expired", "rejected",
                   "evacuated")

#: the derived waterfall phases, in lifecycle order
PHASE_NAMES = ("queue_wait_s", "prefill_s", "decode_s")

#: per-request event cap: a long generation commits one event per tick,
#: so the cap bounds memory without losing the lifecycle marks (which
#: all land before the commit stream)
DEFAULT_MAX_EVENTS = 512


class RequestTimeline:
    """Bounded timestamped event list for one request's lifecycle."""

    __slots__ = ("t0", "events", "dropped", "dropped_tick_s",
                 "max_events", "trace_id", "parent_span_id",
                 "epoch_unix_s")

    def __init__(self, t0: float, max_events: int = DEFAULT_MAX_EVENTS,
                 epoch: Optional[float] = None):
        self.t0 = float(t0)
        #: (seconds since t0, event name, attrs dict or None)
        self.events: List[Tuple[float, str, Optional[dict]]] = []
        self.dropped = 0
        #: tick wall time carried by dropped commit events — kept so a
        #: capped timeline's decode_stall_s stays honest
        self.dropped_tick_s = 0.0
        self.max_events = int(max_events)
        #: distributed-trace correlation (docs/observability.md
        #: "Distributed tracing"): set by the submitter when the
        #: request arrived with a traceparent; None otherwise
        self.trace_id: Optional[str] = None
        self.parent_span_id: Optional[str] = None
        #: wall-clock anchor for the monotonic t0 — what lets the
        #: fleet assembler place this process's relative times on the
        #: router's axis (skew reported, not hidden)
        self.epoch_unix_s = round(
            time.time() if epoch is None else float(epoch), 6)

    def add(self, t: float, event: str, **attrs) -> None:
        """Append one event at absolute clock time `t`; counts (instead
        of stores) NON-terminal events past the cap so a pathological
        generation cannot grow host memory unboundedly. Terminal events
        always land (at most one fires per request), so a capped
        timeline still carries its end mark and `phases()` stays
        end-anchored."""
        if event not in TERMINAL_EVENTS and \
                len(self.events) >= self.max_events:
            self.dropped += 1
            self.dropped_tick_s += float(attrs.get("tick_s", 0.0))
            return
        self.events.append((round(t - self.t0, 6), event,
                            attrs if attrs else None))

    def mark(self, event: str) -> Optional[float]:
        """Relative time of the FIRST occurrence of `event`, or None."""
        for t, name, _ in self.events:
            if name == event:
                return t
        return None

    def end_mark(self) -> Optional[float]:
        """Relative time of the terminal event, if one was recorded."""
        for t, name, _ in reversed(self.events):
            if name in TERMINAL_EVENTS:
                return t
        return None

    def phases(self, now: Optional[float] = None) -> dict:
        """The latency waterfall. `now` (absolute clock) bounds a
        still-live request; a finished one uses its terminal event.
        queue_wait + prefill + decode == total exactly (up to the 6-dp
        rounding of each term)."""
        end = self.end_mark()
        if end is None:
            end = (now - self.t0) if now is not None else (
                self.events[-1][0] if self.events else 0.0)
        prefill_start = self.mark("prefill_start")
        first_token = self.mark("first_token")
        ps = end if prefill_start is None else min(prefill_start, end)
        ft = ps if first_token is None else min(max(first_token, ps), end)
        tick_s = self.dropped_tick_s + \
            sum((attrs or {}).get("tick_s", 0.0)
                for _, name, attrs in self.events
                if name == "commit")
        decode = max(end - ft, 0.0)
        return {
            "queue_wait_s": round(max(ps, 0.0), 6),
            "prefill_s": round(max(ft - ps, 0.0), 6),
            "decode_s": round(decode, 6),
            "decode_stall_s": round(max(decode - tick_s, 0.0), 6),
            "total_s": round(max(end, 0.0), 6),
        }

    def to_dict(self) -> dict:
        """JSON-ready event list (times relative to submit)."""
        events = []
        for t, name, attrs in self.events:
            e = {"t_s": t, "event": name}
            if attrs:
                e.update(attrs)
            events.append(e)
        return {"events": events, "dropped_events": self.dropped,
                "trace_id": self.trace_id,
                "parent_span_id": self.parent_span_id,
                "epoch_unix_s": self.epoch_unix_s}
