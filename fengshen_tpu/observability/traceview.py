"""traceview: convert assembled traces (or flight-recorder trace
bundles) to Chrome trace-event JSON loadable in Perfetto /
chrome://tracing (docs/observability.md "Distributed tracing").

    # an assembled /debug/traces/<id> payload saved to a file
    python -m fengshen_tpu.observability.traceview trace.json -o out.json

    # a flight-recorder bundle directory (reads its traces.json)
    python -m fengshen_tpu.observability.traceview fstpu_dumps/dump-0000-sigterm

The output is the Chrome trace-event "JSON object format":
``{"traceEvents": [...], "displayTimeUnit": "ms"}`` where each
complete-span event is ``{"name", "cat", "ph": "X", "ts", "dur",
"pid", "tid", "args"}`` (ts/dur in MICROSECONDS) plus ``"M"``
process_name metadata rows naming each process. One pid per process:
the router is pid 1, each attached replica the next pid in sorted
order — Perfetto then draws the cross-process waterfall as stacked
tracks on one time axis.

Clock anchoring follows the assembler's math: a replica's events are
shifted by its ``offset_in_trace_s`` onto the router's axis; if any
event would land before t=0 (a replica clock running behind the
router's), the WHOLE view is shifted right so every timestamp is
non-negative — relative ordering, which is what the view is for, is
unaffected, and the per-replica ``clock_skew_s`` rides along in the
attachment's args so the viewer can judge how much to trust the
alignment.

Pure stdlib, deterministic output (sorted keys, integer microseconds):
the same input bytes produce the same output bytes under any
PYTHONHASHSEED.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from fengshen_tpu.observability.timeline import PHASE_NAMES


def _us(seconds: float) -> int:
    return int(round(float(seconds) * 1e6))


def _meta(pid: int, name: str) -> dict:
    return {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": name}}


def _span_events(spans: List[dict], pid: int, cat: str) -> List[dict]:
    events = []
    for span in spans:
        dur = span.get("duration_s")
        args = dict(span.get("attrs") or {})
        args["span_id"] = span.get("span_id")
        if span.get("parent_span_id"):
            args["parent_span_id"] = span["parent_span_id"]
        events.append({
            "name": span.get("name", "span"), "cat": cat, "ph": "X",
            "ts": _us(span.get("t_start_s", 0.0)),
            "dur": _us(dur if dur is not None else 0.0),
            "pid": pid, "tid": 1, "args": args,
        })
    return events


def _waterfall_events(entry: dict, pid: int) -> List[dict]:
    """One replica attachment → phase spans + instant lifecycle
    marks, shifted onto the router's axis by offset_in_trace_s."""
    base = float(entry.get("offset_in_trace_s") or 0.0)
    args_common = {}
    if "clock_skew_s" in entry:
        args_common["clock_skew_s"] = entry["clock_skew_s"]
    if "waterfall" not in entry:
        # a dead replica degraded to an {"error": ...} attachment:
        # render the diagnostic, not a healthy-looking empty track
        return [{
            "name": "fetch_error", "cat": "replica", "ph": "i",
            "s": "t", "ts": _us(base), "pid": pid, "tid": 1,
            "args": dict(args_common, error=entry.get("error")),
        }]
    waterfall = entry.get("waterfall") or {}
    events = []
    phases = waterfall.get("phases") or {}
    cursor = base
    for phase in PHASE_NAMES:
        dur = float(phases.get(phase) or 0.0)
        events.append({
            "name": phase[:-2], "cat": "replica", "ph": "X",
            "ts": _us(cursor), "dur": _us(dur), "pid": pid, "tid": 1,
            "args": dict(args_common,
                         request_id=waterfall.get("request_id")),
        })
        cursor += dur
    for ev in waterfall.get("events") or []:
        args = {k: v for k, v in ev.items() if k not in ("t_s", "event")}
        events.append({
            "name": ev.get("event", "event"), "cat": "replica",
            "ph": "i", "s": "t",
            "ts": _us(base + float(ev.get("t_s") or 0.0)),
            "pid": pid, "tid": 2, "args": args,
        })
    return events


def chrome_trace(payload: dict) -> dict:
    """Convert ONE of the three input shapes to trace-event JSON:
    an assembled `/debug/traces/<id>` document ({"router", "replicas"}),
    a ledger/provider dump ({"service", "traces": [...]}), or a single
    raw ledger trace ({"trace_id", "spans"})."""
    events: List[dict] = []
    other = {}
    if "router" in payload:                      # assembled document
        router = payload.get("router") or {}
        service = router.get("service") or "router"
        events.append(_meta(1, service))
        events.extend(_span_events(router.get("spans") or [], 1,
                                   service))
        for i, name in enumerate(sorted(payload.get("replicas") or {})):
            pid = 2 + i
            events.append(_meta(pid, name))
            events.extend(_waterfall_events(
                payload["replicas"][name], pid))
        other = {"trace_id": payload.get("trace_id"),
                 "request_id": payload.get("request_id")}
    elif "traces" in payload:                    # provider dump
        service = payload.get("service") or "service"
        events.append(_meta(1, service))
        for trace in payload.get("traces") or []:
            events.extend(_span_events(trace.get("spans") or [], 1,
                                       service))
        other = {"service": service,
                 "traces": len(payload.get("traces") or [])}
    else:                                        # one raw ledger trace
        service = payload.get("service") or "service"
        events.append(_meta(1, service))
        events.extend(_span_events(payload.get("spans") or [], 1,
                                   service))
        other = {"trace_id": payload.get("trace_id")}
    # Perfetto dislikes negative timestamps (a replica clock running
    # behind the router's): shift everything right, keep ordering
    min_ts = min((e["ts"] for e in events if e["ph"] != "M"),
                 default=0)
    if min_ts < 0:
        for e in events:
            if e["ph"] != "M":
                e["ts"] -= min_ts
        other["shifted_us"] = -min_ts
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": other}


def _load(path: str) -> Optional[dict]:
    """A json file, or a flight-recorder bundle dir (its traces.json)."""
    if os.path.isdir(path):
        path = os.path.join(path, "traces.json")
    try:
        with open(path) as f:
            out = json.load(f)
        return out if isinstance(out, dict) else None
    except (OSError, ValueError):
        return None


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m fengshen_tpu.observability.traceview",
        description="assembled trace / trace bundle -> Chrome "
                    "trace-event JSON (Perfetto, chrome://tracing)")
    parser.add_argument("input", type=str,
                        help="assembled-trace json file, ledger dump, "
                             "or flight-recorder bundle directory")
    parser.add_argument("-o", "--output", type=str, default=None,
                        help="output path (default: stdout)")
    args = parser.parse_args(argv)
    payload = _load(args.input)
    if payload is None:
        print(f"traceview: cannot read a trace from {args.input!r}",
              file=sys.stderr)
        return 2
    text = json.dumps(chrome_trace(payload), sort_keys=True, indent=1)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text + "\n")
    else:
        print(text)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
