"""Step-stats pipeline: step time, tokens/s, MFU, goodput for the
Trainer.

The Trainer feeds one ``record_execution`` per jitted dispatch (K steps
under --steps_per_execution) and asks for a ``window_entry`` at each
log boundary; rewinds and the guards' cumulative ``bad_step_count``
feed the goodput ledger. Everything lands twice: in the returned dict
(merged into the metrics.jsonl step entry — keys are the PR-3 names
plus ``mfu``/``goodput``) and in registry gauges for `/metrics`.

Definitions (docs/observability.md):

- ``tokens_per_sec``: tokens consumed over the wall-time window since
  the last log entry (includes data loading — it's the pipeline rate,
  not the bare step rate).
- ``mfu``: tokens_per_sec * flops_per_token / (peak * n_devices). The
  peak resolves via `flops.peak_flops_per_chip`, so mfu is ALWAYS
  present and finite — on CPU against the documented nominal figure.
- ``goodput``: productive steps over attempted steps, cumulative for
  the run: attempted = global_step + steps replayed by rewinds,
  productive = global_step - guarded-away (bad) steps. 1.0 for a clean
  run; dips when the guards skip updates or a rewind replays a window.
"""

from __future__ import annotations

import time
from typing import Any, Optional

from fengshen_tpu.observability.flops import peak_flops_per_chip
from fengshen_tpu.observability.registry import (MetricsRegistry,
                                                 get_registry)


class StepStats:
    def __init__(self, flops_per_token: float, n_devices: int,
                 device_kind: Optional[str] = None,
                 registry: Optional[MetricsRegistry] = None,
                 clock=time.perf_counter):
        self.flops_per_token = float(flops_per_token)
        self.peak_total = peak_flops_per_chip(device_kind) * max(
            int(n_devices), 1)
        self._clock = clock
        self._window_start = clock()
        self._window_tokens = 0
        self._window_steps = 0
        self._replayed_steps = 0
        reg = registry if registry is not None else get_registry()
        self._g_step = reg.gauge(
            "fstpu_train_step", "current global step")
        self._g_tps = reg.gauge(
            "fstpu_train_tokens_per_sec",
            "tokens/s over the last log window")
        self._g_mfu = reg.gauge(
            "fstpu_train_mfu",
            "model-FLOPs-utilization over the last log window")
        self._g_goodput = reg.gauge(
            "fstpu_train_goodput",
            "cumulative productive/attempted step ratio")
        self._g_bad = reg.gauge(
            "fstpu_train_bad_steps_total",
            "cumulative steps skipped by the in-graph guards")
        self._c_rewinds = reg.counter(
            "fstpu_train_rewinds_total",
            "rewind-on-divergence restores this run")
        self._c_tokens = reg.counter(
            "fstpu_train_tokens_total", "tokens consumed this run")

    # -- feed ---------------------------------------------------------
    def record_execution(self, n_steps: int, n_tokens: int) -> None:
        self._window_steps += int(n_steps)
        self._window_tokens += int(n_tokens)
        self._c_tokens.inc(int(n_tokens))

    def record_rewind(self, from_step: int, to_step: int) -> None:
        """A rewind will replay [to_step, from_step) — count those
        against goodput's attempted-steps denominator."""
        self._replayed_steps += max(int(from_step) - int(to_step), 0)
        self._c_rewinds.inc()

    # -- read ---------------------------------------------------------
    def goodput(self, global_step: int, bad_step_count: int) -> float:
        attempted = int(global_step) + self._replayed_steps
        if attempted <= 0:
            return 1.0
        productive = max(int(global_step) - int(bad_step_count), 0)
        return productive / attempted

    def window_entry(self, global_step: int,
                     bad_step_count: int = 0) -> dict:
        """Close the current window: compute + publish tokens_per_sec /
        mfu / goodput, reset the window, return the dict to merge into
        the step log entry."""
        now = self._clock()
        dt = now - self._window_start
        tps = self._window_tokens / dt if dt > 0 else 0.0
        mfu = tps * self.flops_per_token / self.peak_total
        goodput = self.goodput(global_step, bad_step_count)
        self._g_step.set(int(global_step))
        self._g_tps.set(tps)
        self._g_mfu.set(mfu)
        self._g_goodput.set(goodput)
        self._g_bad.set(int(bad_step_count))
        self._window_start = now
        self._window_tokens = 0
        self._window_steps = 0
        return {"tokens_per_sec": tps, "mfu": mfu, "goodput": goodput}
