"""Unified observability: metrics registry, Prometheus exposition,
jsonl event sink, MFU/goodput step stats, and trace spans
(docs/observability.md).

Every subsystem plugs into this one core instead of inventing its own
telemetry dialect: the Trainer's step log, the serving engine's
`EngineMetrics`, the resilience events, and bench's JSON rows all write
through here; ``GET /metrics`` (api server routes + the standalone
exporter thread) and `/stats` read from it.
"""

from fengshen_tpu.observability.buildinfo import (BUILD_INFO_METRIC,
                                                  WARMUP_METRIC,
                                                  record_build_info,
                                                  record_warmup_seconds)
from fengshen_tpu.observability.exposition import (CONTENT_TYPE_LATEST,
                                                   MetricsServer,
                                                   render_prometheus,
                                                   start_metrics_server)
from fengshen_tpu.observability.flightrecorder import (FlightRecorder,
                                                       get_flight_recorder)
from fengshen_tpu.observability.flops import (NOMINAL_FALLBACK_FLOPS,
                                              PEAK_FLOPS,
                                              estimate_flops_per_token,
                                              peak_flops_per_chip)
from fengshen_tpu.observability.registry import (Counter, Gauge, Histogram,
                                                 MetricsRegistry,
                                                 get_registry, percentile)
from fengshen_tpu.observability.sink import JsonlSink
from fengshen_tpu.observability.stepstats import StepStats
from fengshen_tpu.observability.timeline import (PHASE_NAMES,
                                                 RequestTimeline)
from fengshen_tpu.observability.tracectx import (SpanLedger,
                                                 TraceContext, TraceIds,
                                                 assemble_trace,
                                                 parse_traceparent)
from fengshen_tpu.observability.tracing import (current_span_stack, span)

__all__ = [
    "BUILD_INFO_METRIC", "CONTENT_TYPE_LATEST", "Counter",
    "FlightRecorder", "Gauge", "Histogram", "JsonlSink",
    "MetricsRegistry", "MetricsServer", "NOMINAL_FALLBACK_FLOPS",
    "PEAK_FLOPS", "PHASE_NAMES", "RequestTimeline", "SpanLedger",
    "StepStats", "TraceContext", "TraceIds", "WARMUP_METRIC",
    "assemble_trace", "current_span_stack", "estimate_flops_per_token",
    "get_flight_recorder", "get_registry", "parse_traceparent",
    "peak_flops_per_chip", "percentile", "record_build_info",
    "record_warmup_seconds", "render_prometheus", "span",
    "start_metrics_server",
]
