"""Prometheus text exposition + the stdlib exporter thread.

`render_prometheus(*registries)` produces the text format
(version 0.0.4) any Prometheus scraper ingests; output is
byte-deterministic (names, label sets, and buckets all iterate sorted —
pinned by tests/test_observability.py under varying PYTHONHASHSEED).

`start_metrics_server` is the exporter for training jobs: a daemon
ThreadingHTTPServer serving ``GET /metrics`` (and ``/healthz``), gated
so only ``process_index == 0`` of a multihost job binds a socket — one
pod, one scrape target, not N identical ones. The serving REST layer
(`api/main.py`) mounts the same renderer on its own ``/metrics`` route
instead of using this server.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional, Sequence

from fengshen_tpu.observability.registry import (Counter, Gauge, Histogram,
                                                 MetricsRegistry,
                                                 get_registry)

CONTENT_TYPE_LATEST = "text/plain; version=0.0.4; charset=utf-8"


def _fmt(v: float) -> str:
    """Prometheus value formatting: integral values without the
    trailing .0 (so counters read `3`, not `3.0`), floats via repr."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


def _escape_help(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _labelstr(names: Sequence[str], values: Sequence[str],
              extra: Sequence[tuple] = ()) -> str:
    pairs = [f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)]
    pairs += [f'{n}="{_escape_label(v)}"' for n, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


def render_prometheus(*registries: MetricsRegistry) -> str:
    """Text exposition over one or more registries (the api layer
    concatenates the process-global registry with the engine's own).
    Duplicate names across registries render both blocks — callers keep
    namespaces disjoint (`fstpu_serving_*` lives only in the engine
    registry)."""
    if not registries:
        registries = (get_registry(),)
    out: list[str] = []
    for reg in registries:
        for metric in reg.metrics():
            out.append(f"# HELP {metric.name} "
                       f"{_escape_help(metric.help)}")
            out.append(f"# TYPE {metric.name} {metric.kind}")
            for label_values, child in metric.children():
                if isinstance(metric, (Counter, Gauge)):
                    out.append(
                        f"{metric.name}"
                        f"{_labelstr(metric.labelnames, label_values)} "
                        f"{_fmt(child.value)}")
                elif isinstance(metric, Histogram):
                    acc = 0
                    for edge, n in zip(metric.buckets, child.counts):
                        acc += n
                        out.append(
                            f"{metric.name}_bucket"
                            f"{_labelstr(metric.labelnames, label_values, [('le', _fmt(edge))])}"
                            f" {acc}")
                    acc += child.counts[-1]
                    out.append(
                        f"{metric.name}_bucket"
                        f"{_labelstr(metric.labelnames, label_values, [('le', '+Inf')])}"
                        f" {acc}")
                    out.append(
                        f"{metric.name}_sum"
                        f"{_labelstr(metric.labelnames, label_values)} "
                        f"{_fmt(child.sum)}")
                    out.append(
                        f"{metric.name}_count"
                        f"{_labelstr(metric.labelnames, label_values)} "
                        f"{child.count}")
    return "\n".join(out) + "\n" if out else ""


def _process_index() -> int:
    """jax.process_index() when jax is importable and initialised-able;
    0 otherwise (the pure-stdlib caller IS the only process)."""
    try:
        import jax
        return int(jax.process_index())
    except Exception:  # noqa: BLE001 — no jax / no backend = single process
        return 0


class MetricsServer:
    """Daemon-thread stdlib HTTP exporter for ``GET /metrics``."""

    def __init__(self, host: str, port: int,
                 registries: Sequence[MetricsRegistry],
                 refresh: Optional[Callable[[], None]] = None):
        import http.server

        regs = tuple(registries)

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                if self.path == "/metrics":
                    if refresh is not None:
                        refresh()
                    body = render_prometheus(*regs).encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     CONTENT_TYPE_LATEST)
                elif self.path == "/healthz":
                    body = b'{"status": "ok"}'
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                else:
                    body = b"not found"
                    self.send_response(404)
                    self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = http.server.ThreadingHTTPServer((host, port),
                                                      Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="fstpu-metrics-exporter")
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def start_metrics_server(
        port: int, host: str = "0.0.0.0",
        registries: Optional[Sequence[MetricsRegistry]] = None,
        refresh: Optional[Callable[[], None]] = None,
        only_process_zero: bool = True) -> Optional[MetricsServer]:
    """Start the exporter thread; returns None (no socket bound) on
    non-zero process indices of a multihost job unless
    ``only_process_zero=False``. ``port=0`` picks a free port
    (``server.port`` has the real one); ``refresh`` runs before each
    scrape (e.g. the engine's gauge refresh)."""
    if only_process_zero and _process_index() != 0:
        return None
    return MetricsServer(host, port, registries or (get_registry(),),
                         refresh=refresh)
