"""Flight recorder: a bounded in-memory ring of recent structured
events + periodic metric snapshots, dumped as a deterministic
post-mortem bundle when something dies.

Five BENCH rounds ended with nothing but a two-line stderr tail
(`parsed: null`, "accelerator unresponsive") because the only telemetry
was aggregate and remote. The recorder keeps the LAST WINDOW of what
the process was doing — sink events (the serving engine's admit/finish
stream, the Trainer's step entries, bench rows), rate-limited metric
snapshots, and whatever each attached provider can still report — in
host memory, and writes it all out on:

- an engine tick error (`serving.engine._serve_loop` wires it),
- a step-guard rewind (`Trainer._rewind` wires it),
- the bench watchdog's abort path (`bench.py` wires it),
- SIGTERM (`install_sigterm`, chained — never replacing — the previous
  handler, the resilience convention),
- demand (`POST /debug/dump` on both API paths).

Bundle layout (everything json, `sort_keys=True`, provider names and
dump sequence numbers instead of wall-clock in filenames — the
determinism test pins byte-identical bundles across PYTHONHASHSEED):

    <dump_dir>/dump-<seq>-<reason>/
        manifest.json     reason, extra, file list, provider errors
        events.jsonl      the ring, oldest first, t_s relative to start
        <provider>.json   one file per attached provider (the engine
                          contributes stats + config + the last-N
                          request timelines; the trainer its step/args)

A dump can never fail its trigger: provider exceptions are recorded in
the manifest instead of raised, and every caller guards the dump call
itself. Pure stdlib; the clock is injectable for deterministic tests.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, Iterable, Optional

#: default ring capacity (events); at serving rates this is minutes of
#: lifecycle events, at trainer rates many log windows
DEFAULT_CAPACITY = 512

#: default minimum seconds between two recorded metric snapshots
DEFAULT_SNAPSHOT_INTERVAL_S = 10.0


class FlightRecorder:
    """Bounded event ring + provider registry + post-mortem dumps."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 dump_dir: str = "fstpu_dumps",
                 clock: Callable[[], float] = time.monotonic,
                 snapshot_interval_s: float = DEFAULT_SNAPSHOT_INTERVAL_S):
        self.capacity = int(capacity)
        self.dump_dir = dump_dir
        self.snapshot_interval_s = float(snapshot_interval_s)
        self._clock = clock
        self._t0 = clock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._providers: Dict[str, Callable[[], dict]] = {}
        self._dump_seq = 0
        self._last_snapshot: Optional[float] = None

    # -- feed ---------------------------------------------------------
    def record(self, entry: dict) -> None:
        """Append one structured event to the ring (thread-safe)."""
        stamped = {"t_s": round(self._clock() - self._t0, 6), **entry}
        with self._lock:
            self._ring.append(stamped)

    def wrap_sink(self, sink: Optional[Callable[[dict], None]] = None
                  ) -> Callable[[dict], None]:
        """A sink callable that records into the ring, then forwards to
        `sink` — drop-in for any `log=`/`JsonlSink` slot."""
        def recording_sink(entry: dict) -> None:
            self.record(entry)
            if sink is not None:
                sink(entry)
        return recording_sink

    def snapshot_metrics(self, registries: Iterable, *,
                         force: bool = False) -> bool:
        """Record a compact {metric: value} snapshot of `registries`
        into the ring, rate-limited to one per `snapshot_interval_s`
        unless `force`. Counters/gauges store their value; histograms
        their (count, sum). Returns whether a snapshot was recorded."""
        now = self._clock()
        with self._lock:
            if not force and self._last_snapshot is not None and \
                    now - self._last_snapshot < self.snapshot_interval_s:
                return False
            self._last_snapshot = now
        snap: Dict[str, object] = {}
        for registry in registries:
            for metric in registry.metrics():
                for values, child in metric.children():
                    key = metric.name if not values else \
                        metric.name + "{" + ",".join(values) + "}"
                    if hasattr(child, "value"):
                        snap[key] = child.value
                    else:   # histogram child
                        snap[key] = {"count": child.count,
                                     "sum": round(child.sum, 6)}
        self.record({"event": "metrics_snapshot", "metrics": snap})
        return True

    # -- providers ----------------------------------------------------
    def attach(self, name: str, provider: Callable[[], dict]) -> None:
        """Register `provider` (a zero-arg callable returning a JSON-able
        dict) to contribute `<name>.json` to every future dump; an
        existing provider under the same name is replaced."""
        with self._lock:
            self._providers[name] = provider

    def detach(self, name: str) -> None:
        with self._lock:
            self._providers.pop(name, None)

    # -- dump ---------------------------------------------------------
    def dump(self, reason: str, extra: Optional[dict] = None,
             dump_dir: Optional[str] = None) -> str:
        """Write the post-mortem bundle; returns its directory path.
        Provider failures land in the manifest, never raise."""
        safe = "".join(c if c.isalnum() or c in "-_" else "-"
                       for c in reason) or "dump"
        root = dump_dir or self.dump_dir
        with self._lock:
            ring = list(self._ring)
            providers = dict(self._providers)
            # skip past bundles an EARLIER process left behind: a
            # crash-restart-crash loop must keep every post-mortem,
            # not overwrite dump-0000-<reason> each time
            while True:
                bundle = os.path.join(
                    root, f"dump-{self._dump_seq:04d}-{safe}")
                self._dump_seq += 1
                if not os.path.isdir(bundle):
                    break
        os.makedirs(bundle, exist_ok=True)
        with open(os.path.join(bundle, "events.jsonl"), "w") as f:
            for entry in ring:
                f.write(json.dumps(entry, sort_keys=True, default=str)
                        + "\n")
        files = ["events.jsonl"]
        errors: Dict[str, str] = {}
        for name in sorted(providers):
            try:
                payload = providers[name]()
            except Exception as e:  # noqa: BLE001 — a post-mortem dump
                # must capture what it can and never fail its trigger
                errors[name] = f"{type(e).__name__}: {str(e)[:200]}"
                continue
            fname = f"{name}.json"
            with open(os.path.join(bundle, fname), "w") as f:
                json.dump(payload, f, sort_keys=True, indent=1,
                          default=str)
            files.append(fname)
        manifest = {
            "schema": 1,
            "reason": reason,
            "extra": extra or {},
            "events": len(ring),
            "files": sorted(files),
            "provider_errors": errors,
        }
        with open(os.path.join(bundle, "manifest.json"), "w") as f:
            json.dump(manifest, f, sort_keys=True, indent=1, default=str)
        return bundle

    # -- signal wiring ------------------------------------------------
    def install_sigterm(self) -> bool:
        """Chain a SIGTERM handler that dumps a bundle before delegating
        to the PREVIOUS handler (the resilience convention: outer
        launchers and the Trainer's preemption autosave keep working).
        Returns False off the main thread / where signals are
        unavailable."""
        import signal
        if threading.current_thread() is not threading.main_thread():
            return False
        previous = signal.getsignal(signal.SIGTERM)

        def handler(signum, frame):
            try:
                self.dump(reason="sigterm")
            except Exception:  # noqa: BLE001 — the dump must never
                # block the process's normal termination path
                pass
            if callable(previous):
                previous(signum, frame)
            elif previous != signal.SIG_IGN:
                # SIG_DFL, or None (a handler installed from C that we
                # cannot call OR restore) — re-deliver through the
                # default disposition so the process still TERMINATES:
                # a dump must never turn SIGTERM into a no-op. SIG_IGN
                # alone is honored by doing nothing, matching the
                # previous disposition.
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signum)

        try:
            signal.signal(signal.SIGTERM, handler)
        except (ValueError, OSError):
            return False
        return True


_GLOBAL: Optional[FlightRecorder] = None
_GLOBAL_LOCK = threading.Lock()


def get_flight_recorder() -> FlightRecorder:
    """The process-global recorder (bench rows, ad-hoc embedders); the
    dump directory honors FSTPU_FLIGHT_DIR. Servers and Trainers build
    their OWN recorders so concurrent engines never share a ring."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = FlightRecorder(
                dump_dir=os.environ.get("FSTPU_FLIGHT_DIR",
                                        "fstpu_dumps"))
        return _GLOBAL
