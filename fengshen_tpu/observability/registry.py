"""Process-local metrics registry: counters, gauges, histograms.

The one telemetry core every subsystem plugs into (docs/observability.md):
the Trainer's step stats, the serving engine's `EngineMetrics`, the
resilience counters, and the span timer all record here, and the
Prometheus renderer (`exposition.render_prometheus`) and the `/stats`
JSON adapters read from it. Pure stdlib — importable on a dev laptop,
in CI, and on a TPU host without jax.

Conventions:

- metric names follow Prometheus rules (`fstpu_<subsystem>_<what>[_total]`)
  and are validated at creation;
- `counter()/gauge()/histogram()` are get-or-create: asking twice for the
  same name returns the SAME object (so adapters can be rebuilt over a
  live registry), and asking for the same name with a different type or
  label set raises — a silent second metric would shadow the first in
  the exposition output;
- every iteration (names, label sets, buckets) is sorted, so rendering
  and snapshots are byte-deterministic regardless of PYTHONHASHSEED or
  insertion order;
- mutation methods (`inc`/`dec`/`set`/`observe`) are host-side only.
  Calling them from jit-traced code records at TRACE time, once, not at
  run time — the `metrics-in-traced-code` fslint rule flags exactly
  this (docs/static_analysis.md).
"""

from __future__ import annotations

import math
import re
import threading
from collections import deque
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: Prometheus' default histogram buckets (seconds-flavored)
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

#: bounded sample window kept per histogram child for percentile queries
DEFAULT_WINDOW = 512


def percentile(values: Iterable[float], q: float) -> float:
    """THE percentile implementation (sorted nearest-rank-below).

    Exactly the semantics the serving `/stats` payload shipped with in
    PR 3 (`idx = min(int(q·n), n-1)` over the sorted window), now the
    single copy in the codebase: `Histogram.percentile` and every
    adapter call through here. Returns 0.0 for an empty input.
    """
    vals = sorted(values)
    if not vals:
        return 0.0
    idx = min(int(q * len(vals)), len(vals) - 1)
    return float(vals[idx])


class _Child:
    """One (metric, label-values) time series."""

    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0.0


class _CounterChild(_Child):
    def inc(self, n: float = 1) -> None:
        if n < 0:
            raise ValueError(f"counters only go up (inc({n}))")
        with self._lock:
            self.value += n


class _GaugeChild(_Child):
    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def inc(self, n: float = 1) -> None:
        with self._lock:
            self.value += n

    def dec(self, n: float = 1) -> None:
        with self._lock:
            self.value -= n


class _HistogramChild:
    __slots__ = ("_lock", "buckets", "counts", "sum", "count", "window")

    def __init__(self, lock: threading.Lock, buckets: Tuple[float, ...],
                 window: int):
        self._lock = lock
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)   # +1 for +Inf
        self.sum = 0.0
        self.count = 0
        self.window = deque(maxlen=window)

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.sum += v
            self.count += 1
            self.window.append(v)
            for i, edge in enumerate(self.buckets):
                if v <= edge:
                    self.counts[i] += 1
                    break
            else:
                self.counts[-1] += 1

    # -- window queries (the /stats percentile surface) ---------------
    def window_values(self) -> List[float]:
        with self._lock:
            return list(self.window)

    def percentile(self, q: float) -> float:
        return percentile(self.window_values(), q)

    def window_avg(self) -> float:
        vals = self.window_values()
        return sum(vals) / len(vals) if vals else 0.0


class Metric:
    """Base: a named family of children keyed by label values.

    Unlabelled metrics have exactly one child (label key ``()``) and
    proxy the mutators directly; labelled ones hand out children via
    ``labels(...)``.
    """

    kind = "untyped"

    def __init__(self, name: str, help: str,
                 labelnames: Sequence[str] = ()):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        if not self.labelnames:
            self._children[()] = self._make_child()

    def _make_child(self):
        raise NotImplementedError

    def labels(self, *values) -> object:
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} takes {len(self.labelnames)} label "
                f"value(s) {self.labelnames}, got {len(values)}")
        key = tuple(str(v) for v in values)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make_child()
            return child

    def _only_child(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} is labelled {self.labelnames}; call "
                ".labels(...) first")
        return self._children[()]

    def children(self) -> List[Tuple[Tuple[str, ...], object]]:
        """(label values, child) pairs, sorted for determinism."""
        with self._lock:
            return sorted(self._children.items())

    def signature(self) -> Tuple[str, Tuple[str, ...]]:
        return (self.kind, self.labelnames)


class Counter(Metric):
    kind = "counter"

    def _make_child(self):
        return _CounterChild(self._lock)

    def inc(self, n: float = 1) -> None:
        self._only_child().inc(n)

    def value(self) -> float:
        return self._only_child().value


class Gauge(Metric):
    kind = "gauge"

    def _make_child(self):
        return _GaugeChild(self._lock)

    def set(self, v: float) -> None:
        self._only_child().set(v)

    def inc(self, n: float = 1) -> None:
        self._only_child().inc(n)

    def dec(self, n: float = 1) -> None:
        self._only_child().dec(n)

    def value(self) -> float:
        return self._only_child().value


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name: str, help: str,
                 labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS,
                 window: int = DEFAULT_WINDOW):
        b = tuple(sorted(float(x) for x in buckets))
        if not b or any(not math.isfinite(x) for x in b):
            raise ValueError(f"bad histogram buckets {buckets!r}")
        self.buckets = b
        self.window_size = int(window)
        super().__init__(name, help, labelnames)

    def _make_child(self):
        return _HistogramChild(self._lock, self.buckets, self.window_size)

    def observe(self, v: float) -> None:
        self._only_child().observe(v)

    def percentile(self, q: float) -> float:
        return self._only_child().percentile(q)

    def window_values(self) -> List[float]:
        return self._only_child().window_values()

    def window_avg(self) -> float:
        return self._only_child().window_avg()

    def signature(self):
        return (self.kind, self.labelnames, self.buckets,
                self.window_size)


class MetricsRegistry:
    """Get-or-create home for a process's (or one engine's) metrics."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Metric] = {}

    def _get_or_create(self, cls, name: str, help: str, **kw) -> Metric:
        candidate = cls(name, help, **kw)
        with self._lock:
            existing = self._metrics.get(name)
            if existing is None:
                self._metrics[name] = candidate
                return candidate
            if existing.signature() != candidate.signature():
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{existing.signature()}, asked for "
                    f"{candidate.signature()}")
            return existing

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help,
                                   labelnames=labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help,
                                   labelnames=labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS,
                  window: int = DEFAULT_WINDOW) -> Histogram:
        return self._get_or_create(Histogram, name, help,
                                   labelnames=labelnames,
                                   buckets=buckets, window=window)

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> List[Metric]:
        """All metrics, sorted by name (deterministic exposition)."""
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]


#: the process-global registry (trainer stats, span timer, HTTP counters);
#: per-engine registries exist alongside it so concurrent engines never
#: cross-contaminate their `/stats` counts
_GLOBAL = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _GLOBAL
