"""JsonlSink: the one structured-event writer.

Subsumes the event dialects that grew per-subsystem — the Trainer's
``metrics.jsonl`` step/val/rewind entries, the resilience loader's
``loader_retry``/``loader_skip_batch`` events, the serving engine's
``serving_admit``/``serving_finish`` events, and bench's one-line JSON
rows — behind a single callable ``sink(entry: dict)``. Event NAMES are
unchanged (compatibility layer: anything already parsing metrics.jsonl
or bench stdout keeps working); what unifies is the writer: one
process-gating rule, one echo format, one logger bridge.

A sink writes to a jsonl ``path``, a ``stream`` (bench writes stdout),
or both; ``echo`` mirrors the Trainer's human-readable console line;
``logger`` bridges numeric fields to a Lightning-style
``log_metrics``. Multihost gating: only ``process_index == 0`` writes
(``only_process_zero=False`` opts out — bench children are already
single-process).

``max_bytes`` caps the jsonl file for long-running serve processes:
when the next line would push past the cap, the file rotates
``path -> path.1 -> ... -> path.<backups>`` (oldest dropped). Rotation
only renames files — the event names and the line format stay
byte-identical, so anything tailing the jsonl keeps parsing. A sink is
shared by concurrent writers (the serving engine's scheduler thread,
HTTP handler threads, the fleet router's poll sweep), so the
rotate-then-append step runs under a lock: without it two threads
racing a rotation boundary can interleave half-written lines or lose a
freshly rotated file's first entries.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Optional, TextIO


def _process_index() -> int:
    try:
        import jax
        return int(jax.process_index())
    except Exception:  # noqa: BLE001 — no jax = single process
        return 0


class JsonlSink:
    """Callable structured-event sink: ``sink({"event": ..., ...})``."""

    def __init__(self, path: Optional[str] = None,
                 stream: Optional[TextIO] = None,
                 echo: bool = False,
                 echo_prefix: str = "[fengshen-tpu] ",
                 logger: Optional[Any] = None,
                 only_process_zero: bool = True,
                 max_bytes: Optional[int] = None,
                 backups: int = 1):
        self.path = path
        self.stream = stream
        self.echo = echo
        self.echo_prefix = echo_prefix
        self.logger = logger
        self.only_process_zero = only_process_zero
        self.max_bytes = max_bytes
        self.backups = max(int(backups), 1)
        # one writer at a time: rotation is a multi-step rename chain
        # and concurrent callers must not interleave inside it
        self._lock = threading.Lock()

    def _maybe_rotate(self, incoming: int) -> None:
        """Size-based rotation (opt-in via ``max_bytes``): shift the
        backup chain so the active file always has room for the next
        line; renames only, content untouched."""
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return      # no file yet — nothing to rotate
        if size + incoming <= self.max_bytes:
            return
        for i in range(self.backups, 0, -1):
            src = self.path if i == 1 else f"{self.path}.{i - 1}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i}")

    @staticmethod
    def format_echo(entry: dict) -> str:
        """The Trainer's console line format (floats at .4g)."""
        return " ".join(
            f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}"
            for k, v in entry.items())

    def __call__(self, entry: dict) -> None:
        if self.only_process_zero and _process_index() != 0:
            return
        line = json.dumps(entry)
        if self.path is not None:
            with self._lock:
                parent = os.path.dirname(self.path)
                if parent:
                    os.makedirs(parent, exist_ok=True)
                if self.max_bytes is not None:
                    self._maybe_rotate(len(line) + 1)
                with open(self.path, "a") as f:
                    f.write(line + "\n")
        if self.stream is not None:
            # same lock as the file path: shared streams get the same
            # no-interleaved-lines guarantee the rotation test pins
            with self._lock:
                self.stream.write(line + "\n")
                self.stream.flush()
        if self.echo:
            print(f"{self.echo_prefix}{self.format_echo(entry)}",
                  flush=True)
        if self.logger is not None and hasattr(self.logger,
                                               "log_metrics"):
            self.logger.log_metrics(
                {k: v for k, v in entry.items()
                 if isinstance(v, (int, float))},
                step=entry.get("step"))
