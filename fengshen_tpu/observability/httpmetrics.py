"""The shared HTTP request telemetry families.

Both server surfaces — the replica api servers (`api/main.py`, fastapi
AND stdlib paths) and the fleet router's own endpoints
(`fleet/server.py`) — count and time their requests into the SAME
global-registry families, so router-side and replica-side latency read
on one dashboard. The family definitions (name, help, labelnames) live
here ONCE: the registry's get-or-create matches on the full signature,
so two hand-kept string copies drifting apart would split the family
at runtime. Pure stdlib (the fleet package imports no jax).
"""

from __future__ import annotations

from fengshen_tpu.observability.registry import get_registry


def http_requests_total():
    """`fstpu_http_requests_total{route,code}` counter family."""
    return get_registry().counter(
        "fstpu_http_requests_total",
        "REST requests by route and status",
        labelnames=("route", "code"))


def http_request_seconds():
    """`fstpu_http_request_seconds{route}` latency histogram family."""
    return get_registry().histogram(
        "fstpu_http_request_seconds",
        "REST request wall seconds by route",
        labelnames=("route",))
