"""Model-FLOPs estimator + per-chip peak table: the MFU denominator.

MFU (model-FLOPs-utilization, the PaLM accounting) = achieved model
FLOP/s over peak hardware FLOP/s:

    mfu = tokens_per_sec * flops_per_token / (peak_per_chip * n_chips)

``estimate_flops_per_token`` counts the PARAMETER matmul FLOPs of one
token through a dense decoder (2 FLOPs per multiply-add, x3 for
forward+backward), from the model config alone:

    per_layer = 2*h*h (q+o) + 2*h*(kv_heads*head_dim) (k+v, GQA-aware)
              + 3*h*inter (gate/up/down)
    per_token = mult * (layers * per_layer + h * vocab)   # mult: 6 train, 2 infer

Assumptions (documented in docs/observability.md): attention
score/value FLOPs (the O(seq) term) are excluded, as are norms,
embeddings-as-lookup, and activation functions — the standard "6N"
family of approximations, exact enough that MFU deltas track real
optimization work. For a non-GQA model this reduces to the familiar
``6*(l*(4h^2 + 3*h*inter) + h*v)``.

Peak FLOP/s comes from the TPU table below (bf16), the
``FSTPU_PEAK_FLOPS`` env override (benchmarking on an unlisted chip),
or a nominal CPU figure — nominal so that MFU stays FINITE and
monotonic in CI/CPU runs; absolute CPU MFU values are indicative only.
"""

from __future__ import annotations

import os
from typing import Any, Optional

#: peak bf16 FLOP/s per chip (the table that lived in trainer.py;
#: trainer re-exports it for compatibility)
PEAK_FLOPS = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}

#: nominal figure for backends not in the table (CPU CI runs): a
#: round 1 TFLOP/s so mfu is finite and comparable run-to-run on the
#: same host, never a hardware claim
NOMINAL_FALLBACK_FLOPS = 1e12

#: env override: FSTPU_PEAK_FLOPS=9.2e14 for an unlisted accelerator
PEAK_FLOPS_ENV = "FSTPU_PEAK_FLOPS"


def peak_flops_per_chip(device_kind: Optional[str] = None) -> float:
    """Peak FLOP/s for one chip of ``device_kind`` (default: the first
    visible jax device). Resolution order: env override, TPU table,
    nominal fallback. Always positive and finite."""
    env = os.environ.get(PEAK_FLOPS_ENV)
    if env:
        peak = float(env)
        if peak <= 0:
            raise ValueError(f"{PEAK_FLOPS_ENV}={env!r} must be > 0")
        return peak
    if device_kind is None:
        try:
            import jax
            device_kind = jax.devices()[0].device_kind
        except Exception:  # noqa: BLE001 — no jax/backend: use fallback
            device_kind = ""
    return PEAK_FLOPS.get(device_kind, NOMINAL_FALLBACK_FLOPS)


def estimate_flops_per_token(config: Any,
                             include_backward: bool = True
                             ) -> Optional[float]:
    """FLOPs one token costs through the model described by ``config``
    (6x params-touched for training, 2x for inference). Returns None
    when the config doesn't describe a dense decoder LM (no
    hidden_size/num_hidden_layers) — callers treat None as "estimator
    doesn't support this model" and omit mfu."""
    h = getattr(config, "hidden_size", None)
    layers = getattr(config, "num_hidden_layers", None)
    if not h or not layers:
        return None
    inter = getattr(config, "intermediate_size", None) or 4 * h
    vocab = getattr(config, "vocab_size", 0) or 0
    heads = getattr(config, "num_attention_heads", None) or 1
    kv_heads = getattr(config, "num_key_value_heads", None) or heads
    head_dim = h // heads
    per_layer = (2 * h * h                       # q_proj + o_proj
                 + 2 * h * (kv_heads * head_dim)  # k_proj + v_proj (GQA)
                 + 3 * h * inter)                # gate + up + down
    mult = 6.0 if include_backward else 2.0
    return mult * (layers * per_layer + h * vocab)
