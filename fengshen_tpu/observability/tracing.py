"""Trace spans: named timed sections, profiler-integrated when possible.

``span("serving/decode")`` wraps a host-side section. Inside it:

- when ``jax.profiler`` is importable, the section is annotated with
  ``TraceAnnotation`` so it shows up named on the TensorBoard trace
  the Trainer's ``--profile_steps`` captures;
- always, the wall time is recorded into the
  ``fstpu_span_seconds{span=...}`` histogram of the target registry —
  so `/metrics` carries p50/p95 section timings even where no profiler
  run is active.

Spans nest: the recorded label is the "/"-joined stack ("fit/step"
inside ``span("fit")`` + ``span("step")``), kept per-thread so the
serving engine thread and the main thread never interleave stacks.

The profiler hook degrades to timing-only when jax (or jax.profiler) is
missing or broken — the registry side is pure stdlib.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Optional

from fengshen_tpu.observability.registry import (MetricsRegistry,
                                                 get_registry)

SPAN_METRIC = "fstpu_span_seconds"

#: sentinel: profiler integration not yet resolved. Tests (and callers
#: that want timing-only spans) may set this to None to force the
#: fallback; set it back to _UNRESOLVED to re-probe.
_UNRESOLVED = object()
_TRACE_ANNOTATION = _UNRESOLVED

_local = threading.local()


def _trace_annotation_cls():
    global _TRACE_ANNOTATION
    if _TRACE_ANNOTATION is _UNRESOLVED:
        try:
            from jax.profiler import TraceAnnotation
            _TRACE_ANNOTATION = TraceAnnotation
        except Exception:  # noqa: BLE001 — no jax: timing-only spans
            _TRACE_ANNOTATION = None
    return _TRACE_ANNOTATION


def current_span_stack() -> tuple:
    """The calling thread's open spans, outermost first."""
    return tuple(getattr(_local, "stack", ()))


@contextmanager
def span(name: str, registry: Optional[MetricsRegistry] = None):
    """Time a section; annotate the profiler trace when available."""
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    stack.append(name)
    label = "/".join(stack)
    cls = _trace_annotation_cls()
    annotation = None
    if cls is not None:
        try:
            annotation = cls(label)
            annotation.__enter__()
        except Exception:  # noqa: BLE001 — profiler refused: time anyway
            annotation = None
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        if annotation is not None:
            try:
                annotation.__exit__(None, None, None)
            except Exception:  # noqa: BLE001 — never mask the body's error
                pass
        stack.pop()
        reg = registry if registry is not None else get_registry()
        reg.histogram(
            SPAN_METRIC,
            "wall seconds spent inside span(), labelled by the nested "
            "span path", labelnames=("span",),
        ).labels(label).observe(dt)
