"""Bench trajectory comparator: diff BENCH_r*.json rounds, flag
regressions, emit a deterministic verdict.

    python -m fengshen_tpu.observability.benchdiff [--dir .]
        [--threshold 0.15] [--json] [--strict] [--baseline FILE]
    make benchdiff

Every bench round lands as `BENCH_r<NN>.json`:

    {"n": 3, "cmd": "...", "rc": 1, "tail": "<stderr tail>",
     "parsed": null | {...row...} | [{...}, ...]}

where each parsed row is the one-line BENCH schema bench.py /
serving/bench.py emit ({"metric", "value", "unit", "vs_baseline", and
optionally "mfu", "degraded", ...}). The comparator:

- classifies each round: ``ok`` (rc 0 + parsed rows), ``wedged``
  (the watchdog/relay abort signatures in the stderr tail — the
  r01–r05 trajectory), or ``failed`` (anything else without rows);
- diffs every metric against the MOST RECENT prior round that carried
  it (rounds often rotate BENCH_CONFIG, so "previous round" is per
  metric, not per file), and against `BASELINE.json`'s ``published``
  table when a metric appears there;
- flags ``regression`` / ``improvement`` when |delta| exceeds
  ``--threshold`` (relative), ``flat`` otherwise, and ``incomparable``
  when exactly one side is a degraded CPU-fallback number (a rescue
  row must never read as a hardware regression), when the two sides
  ran at different memory placements (the ``offload`` +
  ``memory_kind`` row fields, docs/offload.md — an offloaded-update
  row is a different program from a device-resident one), or when two
  fleet rows (docs/fleet.md) carry different ``replicas`` counts — a
  3-replica aggregate must never diff against a 2-replica one;
- prints a deterministic report (sorted rounds, sorted metrics,
  ``sort_keys`` JSON) and an overall verdict: ``REGRESSED`` /
  ``OK`` / ``NO_SIGNAL`` (no parseable rounds at all — five wedges).

Exit codes: 0 on OK/NO_SIGNAL (and on REGRESSED without ``--strict`` —
the Makefile target reports, CI decides), 3 on REGRESSED with
``--strict``, 2 when the directory has no BENCH files. Pure stdlib.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import List, Optional, Tuple

#: stderr signatures of a wedged accelerator relay (bench.py's
#: watchdog + ladder abort messages — see BENCH_r01..r05)
WEDGE_MARKERS = ("accelerator unresponsive", "relay wedged")

_ROUND_RE = re.compile(r"^BENCH_r(\d+)\.json$")

DEFAULT_THRESHOLD = 0.15

VERDICT_REGRESSED = "REGRESSED"
VERDICT_OK = "OK"
VERDICT_NO_SIGNAL = "NO_SIGNAL"


def load_rounds(directory: str) -> List[Tuple[int, str, dict]]:
    """(round number, filename, payload) for every BENCH_r*.json,
    sorted by round number."""
    rounds = []
    for name in sorted(os.listdir(directory)):
        m = _ROUND_RE.match(name)
        if not m:
            continue
        with open(os.path.join(directory, name)) as f:
            rounds.append((int(m.group(1)), name, json.load(f)))
    rounds.sort(key=lambda r: (r[0], r[1]))
    return rounds


def _rows(parsed) -> List[dict]:
    if isinstance(parsed, dict):
        parsed = [parsed]
    if not isinstance(parsed, list):
        return []
    return [r for r in parsed
            if isinstance(r, dict) and "metric" in r and "value" in r]


def classify_round(payload: dict) -> Tuple[str, List[dict]]:
    """('ok'|'wedged'|'failed', parsed rows)."""
    rows = _rows(payload.get("parsed"))
    if int(payload.get("rc", 1)) == 0 and rows:
        return "ok", rows
    tail = payload.get("tail") or ""
    if any(marker in tail for marker in WEDGE_MARKERS):
        return "wedged", rows
    return "failed", rows


def _placement(row: dict) -> str:
    """The memory-placement identity of a BENCH row (docs/offload.md):
    offload ladder level + resolved memory kind. Rows without the
    fields are level "none" (the pre-offload row shape); rows at
    different placements measure different programs and must never be
    compared."""
    level = str(row.get("offload") or "none")
    kind = str(row.get("memory_kind") or "")
    return f"{level}:{kind}" if level != "none" else "none"


def _identity(row: dict) -> str:
    """The full comparison identity of a BENCH row: memory placement
    plus — for fleet rows (docs/fleet.md) — the replica count, plus —
    for disaggregated rows (docs/disaggregation.md) — the phase
    topology. Two fleet rounds at different N measure different
    deployments exactly like two offload rounds at different
    placements measure different programs, and a
    ``prefill=1,decode=2`` topology is a different deployment from a
    ``homogeneous`` 3-replica one even at equal N; all of them diff as
    ``incomparable``, never regression/flat. Fault-drill rows
    (docs/fault_tolerance.md) carry a ``drill`` key for the same
    reason: a preemption round must never be compared against an
    undisturbed one. Kernel-bench rows (docs/kernels.md) carry a
    ``kernel`` key with the dispatch decision (``pallas`` | ``xla``):
    a Mosaic-kernel round and a stock-lowering round measure different
    programs, so they too diff as incomparable. Multimodal rows
    (docs/serving.md "Multimodal engines") carry an ``engine_type``
    key (``batch_image`` | ``embedding`` | ``continuous``): a
    diffusion-serving round and a text-serving round share metric
    names but measure different engines entirely."""
    parts = [_placement(row)]
    if "replicas" in row:
        parts.append(f"replicas={int(row['replicas'])}")
    if "topology" in row:
        parts.append(f"topology={row['topology']}")
    if "drill" in row:
        parts.append(f"drill={row['drill']}")
    if "kernel" in row:
        parts.append(f"kernel={row['kernel']}")
    if "engine_type" in row:
        parts.append(f"engine_type={row['engine_type']}")
    # streaming rows (docs/streaming.md): a token-by-token SSE round
    # measures a different delivery path than a batch round, and a
    # self_draft round runs a different decode program than a
    # prompt_lookup one — both keys join the identity so they only
    # ever diff against their own kind
    if "stream" in row:
        parts.append(f"stream={bool(row['stream'])}")
    if "spec_mode" in row:
        parts.append(f"spec_mode={row['spec_mode']}")
    return "|".join(parts)


def _compare(metric: str, round_n: int, value: float, degraded: bool,
             placement: str, prev_round, prev_value: float,
             prev_degraded: bool, prev_placement: str,
             threshold: float) -> dict:
    comparison = {
        "metric": metric,
        "round": round_n,
        "prev_round": prev_round,
        "value": value,
        "prev_value": prev_value,
    }
    if degraded != prev_degraded or placement != prev_placement:
        comparison.update(status="incomparable", delta_pct=None)
        return comparison
    if prev_value == 0:
        # no relative delta exists; any move off zero is a real change
        # (all BENCH metrics are higher-is-better), never "flat +0%"
        if value == 0:
            comparison.update(status="flat", delta_pct=0.0)
        else:
            comparison.update(
                status="improvement" if value > 0 else "regression",
                delta_pct=None)
        return comparison
    delta = (value - prev_value) / prev_value
    if delta < -threshold:
        status = "regression"
    elif delta > threshold:
        status = "improvement"
    else:
        status = "flat"
    comparison.update(status=status, delta_pct=round(delta * 100.0, 2))
    return comparison


def diff_rounds(rounds: List[Tuple[int, str, dict]],
                baseline: Optional[dict] = None,
                threshold: float = DEFAULT_THRESHOLD) -> dict:
    """The full trajectory report (deterministic: rounds ascend,
    metrics sort lexically, all floats rounded)."""
    published = dict((baseline or {}).get("published") or {})
    report_rounds = []
    comparisons = []
    # metric -> (round, value, degraded): "previous" is per metric
    last_seen: dict = {}
    for round_n, fname, payload in rounds:
        status, rows = classify_round(payload)
        entry = {
            "round": round_n,
            "file": fname,
            "status": status,
            "metrics": {r["metric"]: r["value"]
                        for r in sorted(rows,
                                        key=lambda r: r["metric"])},
        }
        if status != "ok":
            tail = (payload.get("tail") or "").strip()
            entry["detail"] = tail.splitlines()[-1][:160] if tail else ""
        report_rounds.append(entry)
        for row in sorted(rows, key=lambda r: r["metric"]):
            metric = str(row["metric"])
            value = float(row["value"])
            degraded = bool(row.get("degraded"))
            placement = _identity(row)
            prev = last_seen.get(metric)
            if prev is not None:
                comparisons.append(_compare(
                    metric, round_n, value, degraded, placement,
                    *prev, threshold))
            elif metric in published and not degraded:
                # published baselines predate the placement fields:
                # they are level-"none" hardware rows
                comparisons.append(_compare(
                    metric, round_n, value, degraded, placement,
                    "baseline", float(published[metric]), False,
                    "none", threshold))
            last_seen[metric] = (round_n, value, degraded, placement)
    counts = {s: sum(1 for r in report_rounds if r["status"] == s)
              for s in ("ok", "wedged", "failed")}
    regressions = [c for c in comparisons if c["status"] == "regression"]
    if regressions:
        verdict = VERDICT_REGRESSED
    elif counts["ok"]:
        verdict = VERDICT_OK
    else:
        verdict = VERDICT_NO_SIGNAL
    return {
        "schema": 1,
        "threshold": threshold,
        "rounds": report_rounds,
        "comparisons": comparisons,
        "counts": counts,
        "regressions": len(regressions),
        "verdict": verdict,
    }


def render(report: dict) -> str:
    """Human-readable, line-per-fact, deterministic."""
    counts = report["counts"]
    lines = [
        f"benchdiff: rounds={len(report['rounds'])} ok={counts['ok']} "
        f"wedged={counts['wedged']} failed={counts['failed']} "
        f"comparisons={len(report['comparisons'])} "
        f"regressions={report['regressions']} "
        f"threshold={report['threshold']:g}"]
    for entry in report["rounds"]:
        head = f"r{entry['round']:02d} {entry['status'].upper()}"
        if entry["metrics"]:
            body = " ".join(f"{m}={v:g}"
                            for m, v in sorted(entry["metrics"].items()))
        else:
            body = entry.get("detail", "")
        lines.append(f"{head} {body}".rstrip())
    for c in report["comparisons"]:
        prev = c["prev_round"]
        prev_label = prev if prev == "baseline" else f"r{prev:02d}"
        delta = ("n/a" if c["delta_pct"] is None
                 else f"{c['delta_pct']:+g}%")
        lines.append(
            f"r{c['round']:02d} {c['metric']}: {c['prev_value']:g} -> "
            f"{c['value']:g} ({delta}) vs {prev_label} "
            f"{c['status'].upper()}")
    lines.append(f"verdict: {report['verdict']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m fengshen_tpu.observability.benchdiff",
        description="diff BENCH_r*.json rounds and flag regressions")
    parser.add_argument("--dir", default=".",
                        help="directory holding BENCH_r*.json")
    parser.add_argument("--threshold", default=DEFAULT_THRESHOLD,
                        type=float,
                        help="relative change flagged as regression/"
                             "improvement (default 0.15)")
    parser.add_argument("--baseline", default=None,
                        help="BASELINE.json path (default: "
                             "<dir>/BASELINE.json when present)")
    parser.add_argument("--json", action="store_true",
                        help="emit the report as sorted JSON")
    parser.add_argument("--strict", action="store_true",
                        help="exit 3 on a REGRESSED verdict")
    args = parser.parse_args(argv)

    rounds = load_rounds(args.dir)
    if not rounds:
        print(f"benchdiff: no BENCH_r*.json under {args.dir}",
              file=sys.stderr)
        return 2
    baseline = None
    baseline_path = args.baseline or os.path.join(args.dir,
                                                  "BASELINE.json")
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            baseline = json.load(f)
    report = diff_rounds(rounds, baseline=baseline,
                         threshold=args.threshold)
    if args.json:
        print(json.dumps(report, sort_keys=True, indent=1))
    else:
        print(render(report))
    if args.strict and report["verdict"] == VERDICT_REGRESSED:
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
