"""Distributed trace context: W3C-traceparent-style ids, a per-process
span ledger, and cross-process trace assembly (docs/observability.md
"Distributed tracing").

PR 8 gave each replica an exact per-request waterfall and PR 10 put a
retrying router in front of N replicas — so one user-visible latency
now spans processes, and no single timeline explains it: a retried
request's story lives half in the router (placement, backoff, the
failed attempt) and half in two replicas (the wedged execution, the
successful one). This module is the correlation layer:

- **ids**: `TraceIds` mints 128-bit trace ids and 64-bit span ids in
  lowercase hex, W3C trace-context shaped. Seedable (`TraceIds(seed)`)
  so tests get deterministic id streams; unseeded instances draw from
  OS entropy via `random.Random()`. `TraceContext.to_traceparent()` /
  `parse_traceparent()` round-trip the `00-<trace>-<span>-01` header
  form that crosses process boundaries (as an HTTP header AND a JSON
  body field — proxies that strip unknown headers don't break the
  chain).

- **ledger**: `SpanLedger` is a bounded host-side record of spans per
  trace. Every span stores its start on the process's MONOTONIC clock
  (relative to the trace's first span) and the trace stores one
  `epoch_unix_s` wall-clock anchor taken at trace start — that pair is
  what lets `assemble` place two processes' monotonic timelines on one
  axis while REPORTING the residual clock skew instead of hiding it.
  All bookkeeping is plain-dict host work on the caller's thread
  (router / scheduler threads only — never traced code; the
  `trace_context_clean.py` fslint fixture pins this idiom).

- **assembly**: `assemble_trace` stitches a router-side ledger trace
  with the involved replicas' `/debug/requests/<id>` waterfalls into
  ONE cross-process JSON document. Per-replica attachments carry
  `offset_in_trace_s` (the replica's wall anchor minus the router's)
  and `clock_skew_s` (that offset minus when the router actually
  dispatched the attempt — network delay plus NTP error; a large value
  means the hosts disagree about time and the waterfall positions are
  only as trustworthy as that number). Unreachable replicas attach an
  `error` entry — a dead process must not make the trace un-renderable.

Everything is pure stdlib and deterministic given injected clocks:
rendering rounds floats to 6 dp and relies on `sort_keys` dumping, so
the `/debug/traces/<id>` payload is byte-identical across
PYTHONHASHSEED (pinned by subprocess test, like `/fleet`).
"""

from __future__ import annotations

import dataclasses
import random
import re
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional

#: the only traceparent version this repo emits
TRACEPARENT_VERSION = "00"

#: traces the ledger retains (oldest evicted); the flight-recorder
#: `traces.json` provider reports at most this many
DEFAULT_MAX_TRACES = 128

#: spans ONE trace record retains: a client may legitimately reuse one
#: traceparent across many requests (one client trace spanning N
#: calls), and joining must not grow a single record without bound —
#: past the cap new spans are dropped and counted (`spans_dropped` in
#: the rendered trace), like the timeline's per-request event cap
DEFAULT_MAX_SPANS_PER_TRACE = 512

_TRACE_ID_RE = re.compile(r"^[0-9a-f]{32}$")
_SPAN_ID_RE = re.compile(r"^[0-9a-f]{16}$")
_VERSION_RE = re.compile(r"^[0-9a-f]{2}$")


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """One hop's identity: WHICH trace, and WHO the parent span is."""

    trace_id: str
    span_id: str

    def to_traceparent(self) -> str:
        """The wire form (W3C trace-context header shape)."""
        return f"{TRACEPARENT_VERSION}-{self.trace_id}-{self.span_id}-01"


def parse_traceparent(value: Any) -> Optional[TraceContext]:
    """Parse a traceparent string; None on anything malformed — an
    unparseable header must degrade to "start a fresh trace", never to
    an error on the request path."""
    if not isinstance(value, str):
        return None
    parts = value.strip().lower().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, _flags = parts
    if version == "ff" or not _VERSION_RE.match(version):
        return None      # ff (and any non-hex) is forbidden by the spec
    if not _TRACE_ID_RE.match(trace_id) or set(trace_id) == {"0"}:
        return None
    if not _SPAN_ID_RE.match(span_id) or set(span_id) == {"0"}:
        return None
    return TraceContext(trace_id=trace_id, span_id=span_id)


class TraceIds:
    """Id mint. Seeded → deterministic stream (tests); unseeded →
    OS-entropy-seeded. All-zero ids are invalid per the W3C spec, so
    the mint maps a zero draw to 1."""

    def __init__(self, seed: Optional[int] = None):
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def trace_id(self) -> str:
        with self._lock:
            v = self._rng.getrandbits(128)
        return f"{v or 1:032x}"

    def span_id(self) -> str:
        with self._lock:
            v = self._rng.getrandbits(64)
        return f"{v or 1:016x}"


class SpanLedger:
    """Bounded per-process span records keyed by trace id.

    One ledger per process role (the router owns one; replicas' request
    timelines already serve the same purpose on their side). Spans are
    host-side dicts appended under a lock — cheap enough for the
    request path, and NEVER called from traced code.
    """

    def __init__(self, service: str,
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time,
                 max_traces: int = DEFAULT_MAX_TRACES,
                 max_spans_per_trace: int = DEFAULT_MAX_SPANS_PER_TRACE,
                 ids: Optional[TraceIds] = None):
        self.service = service
        self._clock = clock
        self._wall = wall
        self._ids = ids if ids is not None else TraceIds()
        self._lock = threading.Lock()
        self.max_traces = int(max_traces)
        self.max_spans_per_trace = int(max_spans_per_trace)
        #: trace_id -> {"epoch_unix_s", "_t0", "parent_span_id",
        #:              "spans": [span dicts w/ internal "_abs" start]}
        self._traces: "OrderedDict[str, dict]" = OrderedDict()

    # -- recording ----------------------------------------------------

    def start_trace(self, name: str, trace_id: Optional[str] = None,
                    parent_span_id: Optional[str] = None,
                    **attrs) -> TraceContext:
        """Open a trace (or join an incoming one when `trace_id` came
        off the wire) with its root span; returns the context whose
        span_id children should parent to. The wall-clock epoch is
        anchored HERE — every later span is monotonic-relative."""
        tid = trace_id or self._ids.trace_id()
        sid = self._ids.span_id()
        now = self._clock()
        with self._lock:
            rec = self._traces.get(tid)
            if rec is None:
                rec = {"epoch_unix_s": round(self._wall(), 6),
                       "_t0": now, "spans": []}
                self._traces[tid] = rec
                while len(self._traces) > self.max_traces:
                    self._traces.popitem(last=False)
            self._append_locked(rec, self._span(rec, sid, name,
                                                parent_span_id, now,
                                                attrs))
        return TraceContext(trace_id=tid, span_id=sid)

    def start_span(self, trace_id: str, name: str,
                   parent_span_id: Optional[str], **attrs
                   ) -> Optional[str]:
        """Open a child span; None when the trace was already evicted
        (recording must degrade, never raise, on the request path)."""
        sid = self._ids.span_id()
        now = self._clock()
        with self._lock:
            rec = self._traces.get(trace_id)
            if rec is None:
                return None
            if not self._append_locked(rec, self._span(
                    rec, sid, name, parent_span_id, now, attrs)):
                return None
        return sid

    def end_span(self, trace_id: str, span_id: Optional[str],
                 **attrs) -> None:
        """Close a span: stamp duration, merge closing attrs (outcome,
        status, backoff...). Unknown trace/span is a no-op."""
        if span_id is None:
            return
        now = self._clock()
        with self._lock:
            rec = self._traces.get(trace_id)
            if rec is None:
                return
            for span in reversed(rec["spans"]):
                if span["span_id"] == span_id:
                    span["duration_s"] = round(now - span["_abs"], 6)
                    span["attrs"].update(self._clean(attrs))
                    return

    def _append_locked(self, rec: dict, span: dict) -> bool:
        """Append under the per-record span cap; a dropped span is
        counted, never an error (recording degrades on the request
        path — `start_span` returning None makes `end_span` a no-op)."""
        if len(rec["spans"]) >= self.max_spans_per_trace:
            rec["dropped"] = rec.get("dropped", 0) + 1
            return False
        rec["spans"].append(span)
        return True

    @staticmethod
    def _span(rec: dict, sid: str, name: str,
              parent_span_id: Optional[str], now: float,
              attrs: dict) -> dict:
        return {"span_id": sid, "parent_span_id": parent_span_id,
                "name": name,
                "t_start_s": round(now - rec["_t0"], 6),
                "duration_s": None, "_abs": now,
                "attrs": SpanLedger._clean(attrs)}

    @staticmethod
    def _clean(attrs: dict) -> dict:
        """JSON-ready attrs: floats rounded so rendering is
        byte-deterministic."""
        return {k: (round(v, 6) if isinstance(v, float) else v)
                for k, v in attrs.items()}

    # -- reading ------------------------------------------------------

    def get_trace(self, trace_id: str) -> Optional[dict]:
        """One trace's JSON-ready record (spans in creation order)."""
        with self._lock:
            rec = self._traces.get(trace_id)
            if rec is None:
                return None
            return self._render_locked(trace_id, rec)

    def recent(self, n: Optional[int] = None) -> List[dict]:
        """The last `n` traces (default: all retained), oldest first."""
        with self._lock:
            ids = list(self._traces)
            if n is not None:
                ids = ids[-int(n):]
            return [self._render_locked(t, self._traces[t])
                    for t in ids]

    def provider(self) -> dict:
        """The flight-recorder `traces.json` payload: post-mortem
        bundles carry the last-N traces this process handled."""
        return {"service": self.service, "traces": self.recent()}

    def _render_locked(self, trace_id: str, rec: dict) -> dict:
        out = {
            "trace_id": trace_id,
            "service": self.service,
            "epoch_unix_s": rec["epoch_unix_s"],
            "spans": [{k: v for k, v in span.items() if k != "_abs"}
                      for span in rec["spans"]],
        }
        if rec.get("dropped"):
            out["spans_dropped"] = rec["dropped"]
        return out


def assemble_trace(router_trace: dict,
                   replica_fetches: Dict[str, dict]) -> dict:
    """Stitch one router ledger trace with the involved replicas'
    per-process waterfalls into ONE cross-process document.

    `replica_fetches` maps replica name to either
    ``{"waterfall": <GET /debug/requests/<id> payload>}`` or
    ``{"error": <why the fetch failed>}`` — the caller (the router)
    owns the HTTP; this function owns the clock math:

    - each process recorded its own monotonic timeline anchored by one
      wall-clock ``epoch_unix_s``;
    - a replica attachment's ``offset_in_trace_s`` places its t=0 on
      the router's axis (replica epoch − router epoch);
    - ``clock_skew_s`` is that offset minus the router-side start of
      the FIRST attempt to that replica: network delay + host clock
      disagreement, reported rather than hidden (a negative value
      means the replica's clock runs behind the router's).

    The per-process phase invariant (queue_wait + prefill + decode ==
    total, PR 8) is preserved untouched: waterfalls are attached
    verbatim, never re-timed.
    """
    spans = router_trace.get("spans", [])
    request_id = None
    for span in spans:
        rid = span.get("attrs", {}).get("request_id")
        if rid is not None:
            request_id = rid
            break
    attempt_start: Dict[str, float] = {}
    for span in spans:
        if span.get("name") != "router/attempt":
            continue
        rep = span.get("attrs", {}).get("replica")
        if rep is not None and rep not in attempt_start:
            attempt_start[rep] = span.get("t_start_s", 0.0)
    epoch = router_trace.get("epoch_unix_s")
    replicas = {}
    for name in sorted(replica_fetches):
        entry = dict(replica_fetches[name])
        waterfall = entry.get("waterfall")
        if isinstance(waterfall, dict):
            rep_epoch = waterfall.get("epoch_unix_s")
            if isinstance(rep_epoch, (int, float)) and \
                    isinstance(epoch, (int, float)):
                offset = float(rep_epoch) - float(epoch)
                entry["offset_in_trace_s"] = round(offset, 6)
                entry["clock_skew_s"] = round(
                    offset - float(attempt_start.get(name, 0.0)), 6)
        replicas[name] = entry
    return {
        "schema": 1,
        "trace_id": router_trace.get("trace_id"),
        "request_id": request_id,
        "router": router_trace,
        "replicas": replicas,
    }
