"""Server-Sent-Events framing for the streaming tier
(docs/streaming.md "SSE contract").

The wire contract both API paths emit and the fleet router's streaming
transport parses back:

- every `token` event carries `id: <token index>` — SSE's own
  `Last-Event-ID` reconnect header therefore names the exact
  resume-from-token-k index, no side channel needed;
- `data:` is always one JSON object on one line (token ids are ints;
  none of our payloads embed newlines), so the parser here stays a
  plain line-splitter;
- the stream ends with exactly one terminal event (`done`,
  `evacuated`, or `timeout`) and the connection closes — clients never
  need to detect EOF mid-event.
"""

from __future__ import annotations

import json
from typing import Iterator, Optional


def format_event(event: str, data: dict,
                 event_id: Optional[int] = None) -> bytes:
    """One SSE frame: optional `id:`, `event:`, one-line JSON `data:`,
    blank-line terminator."""
    lines = []
    if event_id is not None:
        lines.append(f"id: {int(event_id)}")
    lines.append(f"event: {event}")
    lines.append("data: " + json.dumps(data, separators=(",", ":")))
    return ("\n".join(lines) + "\n\n").encode("utf-8")


def iter_sse(fp) -> Iterator[dict]:
    """Parse an SSE byte stream (a file-like yielding lines) into
    `{"event": str, "id": Optional[int], "data": dict}` frames.

    Tolerates the parts of the SSE grammar we never emit (comments,
    multi-`data:` frames get concatenated) so a proxy in the middle
    cannot break the router's reader.
    """
    event, event_id, data_parts = None, None, []
    for raw in fp:
        line = raw.decode("utf-8", "replace") if isinstance(raw, bytes) \
            else raw
        line = line.rstrip("\r\n")
        if line == "":
            if event is not None or data_parts:
                payload = "".join(data_parts)
                try:
                    data = json.loads(payload) if payload else {}
                except ValueError:
                    data = {"raw": payload}
                yield {"event": event or "message", "id": event_id,
                       "data": data}
            event, event_id, data_parts = None, None, []
            continue
        if line.startswith(":"):        # comment / keep-alive
            continue
        field, _, value = line.partition(":")
        value = value[1:] if value.startswith(" ") else value
        if field == "event":
            event = value
        elif field == "data":
            data_parts.append(value)
        elif field == "id":
            try:
                event_id = int(value)
            except ValueError:
                event_id = None
    if event is not None or data_parts:
        payload = "".join(data_parts)
        try:
            data = json.loads(payload) if payload else {}
        except ValueError:
            data = {"raw": payload}
        yield {"event": event or "message", "id": event_id,
               "data": data}
