"""Live token streams between the engine's scheduler thread and API
worker threads (docs/streaming.md).

Design constraints, in order:

- the SCHEDULER must never block on a slow client: `publish`/`sync`
  only append to a list and notify under a per-stream condition —
  delivery happens on the reader's thread, and a reader that never
  drains costs the engine nothing but the list's memory (bounded by
  `max_new_tokens`, which admission already caps);
- readers must be able to (re)enter at ANY index: a `Last-Event-ID`
  reconnect or a router resuming after a replica death replays from
  token k out of the stream's own buffer — the committed-token list IS
  the replay log, the same journal contract `partial()` serves;
- lock order is one-way: engine `_cv` → `StreamBook._lock` →
  `TokenStream._cond`. The engine syncs streams while holding its own
  lock, so nothing here may ever call back into the engine.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Iterator, Optional

# closed streams kept for late reconnects before eviction; sized like
# the engine's debug ring — enough for any realistic reconnect window,
# bounded so a long-lived server cannot leak one entry per request
_CLOSED_RING = 256


class TokenStream:
    """One request's live token feed.

    The writer (scheduler thread) calls `publish` with the request's
    full committed-token snapshot; the reader iterates `events`, which
    yields each token exactly once from its chosen start index and then
    ONE terminal event. Tokens are append-only: `publish` never
    truncates, so concurrent readers at different offsets stay
    consistent.
    """

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._tokens: list = []
        self.finish_reason: Optional[str] = None
        self.evac_target: Optional[str] = None
        self.closed = False

    def publish(self, tokens, finish_reason: Optional[str] = None,
                evac_target: Optional[str] = None) -> int:
        """Append any tokens past the current length, record terminal
        state, wake readers. Returns the number of NEW tokens (0 when
        the snapshot brings nothing — the common non-commit sync)."""
        with self._cond:
            new = len(tokens) - len(self._tokens)
            if new > 0:
                self._tokens.extend(
                    int(t) for t in tokens[len(self._tokens):])
            if evac_target is not None:
                self.evac_target = evac_target
            if finish_reason is not None and not self.closed:
                self.finish_reason = finish_reason
                self.closed = True
            if new > 0 or self.closed:
                self._cond.notify_all()
            return max(new, 0)

    def tokens(self) -> list:
        """Snapshot of the committed tokens so far."""
        with self._cond:
            return list(self._tokens)

    def events(self, start: int = 0,
               timeout: Optional[float] = None) -> Iterator[tuple]:
        """Yield `("token", index, token_id)` for every token at
        index >= start, then exactly one terminal event:

        - `("evacuated", next_index, target)` — the lane moved to
          another replica mid-generation; reconnect THERE with
          `Last-Event-ID = next_index - 1`;
        - `("done", next_index, finish_reason)` — normal end;
        - `("timeout", next_index, None)` — no event within `timeout`
          seconds (the reader's keep-alive/deadline surface; the
          stream itself stays open).

        Tokens are yielded OUTSIDE the condition so a stalled socket
        write never holds the lock against the scheduler's publish.
        """
        pos = max(int(start), 0)
        while True:
            with self._cond:
                while len(self._tokens) <= pos and not self.closed:
                    if not self._cond.wait(timeout=timeout):
                        yield ("timeout", pos, None)
                        return
                batch = self._tokens[pos:]
                closed = self.closed
                reason = self.finish_reason
                target = self.evac_target
            for tok in batch:
                yield ("token", pos, tok)
                pos += 1
            if closed:
                if target is not None and reason in (
                        "evacuated", "handed_off"):
                    yield ("evacuated", pos, target)
                else:
                    yield ("done", pos, reason)
                return


class StreamBook:
    """The engine's registry of live `TokenStream`s, keyed by
    request_id. `sync` is the scheduler-side hot path: when no stream
    was EVER opened it is one attribute read, and per synced request it
    is one dict probe — a non-streaming engine pays nothing."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._streams: "OrderedDict[str, TokenStream]" = OrderedDict()
        #: flips true at the first open() and never back — the /stats
        #: gate that keeps never-streaming payloads shape-identical
        self.ever_opened = False

    def open(self, req) -> TokenStream:
        """Get-or-create the stream for `req`, seeded with its current
        committed tokens (so a resumed request's stream starts at k and
        a finished request's stream replays-and-closes). Idempotent —
        the reconnect path lands here too."""
        with self._lock:
            self.ever_opened = True
            stream = self._streams.get(req.request_id)
            if stream is None:
                stream = TokenStream()
                self._streams[req.request_id] = stream
                self._evict_closed_locked()
        self._publish(stream, req)
        return stream

    def sync(self, req) -> int:
        """Scheduler-side push: publish `req`'s committed snapshot to
        its stream if one is open. Returns new-token count (0 on the
        no-stream fast path)."""
        if not self.ever_opened:
            return 0
        with self._lock:
            stream = self._streams.get(req.request_id)
        if stream is None:
            return 0
        return self._publish(stream, req)

    @staticmethod
    def _publish(stream: TokenStream, req) -> int:
        # finish_reason doubles as the terminal marker: the engine sets
        # it exactly once per request (finish/reject/detach), and
        # detach_lane stamps evac_target first, so the terminal event
        # can point the reader at the adopter
        return stream.publish(req.tokens,
                              finish_reason=req.finish_reason,
                              evac_target=req.evac_target)

    def get(self, request_id: str) -> Optional[TokenStream]:
        with self._lock:
            return self._streams.get(request_id)

    def active(self) -> int:
        """Count of open (not yet closed) streams — the
        `fstpu_streams_active` gauge / `/stats streams_active`."""
        with self._lock:
            return sum(1 for s in self._streams.values()
                       if not s.closed)

    def _evict_closed_locked(self) -> None:
        # bound the book: drop the OLDEST CLOSED streams once the
        # closed population outgrows the ring; live streams are never
        # evicted (they are bounded by the engine's slot + queue caps)
        closed = [rid for rid, s in self._streams.items() if s.closed]
        for rid in closed[:max(len(closed) - _CLOSED_RING, 0)]:
            del self._streams[rid]
