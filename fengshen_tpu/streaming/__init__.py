"""Streaming tier (docs/streaming.md): token-by-token delivery for the
continuous-batching engine.

Two halves, both host-side (nothing here is ever traced):

- `stream`: `TokenStream` / `StreamBook` — per-request bounded token
  queues the engine's scheduler thread feeds at commit time and API
  worker threads drain, with replay-from-index so `Last-Event-ID`
  reconnects and resume-from-token-k retries pick up mid-stream;
- `sse`: the Server-Sent-Events wire framing (event ids = token
  index) shared by both API paths and parsed back by the fleet
  router's streaming transport.
"""

from fengshen_tpu.streaming.sse import format_event, iter_sse
from fengshen_tpu.streaming.stream import StreamBook, TokenStream

__all__ = ["StreamBook", "TokenStream", "format_event", "iter_sse"]
