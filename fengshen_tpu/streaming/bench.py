"""Streaming-tier microbench (docs/streaming.md): token-by-token SSE
delivery vs wait-for-last-byte, the self-draft tower's speculative
yield on NON-repetitive traffic, and the kill-mid-stream gapless rung.

    make serve-bench-stream
    STREAM_BENCH_NEW_TOKENS=64 python -m fengshen_tpu.streaming.bench

Three rungs, one BENCH-schema JSON line:

1. **TTFT first-byte vs last-byte** at `WIDTH` concurrent streamed
   requests on the continuous engine: per-request wall time from
   submit to the FIRST delivered token event (`ttfb_avg_s`) vs to the
   terminal event (`ttlb_avg_s`). Streaming's whole point is the gap
   between the two — the client reads tokens while the lane is still
   decoding, so first-byte latency is a per-token commit away from
   admission instead of a full generation away.

2. **Self-draft committed/forward** on a non-repetitive workload
   (uniform random prompts — the regime where prompt-lookup's ngram
   copy finds nothing): `value` = the self-draft engine's committed
   tokens per target forward, `vs_baseline` the same number over the
   prompt-lookup engine on IDENTICAL traffic. The draft tower shares
   the target's embedding and first `SPEC_DRAFT_LAYERS` blocks, so it
   predicts the target's own distribution rather than copying the
   prompt — the bar is `vs_baseline > 1` with `value > 1.5` at
   gamma=4.

3. **Kill-mid-stream** (`kill` section): two fake SSE replicas (pure
   stdlib, deterministic token function, shared commit journal)
   behind a real `FleetRouter.route_generate_stream`; replica A's
   connection dies abruptly after `KILL_AFTER` tokens with no
   terminal event. The rung passes only when the client-visible
   concatenated stream is GAPLESS (event ids exactly 0..n-1, no
   duplicates) and token-identical to an undisturbed run — the
   router's dedupe cursor + journal resume doing their job.

The row carries ``stream`` and ``spec_mode`` — benchdiff folds both
into the comparison identity, so a streamed self-draft round never
diffs against a batch or prompt-lookup round. Env knobs
(STREAM_BENCH_*): WIDTH, REQUESTS, NEW_TOKENS, SLOTS, SPEC_GAMMA,
SPEC_DRAFT_LAYERS, KILL_AFTER, SEED, and the serve-bench model shape
knobs VOCAB / HIDDEN / INTER / LAYERS / HEADS / BUCKETS.
"""

from __future__ import annotations

import http.server
import json
import os
import sys
import threading
import time
from typing import List, Optional, Tuple


def _env(name: str, default: int) -> int:
    return int(os.environ.get(f"STREAM_BENCH_{name}", default))


def _buckets() -> Tuple[int, ...]:
    return tuple(int(b) for b in os.environ.get(
        "STREAM_BENCH_BUCKETS", "32,64").split(","))


def _emit(row: dict) -> None:
    from fengshen_tpu.observability import JsonlSink
    if os.environ.get("BENCH_DEGRADED", "0") == "1":
        row["degraded"] = True
    JsonlSink(stream=sys.stdout, only_process_zero=False)(row)


# ---- rung 1: first-byte vs last-byte at WIDTH concurrent ------------

def _ttfb_rung(model, params, prompts, new_tokens: int,
               slots: int, buckets) -> dict:
    from fengshen_tpu.serving import ContinuousBatchingEngine, EngineConfig
    engine = ContinuousBatchingEngine(model, params, EngineConfig(
        num_slots=slots, buckets=buckets, max_new_tokens=new_tokens,
        max_queue=len(prompts), eos_token_id=None, pad_token_id=0))
    engine.warmup()

    ttfb: List[float] = [0.0] * len(prompts)
    ttlb: List[float] = [0.0] * len(prompts)

    def consume(i: int, stream, t0: float) -> None:
        first = True
        for kind, _idx, _tok in stream.events(0, timeout=300.0):
            if kind == "token" and first:
                ttfb[i] = time.perf_counter() - t0
                first = False
            elif kind != "token":
                ttlb[i] = time.perf_counter() - t0
                return

    threads = []
    t_start = time.perf_counter()
    for i, p in enumerate(prompts):
        req = engine.submit(p, stream=True)
        stream = engine.streams.get(req.request_id)
        t = threading.Thread(target=consume,
                             args=(i, stream, time.perf_counter()),
                             daemon=True)
        t.start()
        threads.append(t)
    engine.run_until_idle()
    for t in threads:
        t.join(timeout=300.0)
    dt = time.perf_counter() - t_start
    return {
        "ttfb_avg_s": round(sum(ttfb) / len(ttfb), 4),
        "ttlb_avg_s": round(sum(ttlb) / len(ttlb), 4),
        "ttfb_max_s": round(max(ttfb), 4),
        "first_vs_last_byte": round(
            (sum(ttlb) / max(sum(ttfb), 1e-9)), 2),
        "tokens_per_sec": round(
            len(prompts) * new_tokens / dt, 1),
    }


# ---- rung 2: self-draft vs prompt-lookup on non-repetitive text -----

def _spec_rung(model, params, prompts, new_tokens: int, slots: int,
               buckets, gamma: int, draft_layers: int) -> dict:
    from fengshen_tpu.serving import ContinuousBatchingEngine, EngineConfig
    from fengshen_tpu.serving.bench import committed_per_forward

    out = {}
    for mode, extra in (("prompt_lookup", {}),
                        ("self_draft",
                         {"spec_draft_layers": draft_layers})):
        engine = ContinuousBatchingEngine(model, params, EngineConfig(
            num_slots=slots, buckets=buckets,
            max_new_tokens=new_tokens, max_queue=len(prompts),
            eos_token_id=None, pad_token_id=0,
            spec_mode=mode, spec_gamma=gamma, **extra))
        engine.warmup()
        t0 = time.perf_counter()
        outs = engine.generate_all(prompts)
        dt = time.perf_counter() - t0
        st = engine.stats()
        out[mode] = {
            "committed_per_forward": round(committed_per_forward(
                gamma, st["spec_acceptance_rate"]), 3),
            "acceptance_rate": st["spec_acceptance_rate"],
            "tokens_per_sec": round(
                sum(len(t) for t in outs) / dt, 1),
            "outputs": outs,
        }
    out["token_identical"] = (out["self_draft"].pop("outputs") ==
                              out["prompt_lookup"].pop("outputs"))
    return out


# ---- rung 3: kill mid-stream through the real router ----------------

def _fake_tokens(rid: str, n: int, vocab: int = 997) -> List[int]:
    s = sum(ord(c) for c in rid)
    return [(s * 31 + i * 7) % vocab for i in range(n)]


def start_fake_stream_replica(journal: dict, new_tokens: int,
                              token_s: float,
                              die_after: Optional[int] = None,
                              host: str = "127.0.0.1", port: int = 0):
    """Fake SSE replica: POST /api/text_generation/stream emits
    `new_tokens` deterministic token events (id = token index), each
    committed into the SHARED `journal` first (the fake analog of the
    engine's commit-then-publish order; any surviving peer can serve
    `GET /partial/<rid>` from it, like an evacuation adopter would).
    `die_after=k` aborts the connection after k token events with no
    terminal frame — the SIGKILL-mid-stream analog."""
    from fengshen_tpu.streaming import format_event

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _send(self, code, payload):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                self._send(200, {"status": "ok", "ready": True})
            elif self.path == "/stats":
                self._send(200, {"slots_active": 0, "queue_depth": 0,
                                 "num_slots": 4, "draining": False})
            elif self.path.startswith("/partial/"):
                rid = self.path[len("/partial/"):]
                toks = journal.get(rid)
                if toks is None:
                    self._send(404, {"error": "unknown"})
                else:
                    self._send(200, {"state": "running",
                                     "tokens": list(toks)})
            else:
                self._send(404, {"error": "not found"})

        def do_POST(self):
            if not self.path.endswith("/stream"):
                self._send(404, {"error": "not found"})
                return
            length = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(length) or b"{}")
            rid = str(req.get("request_id"))
            toks = _fake_tokens(rid, new_tokens)
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.end_headers()
            for i, t in enumerate(toks):
                if die_after is not None and i >= die_after:
                    # abrupt death: no terminal event, the socket
                    # just stops — exactly what a SIGKILL leaves
                    self.wfile.flush()
                    self.connection.close()
                    return
                journal.setdefault(rid, [])
                if i >= len(journal[rid]):
                    journal[rid].append(t)
                self.wfile.write(format_event(
                    "token", {"token": t}, event_id=i))
                self.wfile.flush()
                time.sleep(token_s)
            self.wfile.write(format_event(
                "done", {"request_id": rid, "finish_reason": "length",
                         "result": " ".join(str(t) for t in toks)},
                event_id=new_tokens))
            self.wfile.flush()

    server = http.server.ThreadingHTTPServer((host, port), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server, thread


def _kill_rung(new_tokens: int, kill_after: int) -> dict:
    from fengshen_tpu.fleet import FleetConfig, FleetRouter
    from fengshen_tpu.streaming import iter_sse

    def run(die_after: Optional[int]) -> List[dict]:
        journal: dict = {}
        servers = []
        try:
            a, _ = start_fake_stream_replica(
                journal, new_tokens, token_s=0.001,
                die_after=die_after)
            b, _ = start_fake_stream_replica(
                journal, new_tokens, token_s=0.001)
            servers = [a, b]
            targets = ["127.0.0.1:%d" % s.server_address[1]
                       for s in servers]
            router = FleetRouter(FleetConfig(
                replicas=targets, max_retries=3, recovery_probes=1,
                backoff_base_s=0.01, request_timeout_s=30.0))
            router.poll_once()
            # pin the doomed replica as first pick by occupancy tie →
            # lowest index; both idle, so A serves the fresh stream
            code, _body, frames = router.route_generate_stream(
                {"input_text": "kill rung", "request_id": "kill-1"})
            assert code == 200, code
            raw = b"".join(frames)
            router.stop()
            return list(iter_sse(raw.decode().splitlines()))
        finally:
            for s in servers:
                try:
                    s.shutdown()
                    s.server_close()
                except OSError:
                    pass

    clean = run(die_after=None)
    killed = run(die_after=kill_after)

    def token_ids(events):
        return [(e["id"], e["data"]["token"]) for e in events
                if e["event"] == "token"]

    kt = token_ids(killed)
    gapless = [i for i, _ in kt] == list(range(new_tokens))
    return {
        "enabled": True,
        "after_tokens": kill_after,
        "gapless": gapless,
        "token_identical": kt == token_ids(clean),
        "terminal": killed[-1]["event"] if killed else None,
        "delivered": len(kt),
    }


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    width = max(_env("WIDTH", 8), 1)
    slots = _env("SLOTS", 8)
    n_req = max(_env("REQUESTS", width), width)
    new_tokens = _env("NEW_TOKENS", 48)
    gamma = _env("SPEC_GAMMA", 4)
    draft_layers = _env("SPEC_DRAFT_LAYERS", 2)
    kill_after = _env("KILL_AFTER", max(new_tokens // 3, 1))
    buckets = _buckets()

    config = LlamaConfig(
        vocab_size=_env("VOCAB", 4096),
        hidden_size=_env("HIDDEN", 1024),
        intermediate_size=_env("INTER", 2816),
        num_hidden_layers=_env("LAYERS", 4),
        num_attention_heads=_env("HEADS", 8),
        # gamma-wide verify tail past the cursor, like serve-bench-spec
        max_position_embeddings=buckets[-1] + new_tokens + gamma,
        dtype="float32")
    model = LlamaForCausalLM(config)
    params = jax.jit(lambda r: model.init(
        r, jnp.zeros((1, 8), jnp.int32))["params"])(
        jax.random.PRNGKey(_env("SEED", 0)))

    # NON-repetitive traffic: uniform random prompts — prompt-lookup's
    # worst case and the draft tower's home turf
    rng = np.random.RandomState(_env("SEED", 0))
    prompt_len = max(buckets[0] // 2, 1)
    prompts = [rng.randint(3, config.vocab_size - 1,
                           prompt_len).astype(np.int32)
               for _ in range(n_req)]

    ttfb = _ttfb_rung(model, params, prompts[:width], new_tokens,
                      slots, buckets)
    spec = _spec_rung(model, params, prompts, new_tokens, slots,
                      buckets, gamma, draft_layers)
    kill = _kill_rung(new_tokens, kill_after)

    cpf_self = spec["self_draft"]["committed_per_forward"]
    cpf_lookup = spec["prompt_lookup"]["committed_per_forward"]
    _emit({
        "metric": "streaming_self_draft_committed_per_forward",
        "value": cpf_self,
        "unit": "tokens/forward",
        "vs_baseline": round(cpf_self / cpf_lookup, 3)
        if cpf_lookup > 0 else 0.0,
        "mode": "stream",
        # the comparison identity keys (benchdiff `_identity`)
        "stream": True,
        "spec_mode": "self_draft",
        "spec_gamma": gamma,
        "spec_draft_layers": draft_layers,
        "committed_per_forward_lookup": cpf_lookup,
        "acceptance_rate": spec["self_draft"]["acceptance_rate"],
        "acceptance_rate_lookup":
            spec["prompt_lookup"]["acceptance_rate"],
        "tokens_per_sec": spec["self_draft"]["tokens_per_sec"],
        "tokens_per_sec_lookup":
            spec["prompt_lookup"]["tokens_per_sec"],
        "token_identical": spec["token_identical"],
        "concurrent_streams": width,
        "requests": n_req,
        "new_tokens": new_tokens,
        "num_slots": slots,
        "prompt_tokens": prompt_len,
        **{f"stream_{k}": v for k, v in ttfb.items()},
        "kill": kill,
        "backend": jax.default_backend(),
    })


if __name__ == "__main__":
    main()
