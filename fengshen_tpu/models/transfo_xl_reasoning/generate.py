"""Causal-reasoning generation (deduction / abduction prompts).

Port of reference: fengshen/models/transfo_xl_reasoning/generate.py:22-120 —
the Randeng-TransformerXL-Abduction/Deduction checkpoints use the fixed
prompts ``<bos>{text}，因而`` (deduction, :39) and
``<bos>之所以{text}，是因为`` (abduction, :87), with Chinese punctuation
normalisation (:13-19).
"""

from __future__ import annotations

from typing import Any, List, Union

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.utils.generate import sample_sequence_batch


def en_to_zh(sentence: str) -> str:
    """reference: generate.py:13-19."""
    en_pun = u",.!?[]()<>\"\"''"
    zh_pun = u"，。！？【】（）《》“”‘’"
    table = {ord(f): ord(t) for f, t in zip(en_pun, zh_pun)}
    return sentence.translate(table)


def _generate_with_prompt(model, params, tokenizer, prompts,
                          max_out_seq, temperature, top_k, top_p, seed):
    enc = [tokenizer.encode(p) for p in prompts]
    enc = [ids[:-1] if ids and ids[-1] == tokenizer.eos_token_id else ids
           for ids in enc]
    max_len = max(len(x) for x in enc)
    pad = tokenizer.pad_token_id or 0
    batch = np.full((len(enc), max_len), pad, np.int32)
    for i, ids in enumerate(enc):
        batch[i, max_len - len(ids):] = ids
    out = sample_sequence_batch(
        model, params, jnp.asarray(batch), max_out_seq=max_out_seq,
        temperature=temperature, top_k=top_k, top_p=top_p,
        eos_token_id=tokenizer.eos_token_id,
        rng=jax.random.PRNGKey(seed))
    return [en_to_zh(tokenizer.decode(
        [int(t) for t in row[max_len:]])).replace(" ", "")
        for row in np.asarray(out)]


def deduction_generate(model: Any, params: Any, tokenizer: Any,
                       input_text: Union[str, List[str]],
                       max_out_seq: int = 128, temperature: float = 1.0,
                       top_k: int = 0, top_p: float = 0.6,
                       seed: int = 0) -> List[str]:
    """reference: generate.py:22-69 (prompt at :39)."""
    if isinstance(input_text, str):
        input_text = [input_text]
    prompts = [f"<bos>{text}，因而" for text in input_text]
    return _generate_with_prompt(model, params, tokenizer, prompts,
                                 max_out_seq, temperature, top_k, top_p,
                                 seed)


def abduction_generate(model: Any, params: Any, tokenizer: Any,
                       input_text: Union[str, List[str]],
                       max_out_seq: int = 128, temperature: float = 1.0,
                       top_k: int = 0, top_p: float = 0.6,
                       seed: int = 0) -> List[str]:
    """reference: generate.py:71-120 (prompt at :87)."""
    if isinstance(input_text, str):
        input_text = [input_text]
    prompts = [f"<bos>之所以{text}，是因为" for text in input_text]
    return _generate_with_prompt(model, params, tokenizer, prompts,
                                 max_out_seq, temperature, top_k, top_p,
                                 seed)
