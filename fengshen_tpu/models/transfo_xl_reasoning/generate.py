"""Causal-reasoning generation (deduction / abduction prompts).

Port of reference: fengshen/models/transfo_xl_reasoning/generate.py:22-120 —
the Randeng-TransformerXL-Abduction/Deduction checkpoints use the fixed
prompts ``<bos>{text}，因而`` (deduction, :39) and
``<bos>之所以{text}，是因为`` (abduction, :87), with Chinese punctuation
normalisation (:13-19). Batching/sampling rides the shared
utils.generate.generate_with_prompts (left-pad + mask aware).
"""

from __future__ import annotations

from typing import Any, List, Union

from fengshen_tpu.utils.generate import generate_with_prompts


def en_to_zh(sentence: str) -> str:
    """reference: generate.py:13-19."""
    en_pun = u",.!?[]()<>\"\"''"
    zh_pun = u"，。！？【】（）《》“”‘’"
    table = {ord(f): ord(t) for f, t in zip(en_pun, zh_pun)}
    return sentence.translate(table)


def _reason(model, params, tokenizer, prompts, max_out_seq, temperature,
            top_k, top_p, seed):
    outs = generate_with_prompts(
        model, params, tokenizer, prompts, max_out_seq=max_out_seq,
        temperature=temperature, top_k=top_k, top_p=top_p, seed=seed)
    return [en_to_zh(o).replace(" ", "") for o in outs]


def deduction_generate(model: Any, params: Any, tokenizer: Any,
                       input_text: Union[str, List[str]],
                       max_out_seq: int = 128, temperature: float = 1.0,
                       top_k: int = 0, top_p: float = 0.6,
                       seed: int = 0) -> List[str]:
    """reference: generate.py:22-69 (prompt at :39)."""
    if isinstance(input_text, str):
        input_text = [input_text]
    prompts = [f"<bos>{text}，因而" for text in input_text]
    return _reason(model, params, tokenizer, prompts, max_out_seq,
                   temperature, top_k, top_p, seed)


def abduction_generate(model: Any, params: Any, tokenizer: Any,
                       input_text: Union[str, List[str]],
                       max_out_seq: int = 128, temperature: float = 1.0,
                       top_k: int = 0, top_p: float = 0.6,
                       seed: int = 0) -> List[str]:
    """reference: generate.py:71-120 (prompt at :87)."""
    if isinstance(input_text, str):
        input_text = [input_text]
    prompts = [f"<bos>之所以{text}，是因为" for text in input_text]
    return _reason(model, params, tokenizer, prompts, max_out_seq,
                   temperature, top_k, top_p, seed)
