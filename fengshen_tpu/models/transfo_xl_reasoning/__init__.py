"""Transfo-XL reasoning family (reference:
fengshen/models/transfo_xl_reasoning/)."""

from fengshen_tpu.models.transfo_xl_denoise import (
    TransfoXLDenoiseConfig as TransfoXLReasoningConfig,
    TransfoXLDenoiseModel as TransfoXLReasoningModel)
from fengshen_tpu.models.transfo_xl_reasoning.generate import (
    abduction_generate, deduction_generate, en_to_zh)

__all__ = ["TransfoXLReasoningConfig", "TransfoXLReasoningModel",
           "deduction_generate", "abduction_generate", "en_to_zh"]
