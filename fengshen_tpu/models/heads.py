"""Generic task heads over the BERT-like encoder families.

The reference ships ForTokenClassification / ForQuestionAnswering /
ForMultipleChoice per family (e.g. reference:
fengshen/models/longformer/modeling_longformer.py,
fengshen/models/roformer/modeling_roformer.py — each ~2k LoC of repeated
head code). Here one factory builds the three heads for any encoder that
maps input_ids → hidden (and optionally pooled), so every family gets the
full HF-style head set without per-family duplication.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax.numpy as jnp
from flax import linen as nn


def _dt(cfg):
    return jnp.dtype(getattr(cfg, "dtype", "float32"))


def _dense(cfg, feats, name):
    return nn.Dense(feats, dtype=_dt(cfg),
                    param_dtype=jnp.dtype(getattr(cfg, "param_dtype",
                                                  "float32")),
                    kernel_init=nn.initializers.normal(
                        getattr(cfg, "initializer_range", 0.02)),
                    name=name)


def make_task_heads(encoder_cls: Callable, *, has_pooler: bool = True,
                    encoder_name: str = "encoder",
                    rules: Optional[Callable] = None) -> tuple:
    """Returns (ForSequenceClassification, ForTokenClassification,
    ForQuestionAnswering, ForMultipleChoice) classes for `encoder_cls`.

    encoder_cls(config, [add_pooling_layer=...], name=...) must be a flax
    module whose __call__(input_ids, **kwargs) returns hidden or
    (hidden, pooled). Extra batch kwargs (attention_mask, token_type_ids,
    global_attention_mask, ngram ids...) pass straight through.
    """

    def encode(parent_cfg, input_ids, pooled_needed, kwargs):
        if has_pooler:
            mod = encoder_cls(parent_cfg, add_pooling_layer=pooled_needed,
                              name=encoder_name)
        else:
            mod = encoder_cls(parent_cfg, name=encoder_name)
        out = mod(input_ids, **kwargs)
        if isinstance(out, tuple):
            return out
        return out, None

    def dropout(cfg, x, deterministic):
        rate = getattr(cfg, "hidden_dropout_prob", 0.1)
        return nn.Dropout(rate)(x, deterministic=deterministic)

    class ForSequenceClassification(nn.Module):
        config: Any
        num_labels: int = 2

        @nn.compact
        def __call__(self, input_ids, deterministic=True, **kwargs):
            hidden, pooled = encode(self.config, input_ids, True,
                                    dict(kwargs,
                                         deterministic=deterministic))
            if pooled is None:
                pooled = jnp.tanh(_dense(self.config,
                                         hidden.shape[-1],
                                         "pooler")(hidden[:, 0]))
            pooled = dropout(self.config, pooled, deterministic)
            return _dense(self.config, self.num_labels,
                          "classifier")(pooled)

        def partition_rules(self):
            return rules(self.config) if rules else []

    class ForTokenClassification(nn.Module):
        config: Any
        num_labels: int = 2

        @nn.compact
        def __call__(self, input_ids, deterministic=True, **kwargs):
            hidden, _ = encode(self.config, input_ids, False,
                               dict(kwargs, deterministic=deterministic))
            hidden = dropout(self.config, hidden, deterministic)
            return _dense(self.config, self.num_labels,
                          "classifier")(hidden)

        def partition_rules(self):
            return rules(self.config) if rules else []

    class ForQuestionAnswering(nn.Module):
        config: Any

        @nn.compact
        def __call__(self, input_ids, deterministic=True, **kwargs):
            hidden, _ = encode(self.config, input_ids, False,
                               dict(kwargs, deterministic=deterministic))
            logits = _dense(self.config, 2, "qa_outputs")(hidden)
            start, end = jnp.split(logits, 2, axis=-1)
            return start[..., 0], end[..., 0]

        def partition_rules(self):
            return rules(self.config) if rules else []

    class ForMultipleChoice(nn.Module):
        config: Any

        @nn.compact
        def __call__(self, input_ids, deterministic=True, **kwargs):
            """input_ids [B, C, S] (and per-choice kwargs likewise) →
            choice logits [B, C]."""
            batch, n_choices, seq = input_ids.shape
            flat_kwargs = {}
            for k, v in kwargs.items():
                if hasattr(v, "ndim") and v.ndim >= 3 and \
                        v.shape[:2] == (batch, n_choices):
                    flat_kwargs[k] = v.reshape((batch * n_choices,) +
                                               v.shape[2:])
                else:
                    flat_kwargs[k] = v
            flat = input_ids.reshape(batch * n_choices, seq)
            hidden, pooled = encode(self.config, flat, True,
                                    dict(flat_kwargs,
                                         deterministic=deterministic))
            if pooled is None:
                pooled = jnp.tanh(_dense(self.config, hidden.shape[-1],
                                         "pooler")(hidden[:, 0]))
            pooled = dropout(self.config, pooled, deterministic)
            score = _dense(self.config, 1, "classifier")(pooled)
            return score.reshape(batch, n_choices)

        def partition_rules(self):
            return rules(self.config) if rules else []

    return (ForSequenceClassification, ForTokenClassification,
            ForQuestionAnswering, ForMultipleChoice)
