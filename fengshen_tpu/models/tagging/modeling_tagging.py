"""Tagging heads over a MegatronBert encoder.

Port of reference: fengshen/models/tagging_models/ — `BertLinear`
(token-softmax), `BertCrf` (CRF decode), `BertSpan` (start/end pointers),
`BertBiaffine` (span biaffine scorer).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn

from fengshen_tpu.models.megatron_bert import MegatronBertConfig
from fengshen_tpu.models.megatron_bert.modeling_megatron_bert import (
    PARTITION_RULES, SCAN_PARTITION_RULES, _dense)
from fengshen_tpu.models.tagging.crf import CRF
from fengshen_tpu.parallel.cross_entropy import stable_cross_entropy


class _TaggingBase(nn.Module):
    """`backbone_type="bert"` matches the published checkpoints (the
    reference heads wrap a plain HF BertModel,
    reference: fengshen/models/tagging_models/bert_for_tagging.py:25)."""

    config: MegatronBertConfig
    num_labels: int = 9
    backbone_type: str = "megatron_bert"

    def partition_rules(self):
        return SCAN_PARTITION_RULES if self.config.scan_layers \
            else PARTITION_RULES

    def _encode(self, input_ids, attention_mask, token_type_ids,
                deterministic):
        from fengshen_tpu.models.towers import encoder_tower
        hidden, _ = encoder_tower(self.config, self.backbone_type)(
            input_ids, attention_mask, token_type_ids,
            deterministic=deterministic)
        return nn.Dropout(self.config.hidden_dropout_prob)(
            hidden, deterministic=deterministic)


class BertLinear(_TaggingBase):
    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 labels=None, deterministic=True):
        hidden = self._encode(input_ids, attention_mask, token_type_ids,
                              deterministic)
        logits = _dense(self.config, self.num_labels, "classifier")(hidden)
        if labels is None:
            return logits
        loss, _ = stable_cross_entropy(logits, labels)
        return loss, logits


class BertCrf(_TaggingBase):
    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 labels=None, decode: bool = False, deterministic=True):
        hidden = self._encode(input_ids, attention_mask, token_type_ids,
                              deterministic)
        logits = _dense(self.config, self.num_labels, "classifier")(hidden)
        crf = CRF(self.num_labels, name="crf")
        if decode:
            return crf.decode(logits, attention_mask)
        if labels is None:
            return logits
        safe_labels = jnp.where(labels == -100, 0, labels)
        mask = attention_mask if attention_mask is not None else \
            jnp.ones(labels.shape, jnp.int32)
        mask = mask * (labels != -100)
        loss = crf(logits, safe_labels, mask)
        return loss, logits


class BertSpan(_TaggingBase):
    """Start/end pointer head. The end pointer conditions on the start
    labels — one-hot (soft_label) or the raw label id as one float
    feature (hard label) during training, softmax/argmax of the start
    logits at inference — through dense_0 → tanh → LayerNorm → dense_1
    (reference: fengshen/models/tagging_models/layers/linears.py:27-40
    PoolerEndLogits; bert_for_tagging.py:140-155 soft/hard wiring)."""

    soft_label: bool = True

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 start_labels=None, end_labels=None, deterministic=True):
        from fengshen_tpu.ops.norms import LayerNorm
        hidden = self._encode(input_ids, attention_mask, token_type_ids,
                              deterministic)
        start_logits = _dense(self.config, self.num_labels,
                              "start_classifier")(hidden)
        training = start_labels is not None and not deterministic
        if self.soft_label:
            label_feat = (
                jax.nn.one_hot(start_labels, self.num_labels,
                               dtype=hidden.dtype) if training
                else jax.nn.softmax(start_logits, -1).astype(hidden.dtype))
        else:
            label_feat = (
                start_labels if training
                else jnp.argmax(start_logits, -1)
            ).astype(hidden.dtype)[..., None]
        x = jnp.concatenate([hidden, label_feat], axis=-1)
        x = jnp.tanh(_dense(self.config, x.shape[-1], "end_dense_0")(x))
        x = LayerNorm(epsilon=self.config.layer_norm_eps,
                      name="end_ln")(x)
        end_logits = _dense(self.config, self.num_labels,
                            "end_dense_1")(x)
        if start_labels is None:
            return start_logits, end_logits
        s_loss, _ = stable_cross_entropy(start_logits, start_labels)
        e_loss, _ = stable_cross_entropy(end_logits, end_labels)
        return (s_loss + e_loss) / 2, (start_logits, end_logits)


class BertBiaffine(_TaggingBase):
    """Span scorer: bi-LSTM context mixer + per-span label logits via a
    biaffine form (reference: tagging_models BertBiaffine,
    bert_for_tagging.py:77-96 — 2-layer bidirectional LSTM over the
    encoder output, ReLU start/end projections, [d+1, L, d+1] U)."""

    biaffine_size: int = 128
    use_lstm: bool = True

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 span_labels=None, deterministic=True):
        cfg = self.config
        hidden = self._encode(input_ids, attention_mask, token_type_ids,
                              deterministic)
        if self.use_lstm:
            half = cfg.hidden_size // 2
            for li in range(2):
                fwd = nn.RNN(nn.OptimizedLSTMCell(
                    half, name=f"lstm_l{li}_fwd"))
                bwd = nn.RNN(nn.OptimizedLSTMCell(
                    half, name=f"lstm_l{li}_bwd"), reverse=True,
                    keep_order=True)
                hidden = jnp.concatenate([fwd(hidden), bwd(hidden)],
                                         axis=-1)
            hidden = nn.Dropout(cfg.hidden_dropout_prob)(
                hidden, deterministic=deterministic)
        start = jax.nn.relu(_dense(cfg, self.biaffine_size, "start_mlp")(
            hidden))
        end = jax.nn.relu(_dense(cfg, self.biaffine_size, "end_mlp")(
            hidden))
        U = self.param("biaffine_u", nn.initializers.normal(0.02),
                       (self.biaffine_size + 1, self.num_labels,
                        self.biaffine_size + 1), jnp.float32)
        ones_s = jnp.ones(start.shape[:-1] + (1,), start.dtype)
        start = jnp.concatenate([start, ones_s], axis=-1)
        end = jnp.concatenate([end, ones_s], axis=-1)
        # [B, Si, L, Sj]
        logits = jnp.einsum("bid,dle,bje->bilj", start,
                            U.astype(start.dtype), end)
        logits = logits.transpose(0, 1, 3, 2)  # [B, Si, Sj, L]
        if span_labels is None:
            return logits
        loss, _ = stable_cross_entropy(logits, span_labels)
        return loss, logits
