"""Linear-chain CRF in jax.

Port of the reference's torch CRF
(reference: fengshen/models/tagging_models/layers/crf.py — forward
log-likelihood with masked sequences and Viterbi decode). Both the forward
algorithm and Viterbi run as `lax.scan` over time — compiler-friendly, no
per-step Python.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn


class CRF(nn.Module):
    num_tags: int

    def setup(self):
        self.start_transitions = self.param(
            "start_transitions", nn.initializers.uniform(0.1),
            (self.num_tags,))
        self.end_transitions = self.param(
            "end_transitions", nn.initializers.uniform(0.1),
            (self.num_tags,))
        self.transitions = self.param(
            "transitions", nn.initializers.uniform(0.1),
            (self.num_tags, self.num_tags))

    def __call__(self, emissions, tags, mask=None):
        """Negative mean log-likelihood. emissions [B,S,T], tags [B,S],
        mask [B,S] (1 = real token)."""
        if mask is None:
            mask = jnp.ones(tags.shape, jnp.int32)
        numerator = self._score(emissions, tags, mask)
        denominator = self._normalizer(emissions, mask)
        return -(numerator - denominator).mean()

    def _score(self, emissions, tags, mask):
        batch, seq, _ = emissions.shape
        maskf = mask.astype(jnp.float32)
        first_emit = jnp.take_along_axis(
            emissions[:, 0], tags[:, 0, None], axis=-1)[:, 0]
        score = self.start_transitions[tags[:, 0]] + first_emit

        def step(carry, t):
            score, prev_tag = carry
            emit = jnp.take_along_axis(
                emissions[:, t], tags[:, t, None], axis=-1)[:, 0]
            trans = self.transitions[prev_tag, tags[:, t]]
            score = score + (emit + trans) * maskf[:, t]
            prev_tag = jnp.where(mask[:, t] > 0, tags[:, t], prev_tag)
            return (score, prev_tag), None

        (score, last_tag), _ = jax.lax.scan(
            step, (score, tags[:, 0]), jnp.arange(1, seq))
        return score + self.end_transitions[last_tag]

    def _normalizer(self, emissions, mask):
        batch, seq, n = emissions.shape
        alpha = self.start_transitions[None] + emissions[:, 0]

        def step(alpha, t):
            # [B, prev, next]
            scores = alpha[:, :, None] + self.transitions[None] + \
                emissions[:, t][:, None, :]
            new_alpha = jax.nn.logsumexp(scores, axis=1)
            keep = mask[:, t, None] > 0
            return jnp.where(keep, new_alpha, alpha), None

        alpha, _ = jax.lax.scan(step, alpha, jnp.arange(1, seq))
        return jax.nn.logsumexp(alpha + self.end_transitions[None], axis=-1)

    def decode(self, emissions, mask=None):
        """Viterbi best paths [B, S] (pad positions hold tag 0)."""
        batch, seq, n = emissions.shape
        if mask is None:
            mask = jnp.ones((batch, seq), jnp.int32)
        score = self.start_transitions[None] + emissions[:, 0]

        def forward(score, t):
            # [B, prev, next]
            cand = score[:, :, None] + self.transitions[None] + \
                emissions[:, t][:, None, :]
            best_prev = cand.argmax(axis=1)
            best_score = cand.max(axis=1)
            keep = mask[:, t, None] > 0
            new_score = jnp.where(keep, best_score, score)
            # when masked, point back to self so backtrack is a no-op
            best_prev = jnp.where(keep, best_prev,
                                  jnp.arange(n)[None, :])
            return new_score, best_prev

        score, history = jax.lax.scan(forward, score, jnp.arange(1, seq))
        last = (score + self.end_transitions[None]).argmax(-1)

        def backward(tag, backptr):
            prev = jnp.take_along_axis(backptr, tag[:, None], axis=-1)[:, 0]
            return prev, tag

        # ys[i] = tag at time i+1; final carry = tag at time 0
        tag0, tags_rest = jax.lax.scan(backward, last, history, reverse=True)
        tags = jnp.concatenate([tag0[:, None], tags_rest.transpose(1, 0)],
                               axis=1)
        return tags * mask
