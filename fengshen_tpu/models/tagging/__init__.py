"""Sequence-tagging models (reference: fengshen/models/tagging_models/ —
BertLinear / BertCrf / BertSpan / BertBiaffine over a BERT encoder, with the
CRF layer at tagging_models/layers/crf.py)."""

from fengshen_tpu.models.tagging.crf import CRF
from fengshen_tpu.models.tagging.modeling_tagging import (
    BertLinear, BertCrf, BertSpan, BertBiaffine)

__all__ = ["CRF", "BertLinear", "BertCrf", "BertSpan", "BertBiaffine"]
