"""Reference tagging checkpoints → flax params.

One converter for the four heads of
fengshen/models/tagging_models/bert_for_tagging.py (all over a plain HF
BertModel tower `bert.*`):

- BertLinear: `classifier`
- BertCrf:    `classifier` + `crf.{start_transitions,end_transitions,
              transitions}` (layers/crf.py:32-36)
- BertSpan:   `start_fc.dense` + `end_fc.{dense_0,LayerNorm,dense_1}`
              (layers/linears.py:18-40)
- BertBiaffine: 2-layer bi-LSTM `lstm.*` + `start_layer.0`/`end_layer.0`
              + `biaffne_layer.U` [d+1, L, d+1] (sic — the reference
              misspells "biaffine" in the attr name)
"""

from __future__ import annotations

from typing import Any, Mapping

from fengshen_tpu.utils.convert_common import (detect_bert_arch,
                                               encoder_tower_params,
                                               lstm_cell_params,
                                               make_helpers, tensor,
                                               unwrap_lightning)


def torch_to_params(state_dict: Mapping[str, Any], config,
                    head: str = "linear",
                    backbone_type: str | None = None) -> dict:
    """`head` ∈ {linear, crf, span, biaffine} matching the four flax
    heads in modeling_tagging.py."""
    sd = unwrap_lightning(state_dict)
    if backbone_type is None:
        backbone_type = detect_bert_arch(sd)
    t, lin, ln = make_helpers(sd)
    params: dict = {"bert": encoder_tower_params(sd, config, backbone_type)}

    if head in ("linear", "crf"):
        params["classifier"] = lin("classifier")
    if head == "crf":
        params["crf"] = {
            "start_transitions": t("crf.start_transitions"),
            "end_transitions": t("crf.end_transitions"),
            "transitions": t("crf.transitions"),
        }
    if head == "span":
        params["start_classifier"] = lin("start_fc.dense")
        params["end_dense_0"] = lin("end_fc.dense_0")
        params["end_ln"] = ln("end_fc.LayerNorm")
        params["end_dense_1"] = lin("end_fc.dense_1")
    if head == "biaffine":
        params["start_mlp"] = lin("start_layer.0")
        params["end_mlp"] = lin("end_layer.0")
        params["biaffine_u"] = tensor(sd, "biaffne_layer.U")
        for li in range(2):
            params[f"lstm_l{li}_fwd"] = lstm_cell_params(
                sd, "lstm", li, reverse=False)
            params[f"lstm_l{li}_bwd"] = lstm_cell_params(
                sd, "lstm", li, reverse=True)
    return params
