"""Hubert in flax: conv waveform encoder + transformer + masked
cluster prediction.

Released-architecture port of the reference workload (reference:
fengshen/examples/hubert/pretrain_hubert.py:19-55 over fairseq's
HubertModel; data at fengshen/data/hubert/hubert_dataset.py): raw audio →
strided conv feature encoder (~50Hz frames, hubert-base "group" norm or
hubert-large "layer" norm mode, exact erf gelu) → pre-projection
LayerNorm → span-masked frames replaced by a learned mask embedding →
weight-normed SamePad conv positional embedding → encoder LayerNorm →
post-LN transformer → per-frame logits over k-means cluster codebooks;
loss is CE at masked (and optionally unmasked) frames.

Forward parity with `transformers.HubertModel` (the released-checkpoint
format) is tested in tests/test_hubert.py for both conv-norm modes and
both encoder variants — post-LN (hubert-base) and the pre-LN
`do_stable_layer_norm=True` stack (hubert-large, `BertLayer(pre_ln=
True)` with the encoder LayerNorm after the layers).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from fengshen_tpu.models.bert.modeling_bert import BertConfig, BertLayer


@dataclasses.dataclass
class HubertConfig:
    # conv feature encoder: (channels, kernel, stride) per layer
    conv_layers: Sequence[Sequence[int]] = (
        (512, 10, 5), (512, 3, 2), (512, 3, 2), (512, 3, 2), (512, 3, 2),
        (512, 2, 2), (512, 2, 2))
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    num_clusters: int = 500
    mask_prob: float = 0.65
    mask_length: int = 10
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    # fairseq-style conv positional embedding over frames
    pos_conv_kernel: int = 128
    pos_conv_groups: int = 16
    # fairseq/HF conv-encoder norm mode: "group" (hubert-base: bias-free
    # convs, one channel-wise GroupNorm after layer 0) or "layer"
    # (hubert-large: biased convs, LayerNorm after every conv)
    feat_extract_norm: str = "group"
    # hubert-large's pre-LN transformer: encoder LayerNorm moves AFTER
    # the stack and each layer normalizes before attention/ffn
    do_stable_layer_norm: bool = False
    layer_norm_eps: float = 1e-5
    dtype: str = "float32"
    param_dtype: str = "float32"

    @classmethod
    def small_test_config(cls, **overrides: Any) -> "HubertConfig":
        base = dict(conv_layers=((16, 10, 5), (16, 3, 2)), hidden_size=32,
                    num_hidden_layers=2, num_attention_heads=4,
                    intermediate_size=64, num_clusters=16, mask_length=2,
                    pos_conv_kernel=7, pos_conv_groups=4)
        base.update(overrides)
        return cls(**base)

    def _bert_config(self) -> BertConfig:
        return BertConfig(
            vocab_size=1, hidden_size=self.hidden_size,
            num_hidden_layers=self.num_hidden_layers,
            num_attention_heads=self.num_attention_heads,
            intermediate_size=self.intermediate_size,
            layer_norm_eps=self.layer_norm_eps,
            hidden_dropout_prob=self.hidden_dropout_prob,
            attention_probs_dropout_prob=self.attention_probs_dropout_prob,
            dtype=self.dtype, param_dtype=self.param_dtype)


def compute_mask_indices(shape: tuple[int, int], mask_prob: float,
                         mask_length: int, rng: np.random.RandomState
                         ) -> np.ndarray:
    """Span mask over frames (fairseq-style): choose start indices so that
    ~mask_prob of frames fall inside a span of mask_length."""
    batch, frames = shape
    mask = np.zeros(shape, bool)
    n_spans = max(1, int(mask_prob * frames / mask_length + rng.random()))
    for b in range(batch):
        starts = rng.choice(max(frames - mask_length, 1),
                            size=min(n_spans, max(frames - mask_length, 1)),
                            replace=False)
        for s in starts:
            mask[b, s:s + mask_length] = True
    return mask


class HubertModel(nn.Module):
    config: HubertConfig

    @nn.compact
    def __call__(self, waveform, mask_time_indices=None,
                 deterministic=True):
        """waveform [B, T] → (logits [B, F, num_clusters], features)."""
        cfg = self.config
        dt = jnp.dtype(cfg.dtype)
        layer_mode = cfg.feat_extract_norm == "layer"
        h = waveform[..., None]  # [B, T, 1]
        for i, (ch, kernel, stride) in enumerate(cfg.conv_layers):
            # VALID padding: fairseq/HF HuBERT convs are unpadded, which
            # fixes the frame count expected by the k-means label pipeline
            h = nn.Conv(ch, (kernel,), strides=(stride,), padding="VALID",
                        use_bias=layer_mode, dtype=dt,
                        name=f"conv_{i}")(h)
            if layer_mode:
                # hubert-large: LayerNorm over channels after every conv
                h = nn.LayerNorm(epsilon=cfg.layer_norm_eps,
                                 name=f"conv_norm_{i}")(h)
            elif i == 0:
                # hubert-base: ONE channel-wise GroupNorm (group per
                # channel — fairseq mode="default"/HF "group")
                h = nn.GroupNorm(num_groups=ch, epsilon=cfg.layer_norm_eps,
                                 name="conv_norm_0")(h)
            h = jax.nn.gelu(h, approximate=False)  # torch erf gelu
        # HF/fairseq order: LayerNorm over the CONV dim, THEN project
        # (feature_projection.layer_norm before .projection)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps,
                         name="feature_norm")(h)
        features = nn.Dense(cfg.hidden_size, dtype=dt,
                            name="feature_projection")(h)

        mask_emb = self.param("mask_embedding",
                              nn.initializers.normal(0.02),
                              (cfg.hidden_size,),
                              jnp.dtype(cfg.param_dtype))
        if mask_time_indices is not None:
            features = jnp.where(mask_time_indices[..., None],
                                 mask_emb[None, None].astype(features.dtype),
                                 features)

        # conv positional embedding (fairseq pos_conv): grouped conv over
        # frames with k//2 padding — fairseq trims the LAST frame when
        # the kernel is even (SamePadLayer) — gelu, added to features
        k = cfg.pos_conv_kernel
        pos = nn.Conv(cfg.hidden_size, (k,),
                      padding=((k // 2, k // 2),),
                      feature_group_count=cfg.pos_conv_groups,
                      dtype=dt, name="pos_conv")(features)
        if k % 2 == 0:
            pos = pos[:, :-1]
        features = features + jax.nn.gelu(pos, approximate=False)
        # encoder-level LayerNorm: BEFORE the stack for the post-LN
        # encoder (HF HubertEncoder), AFTER it for hubert-large's
        # pre-LN stable variant (HubertEncoderStableLayerNorm)
        if not cfg.do_stable_layer_norm:
            features = nn.LayerNorm(epsilon=cfg.layer_norm_eps,
                                    name="encoder_norm")(features)

        bert_cfg = cfg._bert_config()
        hidden = features
        for i in range(cfg.num_hidden_layers):
            hidden = BertLayer(bert_cfg,
                               pre_ln=cfg.do_stable_layer_norm,
                               name=f"layer_{i}")(
                hidden, None, deterministic)
        if cfg.do_stable_layer_norm:
            hidden = nn.LayerNorm(epsilon=cfg.layer_norm_eps,
                                  name="encoder_norm")(hidden)
        logits = nn.Dense(cfg.num_clusters, dtype=dt,
                          name="cluster_head")(hidden)
        return logits, hidden

    def partition_rules(self):
        # same layer param names as the BERT stack it reuses
        from fengshen_tpu.models.bert.modeling_bert import PARTITION_RULES
        return PARTITION_RULES


def hubert_pretrain_loss(logits, cluster_targets, mask_time_indices,
                         unmasked_weight: float = 0.0, frame_mask=None):
    """CE at masked frames (+ optional unmasked term, fairseq's
    pred_nomask). The per-frame CE is computed once and reduced under the
    two masks; `frame_mask` (1 = real frame) keeps pad frames out of the
    unmasked term on variable-length batches."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    token_ce = -jnp.take_along_axis(logp, cluster_targets[..., None],
                                    axis=-1)[..., 0]
    masked = mask_time_indices.astype(jnp.float32)
    n_m = jnp.maximum(masked.sum(), 1)
    loss_m = (token_ce * masked).sum() / n_m
    if unmasked_weight > 0.0:
        unmasked = 1.0 - masked
        if frame_mask is not None:
            unmasked = unmasked * frame_mask.astype(jnp.float32)
        loss_u = (token_ce * unmasked).sum() / jnp.maximum(unmasked.sum(),
                                                           1)
        return loss_m + unmasked_weight * loss_u, masked.sum()
    return loss_m, masked.sum()
