"""Hubert audio family (reference: fengshen/examples/hubert/
pretrain_hubert.py wraps the fairseq HubertModel; here a native flax
implementation of the masked-cluster-prediction pretraining)."""

from fengshen_tpu.models.hubert.modeling_hubert import (
    HubertConfig, HubertModel, hubert_pretrain_loss, compute_mask_indices)

__all__ = ["HubertConfig", "HubertModel", "hubert_pretrain_loss",
           "compute_mask_indices"]
