"""torch(HF/fairseq) → jax weights for HuBERT.

Importer for released HuBERT checkpoints in HF naming
(reference: fengshen/examples/hubert/pretrain_hubert.py:19-55 wraps the
fairseq HubertModel; HF `HubertModel` is the released-weights format).
The conv feature encoder, feature projection, masked embed, weight-normed
conv positional embedding, and transformer layers all map; the k-means
`cluster_head` exists only in pretraining checkpoints (fairseq
`final_proj`) and is left to the caller when absent.
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from fengshen_tpu.models.hubert.modeling_hubert import HubertConfig
from fengshen_tpu.utils.convert_common import make_helpers, tensor


def _weight_norm_conv(state_dict: Mapping[str, Any], prefix: str
                      ) -> np.ndarray:
    """Collapse fairseq/HF weight-norm (weight_g, weight_v) into an
    effective conv weight; also accepts a plain `weight`.

    HF/fairseq build the pos conv with ``weight_norm(conv, dim=2)``
    (weight_g shape (1, 1, K): one gain per kernel position, norm over the
    out/in axes). The g shape disambiguates the convention, so dim=0
    checkpoints ((out, 1, 1) gains) also import correctly."""
    if f"{prefix}.weight" in state_dict:
        return tensor(state_dict, f"{prefix}.weight")
    if f"{prefix}.parametrizations.weight.original0" in state_dict:
        # torch >= 2.1 parametrize naming: original0 = g, original1 = v
        g = tensor(state_dict,
                   f"{prefix}.parametrizations.weight.original0")
        v = tensor(state_dict,
                   f"{prefix}.parametrizations.weight.original1")
    else:
        g = tensor(state_dict, f"{prefix}.weight_g")
        v = tensor(state_dict, f"{prefix}.weight_v")
    if g.shape[0] == 1:      # dim=2: per-kernel-position gain
        axes = (0, 1)
    else:                    # dim=0: per-out-channel gain
        axes = (1, 2)
    norm = np.sqrt((v ** 2).sum(axis=axes, keepdims=True))
    return g * v / np.maximum(norm, 1e-12)


def torch_to_params(state_dict: Mapping[str, Any],
                    config: HubertConfig) -> dict:
    sd = state_dict
    if any(k.startswith("hubert.") for k in sd):
        sd = {k[len("hubert."):]: v for k, v in sd.items()
              if k.startswith("hubert.")}
    t, lin, ln = make_helpers(sd)

    params: dict = {}
    for i in range(len(config.conv_layers)):
        # torch Conv1d [out, in, k] → flax [k, in, out]
        pre = f"feature_extractor.conv_layers.{i}"
        w = t(f"{pre}.conv.weight")
        params[f"conv_{i}"] = {"kernel": w.transpose(2, 1, 0)}
        if f"{pre}.conv.bias" in sd:
            params[f"conv_{i}"]["bias"] = t(f"{pre}.conv.bias")
        if f"{pre}.layer_norm.weight" in sd:
            # layer 0 GroupNorm in "group" mode, per-layer LayerNorm in
            # "layer" mode — both live under .layer_norm in HF naming
            params[f"conv_norm_{i}"] = ln(f"{pre}.layer_norm")
    params["feature_projection"] = lin("feature_projection.projection")
    params["feature_norm"] = ln("feature_projection.layer_norm")
    if "encoder.layer_norm.weight" in sd:
        params["encoder_norm"] = ln("encoder.layer_norm")
    if "masked_spec_embed" in sd:
        params["mask_embedding"] = t("masked_spec_embed")

    pos_w = _weight_norm_conv(sd, "encoder.pos_conv_embed.conv")
    # grouped torch Conv1d [out, in/groups, k] → flax [k, in/groups, out]
    params["pos_conv"] = {
        "kernel": pos_w.transpose(2, 1, 0),
        "bias": t("encoder.pos_conv_embed.conv.bias")}

    for i in range(config.num_hidden_layers):
        p = f"encoder.layers.{i}"
        params[f"layer_{i}"] = {
            "query": lin(f"{p}.attention.q_proj"),
            "key": lin(f"{p}.attention.k_proj"),
            "value": lin(f"{p}.attention.v_proj"),
            "attention_output_dense": lin(f"{p}.attention.out_proj"),
            "attention_ln": ln(f"{p}.layer_norm"),
            "intermediate_dense": lin(
                f"{p}.feed_forward.intermediate_dense"),
            "output_dense": lin(f"{p}.feed_forward.output_dense"),
            "output_ln": ln(f"{p}.final_layer_norm"),
        }
    # fairseq pretraining head (km logits); HF fine-tune ckpts lack it
    if "final_proj.weight" in sd:
        params["cluster_head"] = lin("final_proj")
    return params


def params_to_torch_state(params: dict, config, template_state,
                          **import_kwargs) -> dict:
    """flax params → HF state_dict-shaped numpy mapping — the derived
    exact inverse of `torch_to_params` (utils/convert_common.
    invert_import), plus a hand-inverted pos-conv weight-norm: the
    import COLLAPSES (g, v) into an effective weight (arithmetic the
    numeric inverter rightly refuses), so the export re-decomposes the
    effective weight as v := w, g := ‖w‖ over the norm axes — an exact
    preimage under g·v/‖v‖."""
    from fengshen_tpu.utils.convert_common import (invert_import,
                                                   load_torch_checkpoint)
    if isinstance(template_state, str):
        template_state = load_torch_checkpoint(template_state)
    prefix = "encoder.pos_conv_embed.conv"
    maybe_hubert = "hubert." if any(
        k.startswith("hubert.") for k in template_state) else ""
    wn_keys = [k for k in template_state
               if k.startswith(f"{maybe_hubert}{prefix}.") and
               ("weight_g" in k or "weight_v" in k or
                "parametrizations" in k)]
    if not wn_keys:
        return invert_import(torch_to_params, template_state, config,
                             params, **import_kwargs)
    g_key = next(k for k in wn_keys
                 if k.endswith(("weight_g", "original0")))
    g_shape = tuple(template_state[g_key].shape)
    # swap (g, v) for one plain-weight key so the permutation inverse
    # applies, then decompose back
    eff = _weight_norm_conv(
        {k[len(maybe_hubert):]: v for k, v in template_state.items()
         if k.startswith(maybe_hubert)}, prefix)
    template2 = {k: v for k, v in template_state.items()
                 if k not in wn_keys}
    template2[f"{maybe_hubert}{prefix}.weight"] = eff
    out = invert_import(torch_to_params, template2, config, params,
                        **import_kwargs)
    w = out.pop(f"{maybe_hubert}{prefix}.weight")
    axes = (0, 1) if g_shape[0] == 1 else (1, 2)
    g = np.sqrt((w.astype(np.float64) ** 2).sum(axis=axes,
                                                keepdims=True))
    for k in wn_keys:
        # keep each key's own checkpoint dtype (fp16 templates must
        # export fp16, like every other key)
        src = template_state[k]
        dt = str(getattr(src, "dtype", "float32")).replace("torch.", "")
        val = g if k.endswith(("weight_g", "original0")) else w
        try:
            out[k] = val.astype(np.dtype(dt))
        except TypeError:
            out[k] = val.astype(np.float32)
    return out
