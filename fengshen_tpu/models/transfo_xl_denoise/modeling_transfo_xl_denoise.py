"""Denoising text autoencoder with segment recurrence.

Behavioural port of reference: fengshen/models/transfo_xl_denoise/ —
`TransfoXLDenoiseModel` reconstructs original text from corrupted input
(the "denoise" objective) over a long-context causal backbone; the
Transformer-XL trick is segment-level recurrence (previous-segment states
attended as read-only memory).

TPU-native design: the backbone is the GPT2 decoder whose preallocated KV
cache doubles as the XL memory — processing a long document as fixed-size
segments through the cache gives the same recurrence pattern with static
shapes (reference: SURVEY.md §5.7 item 4).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from fengshen_tpu.models.gpt2 import GPT2Config, GPT2Model
from fengshen_tpu.parallel.cross_entropy import stable_cross_entropy


@dataclasses.dataclass
class TransfoXLDenoiseConfig(GPT2Config):
    segment_length: int = 512  # per-segment window under recurrence
    # The published checkpoints are trained with relative position
    # encoding (reference: configuration_transfo_xl_denoise.py:103
    # relative_encoding=True) — turning this on swaps the backbone to the
    # faithful TransfoXLModel so imports are exact; False keeps the
    # original absolute-position GPT2 backbone.
    relative_encoding: bool = False

    @classmethod
    def small_test_config(cls, **overrides: Any):
        base = dict(vocab_size=128, n_positions=64, n_embd=32, n_layer=2,
                    n_head=4, segment_length=16)
        base.update(overrides)
        return cls(**base)


class TransfoXLDenoiseModel(nn.Module):
    """source (corrupted) + target prefix → reconstruction logits."""

    config: TransfoXLDenoiseConfig

    def setup(self):
        if self.config.relative_encoding:
            from fengshen_tpu.models.transfo_xl_denoise.modeling_transfo_xl \
                import TransfoXLConfig, TransfoXLModel
            cfg = self.config
            self.backbone = TransfoXLModel(TransfoXLConfig(
                vocab_size=cfg.vocab_size, hidden_size=cfg.n_embd,
                num_layers=cfg.n_layer, num_attention_heads=cfg.n_head,
                max_sequence_length=cfg.n_positions,
                max_memory_length=cfg.segment_length,
                embedding_dropout_prob=cfg.embd_pdrop,
                attention_dropout_prob=cfg.attn_pdrop,
                output_dropout_prob=cfg.resid_pdrop,
                layernorm_epsilon=cfg.layer_norm_epsilon,
                initializer_range=cfg.initializer_range,
                dtype=cfg.dtype, param_dtype=cfg.param_dtype),
                name="backbone")
            self.lm_head = None
        else:
            self.backbone = GPT2Model(self.config, name="backbone")
            self.lm_head = nn.Dense(self.config.vocab_size, use_bias=False,
                                    param_dtype=jnp.dtype(
                                        self.config.param_dtype),
                                    name="lm_head")

    def __call__(self, input_ids, attention_mask=None, position_ids=None,
                 init_cache=False, deterministic=True, mems=None,
                 return_mems=False):
        """`mems`/`return_mems` drive the XL segment recurrence in
        relative mode (ignored by the absolute-position backbone, whose
        recurrence rides the KV cache via forward_segments)."""
        if self.config.relative_encoding:
            logits, new_mems = self.backbone(
                input_ids, attention_mask=attention_mask, mems=mems,
                deterministic=deterministic)
            return (logits, new_mems) if return_mems else logits
        hidden = self.backbone(input_ids, attention_mask=attention_mask,
                               position_ids=position_ids,
                               init_cache=init_cache,
                               deterministic=deterministic)
        return self.lm_head(hidden)

    def forward_segments(self, input_ids, deterministic=True):
        """Long input processed as fixed-size segments — via the XL
        memory in relative mode, via the preallocated KV cache otherwise
        (apply with mutable=["cache"] and an initialised cache for the
        latter). Returns concatenated logits."""
        cfg = self.config
        seg = cfg.segment_length
        batch, total = input_ids.shape
        n_seg = (total + seg - 1) // seg
        outs = []
        if cfg.relative_encoding:
            mems = None
            for s in range(n_seg):
                chunk = input_ids[:, s * seg:(s + 1) * seg]
                logits, mems = self.backbone(
                    chunk, mems=mems, deterministic=deterministic)
                outs.append(logits)
            return jnp.concatenate(outs, axis=1)
        for s in range(n_seg):
            chunk = input_ids[:, s * seg:(s + 1) * seg]
            pos = (s * seg + jnp.arange(chunk.shape[1]))[None]
            hidden = self.backbone(chunk, position_ids=pos,
                                   init_cache=True,
                                   deterministic=deterministic)
            outs.append(self.lm_head(hidden))
        return jnp.concatenate(outs, axis=1)

    def partition_rules(self):
        if self.config.relative_encoding:
            # rules are re.search'd against full paths, so the XL rules
            # match under the "backbone/" prefix unchanged
            from fengshen_tpu.models.transfo_xl_denoise \
                .modeling_transfo_xl import XL_PARTITION_RULES
            return XL_PARTITION_RULES
        from fengshen_tpu.models.gpt2.modeling_gpt2 import PARTITION_RULES
        return PARTITION_RULES


@dataclass
class DenoiseCollator:
    """Corrupt → reconstruct pairs (reference: transfo_xl_denoise's
    denoising objective): token dropout + local shuffling on the source,
    loss on reconstructing the original after a separator."""

    tokenizer: Any
    max_seq_length: int = 512
    drop_prob: float = 0.15
    shuffle_window: int = 3
    seed: int = 42
    content_key: str = "text"

    def __post_init__(self):
        self.rng = np.random.RandomState(self.seed)

    def corrupt(self, ids: list[int]) -> list[int]:
        keep = [t for t in ids if self.rng.random() > self.drop_prob]
        if not keep:
            keep = ids[:1]
        out = list(keep)
        for i in range(0, len(out) - self.shuffle_window,
                       self.shuffle_window):
            window = out[i:i + self.shuffle_window]
            self.rng.shuffle(window)
            out[i:i + self.shuffle_window] = window
        return out

    def __call__(self, samples: list[dict]) -> dict:
        sep = self.tokenizer.sep_token_id or self.tokenizer.eos_token_id or 0
        pad = self.tokenizer.pad_token_id or 0
        batch = {"input_ids": [], "attention_mask": [], "labels": []}
        half = self.max_seq_length // 2
        for s in samples:
            text = s[self.content_key] if isinstance(s, dict) else s
            ids = self.tokenizer.encode(text, add_special_tokens=False
                                        )[: half - 1]
            src = self.corrupt(ids)[: half - 1]
            seq = src + [sep] + ids
            labels = [-100] * (len(src) + 1) + ids
            p = self.max_seq_length - len(seq)
            batch["input_ids"].append(seq + [pad] * p)
            batch["attention_mask"].append([1] * len(seq) + [0] * p)
            batch["labels"].append(labels + [-100] * p)
        return {k: np.asarray(v) for k, v in batch.items()}
