"""torch → jax weights for the Transformer-XL families.

One importer for all three published checkpoint families — denoise
("Bigan"), paraphrase, reasoning — which share the single
TransfoXLDenoiseModel backbone (reference:
fengshen/models/transfo_xl_paraphrase/__init__.py:1 and
transfo_xl_reasoning/__init__.py:2 both re-export it).

Reference state-dict naming (modeling_transfo_xl_denoise.py:681-704):
`word_embeddings.weight` (tied output head), `transformer.r_w_bias` /
`transformer.r_r_bias` (shared across layers),
`transformer.layers.{i}.{input_layernorm, attention.query_key_value,
attention.relative, attention.dense, post_attention_layernorm,
mlp.dense_h_to_4h, mlp.dense_4h_to_h}`, `transformer.final_layernorm`.
"""

from __future__ import annotations

from typing import Any, Mapping

from fengshen_tpu.utils.convert_common import (make_helpers,
                                               unwrap_lightning)


def torch_to_params(state_dict: Mapping[str, Any], config) -> dict:
    """Returns {"backbone": <TransfoXLModel params>} matching
    `TransfoXLDenoiseModel(config with relative_encoding=True)`."""
    sd = unwrap_lightning(state_dict)
    t, lin, ln = make_helpers(sd)

    n_layers = getattr(config, "num_layers", None) or config.n_layer
    backbone: dict = {
        "word_embeddings": {"embedding": t("word_embeddings.weight")},
        "r_w_bias": t("transformer.r_w_bias"),
        "r_r_bias": t("transformer.r_r_bias"),
        "final_layernorm": ln("transformer.final_layernorm"),
    }
    for i in range(n_layers):
        pre = f"transformer.layers.{i}"
        backbone[f"layer_{i}"] = {
            "input_layernorm": ln(f"{pre}.input_layernorm"),
            "attention": {
                "query_key_value": lin(f"{pre}.attention.query_key_value"),
                "relative": lin(f"{pre}.attention.relative"),
                "dense": lin(f"{pre}.attention.dense"),
            },
            "post_attention_layernorm": ln(
                f"{pre}.post_attention_layernorm"),
            "dense_h_to_4h": lin(f"{pre}.mlp.dense_h_to_4h"),
            "dense_4h_to_h": lin(f"{pre}.mlp.dense_4h_to_h"),
        }
    return {"backbone": backbone}


#: fs→torch export: derived exact inverse of `torch_to_params`
#: (template_state = the source checkpoint: dict, Lightning ckpt, or dir)
from fengshen_tpu.utils.convert_common import (  # noqa: E402
    make_derived_export)

params_to_torch_state = make_derived_export(torch_to_params)
