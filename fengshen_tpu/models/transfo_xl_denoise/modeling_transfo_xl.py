"""Transformer-XL backbone with relative position encoding.

Faithful flax port of the reference's GLM-style GPT2Transformer
(reference: fengshen/models/transfo_xl_denoise/
modeling_transfo_xl_denoise.py — PositionalEmbedding :106-122, fused-qkv
relative attention with _rel_shift :190-340, pre-LN layer :370-470,
transformer + memory :520-660, tied output head :681-770). The published
Bigan/Transformer-XL checkpoints (denoise / paraphrase / reasoning, all
three families share this one backbone per the reference __init__ files)
are trained with relative_encoding=True, so this module is the import
target; the attention is MXU-dense (one fused qkv matmul + two batched
matmuls per layer) and the rel-shift is a static gather, so XLA fuses the
whole layer.

Memory (the XL segment recurrence) is a per-layer list of past hidden
states with static length, attended as read-only keys — pass `mems` and
collect `new_mems` exactly like the reference's update_mems.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from fengshen_tpu.sharding import (to_partition_rules,
                                   with_logical_constraint)


@dataclasses.dataclass
class TransfoXLConfig:
    """Field names follow the reference configuration
    (configuration_transfo_xl_denoise.py:91-118; published 1.1B:
    32 layers, hidden 1600, 25 heads, vocab 50048)."""

    vocab_size: int = 50048
    hidden_size: int = 1600
    num_layers: int = 32
    num_attention_heads: int = 25
    max_sequence_length: int = 512
    max_memory_length: int = 512
    embedding_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    output_dropout_prob: float = 0.1
    layernorm_epsilon: float = 1e-5
    relative_encoding: bool = True
    initializer_range: float = 0.02
    dtype: str = "float32"
    param_dtype: str = "float32"

    @classmethod
    def small_test_config(cls, **overrides: Any) -> "TransfoXLConfig":
        base = dict(vocab_size=128, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_sequence_length=64,
                    max_memory_length=16)
        base.update(overrides)
        return cls(**base)


def xl_positional_embedding(pos_seq: jnp.ndarray,
                            hidden_size: int) -> jnp.ndarray:
    """[sin | cos] concat over inv_freq = 10000^(-2i/H) (reference
    PositionalEmbedding :106-122). pos_seq is DESCENDING key distances."""
    inv_freq = 1.0 / (10000 ** (np.arange(0, hidden_size, 2,
                                          dtype=np.float32) /
                                hidden_size))
    ang = pos_seq[:, None] * jnp.asarray(inv_freq)[None, :]
    # keep the sin|cos concat replicated: GSPMD must never turn it into
    # a sharded matmul contraction (docs/sharding.md "Root cause")
    return with_logical_constraint(
        jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=-1),
        ("seq", "relpos"))


def rel_shift(bd: jnp.ndarray) -> jnp.ndarray:
    """The reference's pad-reshape-slice `_rel_shift` (:234-249), verbatim
    in jnp — pure reshapes, so XLA lowers it to a layout change."""
    batch, n_head, qlen, klen = bd.shape
    zero_pad = jnp.zeros((batch, n_head, qlen, 1), bd.dtype)
    padded = jnp.concatenate([zero_pad, bd], axis=-1)
    padded = padded.reshape(batch, n_head, klen + 1, qlen)
    return padded[:, :, 1:, :].reshape(batch, n_head, qlen, klen)


class XLSelfAttention(nn.Module):
    """Fused-qkv relative attention (reference GPT2SelfAttention
    :190-340). r_w/r_r biases are shared across layers and passed in."""

    config: TransfoXLConfig

    @nn.compact
    def __call__(self, hidden, ltor_mask, pos_emb, r_w_bias, r_r_bias,
                 mem=None, deterministic=True):
        cfg = self.config
        batch, qlen, h = hidden.shape
        n_head = cfg.num_attention_heads
        hd = h // n_head
        dt = jnp.dtype(cfg.dtype)

        cat = hidden if mem is None else jnp.concatenate([mem, hidden], 1)
        klen = cat.shape[1]
        qkv = nn.Dense(3 * h, dtype=dt,
                       param_dtype=jnp.dtype(cfg.param_dtype),
                       kernel_init=nn.initializers.normal(
                           cfg.initializer_range),
                       name="query_key_value")(cat)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q[:, -qlen:]

        def heads(t):
            return t.reshape(batch, t.shape[1], n_head, hd).transpose(
                0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)

        # relative projection of the positional basis (klen rows)
        rel = nn.Dense(h, dtype=dt,
                       param_dtype=jnp.dtype(cfg.param_dtype),
                       kernel_init=nn.initializers.normal(
                           cfg.initializer_range),
                       name="relative")(pos_emb)
        rel = rel.reshape(klen, n_head, hd).transpose(1, 0, 2)  # [n, k, d]

        ac = jnp.einsum("bnqd,bnkd->bnqk",
                        q + r_w_bias[None, :, None].astype(q.dtype), k,
                        preferred_element_type=jnp.float32)
        bd = jnp.einsum("bnqd,nkd->bnqk",
                        q + r_r_bias[None, :, None].astype(q.dtype), rel,
                        preferred_element_type=jnp.float32)
        bd = rel_shift(bd)

        scores = (ac + bd) / jnp.sqrt(jnp.asarray(hd, jnp.float32))
        mask = ltor_mask.astype(scores.dtype)
        scores = scores * mask - 10000.0 * (1.0 - mask)
        probs = jax.nn.softmax(scores, axis=-1)
        probs = nn.Dropout(cfg.attention_dropout_prob)(
            probs, deterministic=deterministic)
        ctx = jnp.einsum("bnqk,bnkd->bnqd", probs.astype(v.dtype), v)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(batch, qlen, h)
        out = nn.Dense(h, dtype=dt,
                       param_dtype=jnp.dtype(cfg.param_dtype),
                       kernel_init=nn.initializers.normal(
                           cfg.initializer_range /
                           np.sqrt(2.0 * cfg.num_layers)),
                       name="dense")(ctx)
        return nn.Dropout(cfg.output_dropout_prob)(
            out, deterministic=deterministic)


class XLLayer(nn.Module):
    """Pre-LN layer (reference GPT2TransformerLayer :370-470): the memory
    is normalised with the SAME input_layernorm before attention."""

    config: TransfoXLConfig

    @nn.compact
    def __call__(self, hidden, ltor_mask, pos_emb, r_w_bias, r_r_bias,
                 mem=None, deterministic=True):
        cfg = self.config
        h = cfg.hidden_size
        dt = jnp.dtype(cfg.dtype)
        ln_in = nn.LayerNorm(epsilon=cfg.layernorm_epsilon, dtype=dt,
                             name="input_layernorm")
        x = ln_in(hidden)
        m = ln_in(mem) if mem is not None else None
        attn = XLSelfAttention(cfg, name="attention")(
            x, ltor_mask, pos_emb, r_w_bias, r_r_bias, m, deterministic)
        hidden = hidden + attn
        y = nn.LayerNorm(epsilon=cfg.layernorm_epsilon, dtype=dt,
                         name="post_attention_layernorm")(hidden)
        mid = nn.Dense(4 * h, dtype=dt,
                       param_dtype=jnp.dtype(cfg.param_dtype),
                       kernel_init=nn.initializers.normal(
                           cfg.initializer_range),
                       name="dense_h_to_4h")(y)
        # OpenAI tanh gelu (reference gelu_impl :156-162)
        mid = jax.nn.gelu(mid, approximate=True)
        out = nn.Dense(h, dtype=dt,
                       param_dtype=jnp.dtype(cfg.param_dtype),
                       kernel_init=nn.initializers.normal(
                           cfg.initializer_range /
                           np.sqrt(2.0 * cfg.num_layers)),
                       name="dense_4h_to_h")(mid)
        out = nn.Dropout(cfg.output_dropout_prob)(
            out, deterministic=deterministic)
        return hidden + out


class TransfoXLModel(nn.Module):
    """Word embeddings + relative transformer + tied output head
    (reference TransfoXLDenoiseModel :681-770). Returns (logits,
    new_mems); feed `mems` (list of [B, M, H], one per layer) for the XL
    segment recurrence.

    With `latent_size > 0` the model is the reference's
    GPT2ModelForLatent (DAVAE/GPT2ModelForLatent.py:500-575): `latent`
    [B, latent_size] is projected by a bias-free `linear_emb` and added
    after the embedding and after EVERY layer."""

    config: TransfoXLConfig
    latent_size: int = 0

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, mems=None,
                 latent=None, deterministic=True):
        cfg = self.config
        batch, qlen = input_ids.shape
        mem_len = mems[0].shape[1] if mems else 0
        klen = qlen + mem_len

        wte = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                       dtype=jnp.dtype(cfg.dtype),
                       param_dtype=jnp.dtype(cfg.param_dtype),
                       embedding_init=nn.initializers.normal(
                           cfg.initializer_range),
                       name="word_embeddings")
        hidden = wte(input_ids)
        latent_emb = None
        if self.latent_size > 0:
            assert latent is not None, "latent_size>0 requires `latent`"
            latent_emb = nn.Dense(cfg.hidden_size, use_bias=False,
                                  name="linear_emb")(latent)[:, None, :]
            hidden = hidden + latent_emb.astype(hidden.dtype)

        # causal mask over memory+current keys: query i attends keys
        # <= mem_len + i; multiplied by any padding mask
        ltor = jnp.tril(jnp.ones((qlen, klen), jnp.float32),
                        k=mem_len)[None, None]
        if attention_mask is not None:
            if attention_mask.ndim == 2:  # [B, S] padding mask
                pad = jnp.concatenate(
                    [jnp.ones((batch, mem_len), attention_mask.dtype),
                     attention_mask], axis=1)
                ltor = ltor * pad[:, None, None, :]
            else:
                ltor = attention_mask

        # descending key distances (reference :588-591)
        pos_seq = jnp.arange(klen - 1, -1, -1, dtype=jnp.float32)
        pos_emb = xl_positional_embedding(pos_seq, cfg.hidden_size)
        pos_emb = nn.Dropout(cfg.embedding_dropout_prob)(
            pos_emb, deterministic=deterministic)
        hidden = nn.Dropout(cfg.embedding_dropout_prob)(
            hidden, deterministic=deterministic)

        n_head = cfg.num_attention_heads
        hd = cfg.hidden_size // n_head
        r_w_bias = self.param("r_w_bias", nn.initializers.zeros,
                              (n_head, hd), jnp.float32)
        r_r_bias = self.param("r_r_bias", nn.initializers.zeros,
                              (n_head, hd), jnp.float32)

        new_mems = []
        mem_keep = cfg.max_memory_length
        for i in range(cfg.num_layers):
            if mem_keep > 0:
                prev = hidden if mems is None else jnp.concatenate(
                    [mems[i], hidden], axis=1)
                new_mems.append(
                    jax.lax.stop_gradient(prev[:, -mem_keep:]))
            mem_i = mems[i] if mems else None
            hidden = XLLayer(cfg, name=f"layer_{i}")(
                hidden, ltor, pos_emb, r_w_bias, r_r_bias, mem_i,
                deterministic)
            if latent_emb is not None:
                hidden = hidden + latent_emb.astype(hidden.dtype)
        hidden = nn.LayerNorm(epsilon=cfg.layernorm_epsilon,
                              dtype=jnp.dtype(cfg.dtype),
                              name="final_layernorm")(hidden)
        logits = hidden @ wte.embedding.T.astype(hidden.dtype)
        return logits, new_mems

    def partition_rules(self):
        # resolved at call time so a `use_rules` scope takes effect
        return to_partition_rules(XL_PARAM_LOGICAL_AXES)


#: Logical-axis annotations (docs/sharding.md). The fused qkv is
#: column-parallel on its OUTPUT (heads) dim — the head split happens
#: after the matmul, so sharding the 3h output dim over `heads` IS the
#: split-heads-before-the-shard Megatron layout (each tensor shard
#: holds whole heads of each of q/k/v). `relative` must be
#: column-parallel too: its input is the sin|cos positional concat,
#: and a concatenate consumed through a sharded matmul contraction
#: mispartitions on this XLA build (the NOTES.md item 4 root cause,
#: docs/sharding.md "Root cause") — hence `relpos` (→ None), never
#: `embed`, on its contraction dim.
XL_PARAM_LOGICAL_AXES: list[tuple[str, tuple]] = [
    (r"word_embeddings/embedding", ("vocab", "embed")),
    (r"layer_\d+/attention/query_key_value/kernel", ("embed", "heads")),
    (r"layer_\d+/attention/relative/kernel", ("relpos", "heads")),
    (r"layer_\d+/attention/dense/kernel", ("heads", "embed")),
    (r"layer_\d+/dense_h_to_4h/kernel", ("embed", "mlp")),
    (r"layer_\d+/dense_4h_to_h/kernel", ("mlp", "embed")),
    (r".*", (None,)),
]

XL_PARTITION_RULES = to_partition_rules(XL_PARAM_LOGICAL_AXES)
