"""Transfo-XL denoise / Bigan family (reference:
fengshen/models/transfo_xl_denoise/ — denoising AE over a GPT2-XL-scale
backbone with segment-level recurrence for long text)."""

from fengshen_tpu.models.transfo_xl_denoise.modeling_transfo_xl_denoise \
    import (TransfoXLDenoiseConfig, TransfoXLDenoiseModel,
            DenoiseCollator)
from fengshen_tpu.models.transfo_xl_denoise.modeling_transfo_xl import (
    TransfoXLConfig, TransfoXLModel)

__all__ = ["TransfoXLDenoiseConfig", "TransfoXLDenoiseModel",
           "DenoiseCollator", "TransfoXLConfig", "TransfoXLModel"]
