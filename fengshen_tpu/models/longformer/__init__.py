"""Longformer family (reference: fengshen/models/longformer/ — sliding
window + global attention with RoPE for long-doc Chinese NLU, 2,572 LoC)."""

from fengshen_tpu.models.longformer.modeling_longformer import (
    LongformerConfig, LongformerModel, LongformerForMaskedLM,
    LongformerForSequenceClassification)

__all__ = ["LongformerConfig", "LongformerModel", "LongformerForMaskedLM",
           "LongformerForSequenceClassification"]

from fengshen_tpu.models.longformer.task_heads import (LongformerForTokenClassification, LongformerForQuestionAnswering, LongformerForMultipleChoice)
__all__ += ['LongformerForTokenClassification', 'LongformerForQuestionAnswering', 'LongformerForMultipleChoice']
