"""torch(HF) → jax weights for Longformer.

Importer for released Erlangshen-Longformer checkpoints (the reference
family loads HF-format state dicts,
reference: fengshen/models/longformer/modeling_longformer.py).
"""

from __future__ import annotations

from typing import Any, Mapping

from fengshen_tpu.models.longformer.modeling_longformer import (
    LongformerConfig)
from fengshen_tpu.utils.convert_common import make_helpers


def torch_to_params(state_dict: Mapping[str, Any],
                    config: LongformerConfig) -> dict:
    t, lin, ln = make_helpers(state_dict)

    pos = t("longformer.embeddings.position_embeddings.weight")
    if pos.shape[0] == config.max_position_embeddings + 2:
        # RoBERTa-style checkpoints reserve positions 0/1 for padding
        pos = pos[2:]

    def layer(i):
        p = f"longformer.encoder.layer.{i}"
        out = {
            "self": {
                "query": lin(f"{p}.attention.self.query"),
                "key": lin(f"{p}.attention.self.key"),
                "value": lin(f"{p}.attention.self.value"),
                "query_global": lin(f"{p}.attention.self.query_global"),
                "key_global": lin(f"{p}.attention.self.key_global"),
                "value_global": lin(f"{p}.attention.self.value_global"),
            },
            "attention_output_dense": lin(f"{p}.attention.output.dense"),
            "attention_ln": ln(f"{p}.attention.output.LayerNorm"),
            "intermediate_dense": lin(f"{p}.intermediate.dense"),
            "output_dense": lin(f"{p}.output.dense"),
            "output_ln": ln(f"{p}.output.LayerNorm"),
        }
        return out

    lf = {
        "word_embeddings": {
            "embedding": t("longformer.embeddings.word_embeddings.weight")},
        "token_type_embeddings": {
            "embedding":
                t("longformer.embeddings.token_type_embeddings.weight")},
        "embeddings_ln": ln("longformer.embeddings.LayerNorm"),
    }
    if not config.use_rotary:
        lf["position_embeddings"] = {"embedding": pos}
    for i in range(config.num_hidden_layers):
        lf[f"layer_{i}"] = layer(i)
    if "longformer.pooler.dense.weight" in state_dict:
        lf["pooler"] = lin("longformer.pooler.dense")

    params: dict = {"longformer": lf}
    if "lm_head.dense.weight" in state_dict:
        params["transform_dense"] = lin("lm_head.dense")
        params["transform_ln"] = ln("lm_head.layer_norm")
        params["bias"] = t("lm_head.bias")
    return params


#: fs→torch export: derived exact inverse of `torch_to_params`
#: (template_state = the source checkpoint: dict, Lightning ckpt, or dir)
from fengshen_tpu.utils.convert_common import (  # noqa: E402
    make_derived_export)

params_to_torch_state = make_derived_export(torch_to_params)
