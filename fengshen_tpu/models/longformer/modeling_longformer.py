"""Longformer in flax.

Reference: fengshen/models/longformer/modeling_longformer.py — BERT encoder
whose attention is sliding-window local + designated global tokens, the
reference's long-document NLU answer (SURVEY.md §5.7). Semantics:

- local: token i attends j iff |i-j| ≤ window//2;
- global tokens (from `global_attention_mask`) attend everywhere and are
  attended by everyone, through SEPARATE global q/k/v projections for the
  global-query rows (HF convention).

This implementation expresses the pattern as a mask over dense attention —
on TPU the MXU makes dense-with-mask the right baseline; the block-sparse
layouts in ops.masks + Pallas flash cover the truly long regime. The
reference fork also adds RoPE (`RoPEmbedding`); enabled via
`use_rotary=True` (the Erlangshen-Longformer variant).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from fengshen_tpu.ops.activations import get_activation
from fengshen_tpu.ops.masks import sliding_window_mask
from fengshen_tpu.ops.norms import LayerNorm
from fengshen_tpu.ops.rotary import apply_rotary_pos_emb
from fengshen_tpu.parallel.mesh import BATCH_AXES
from fengshen_tpu.parallel.partition import with_sharding_constraint

PARTITION_RULES: list[tuple[str, P]] = [
    ("word_embeddings/embedding", P("tensor", None)),
    (r"(query|key|value|query_global|key_global|value_global|"
     r"intermediate_dense)/kernel", P("fsdp", "tensor")),
    (r"(attention_output_dense|output_dense)/kernel", P("tensor", "fsdp")),
    (".*", P(None)),
]


@dataclasses.dataclass
class LongformerConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 4096
    type_vocab_size: int = 2
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    attention_window: int = 512
    use_rotary: bool = False  # Erlangshen fork adds RoPE
    pad_token_id: int = 0
    num_labels: int = 2
    dtype: str = "bfloat16"
    param_dtype: str = "float32"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def from_pretrained(cls, path: str) -> "LongformerConfig":
        cfg_file = os.path.join(path, "config.json") if os.path.isdir(path) \
            else path
        with open(cfg_file) as f:
            raw = json.load(f)
        known = {f.name for f in dataclasses.fields(cls)}
        if isinstance(raw.get("attention_window"), list):
            raw["attention_window"] = raw["attention_window"][0]
        return cls(**{k: v for k, v in raw.items() if k in known})

    @classmethod
    def small_test_config(cls, **overrides: Any) -> "LongformerConfig":
        base = dict(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=64,
                    max_position_embeddings=64, attention_window=8)
        base.update(overrides)
        return cls(**base)


def _dt(cfg):
    return jnp.dtype(cfg.dtype)


def _dense(cfg, feats, name):
    return nn.Dense(feats, dtype=_dt(cfg),
                    param_dtype=jnp.dtype(cfg.param_dtype),
                    kernel_init=nn.initializers.normal(
                        cfg.initializer_range), name=name)


class LongformerSelfAttention(nn.Module):
    config: LongformerConfig

    @nn.compact
    def __call__(self, hidden, attention_mask=None,
                 global_attention_mask=None, deterministic=True):
        cfg = self.config
        batch, seq, _ = hidden.shape
        n_head, head_dim = cfg.num_attention_heads, cfg.head_dim

        def qkv(prefix):
            q = _dense(cfg, cfg.hidden_size, f"query{prefix}")(hidden)
            k = _dense(cfg, cfg.hidden_size, f"key{prefix}")(hidden)
            v = _dense(cfg, cfg.hidden_size, f"value{prefix}")(hidden)
            shape = (batch, seq, n_head, head_dim)
            q, k, v = (x.reshape(shape) for x in (q, k, v))
            if cfg.use_rotary:
                pos = jnp.arange(seq)[None]
                q, k = apply_rotary_pos_emb(q, k, pos)
            return q, k, v

        q, k, v = qkv("")
        qg, kg, vg = qkv("_global")

        half = cfg.attention_window // 2
        local = sliding_window_mask(seq, half + 1, causal=False)  # |i-j|<=half
        valid = jnp.ones((batch, seq), bool) if attention_mask is None \
            else attention_mask.astype(bool)
        if global_attention_mask is None:
            is_global = jnp.zeros((batch, seq), bool)
        else:
            is_global = global_attention_mask.astype(bool) & valid

        # pattern: local OR column-global (everyone sees global keys);
        # global-query rows handled separately below
        mask = local[None] | is_global[:, None, :]
        mask = mask & valid[:, None, :] & valid[:, :, None]
        bias = jnp.where(mask[:, None], 0.0, -1e9)

        scale = 1.0 / jnp.sqrt(jnp.asarray(head_dim, jnp.float32))
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                            preferred_element_type=jnp.float32) * scale
        probs = jax.nn.softmax(scores + bias, axis=-1)
        out_local = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)

        # global queries: full attention with the global projections
        g_scores = jnp.einsum("bqhd,bkhd->bhqk", qg, kg,
                              preferred_element_type=jnp.float32) * scale
        g_bias = jnp.where(valid[:, None, None, :], 0.0, -1e9)
        g_probs = jax.nn.softmax(g_scores + g_bias, axis=-1)
        out_global = jnp.einsum("bhqk,bkhd->bqhd",
                                g_probs.astype(vg.dtype), vg)

        out = jnp.where(is_global[:, :, None, None], out_global, out_local)
        out = with_sharding_constraint(
            out, P(BATCH_AXES, "sequence", "tensor", None))
        return out.reshape(batch, seq, cfg.hidden_size)


class LongformerLayer(nn.Module):
    config: LongformerConfig

    @nn.compact
    def __call__(self, hidden, attention_mask=None,
                 global_attention_mask=None, deterministic=True):
        cfg = self.config
        h = LongformerSelfAttention(cfg, name="self")(
            hidden, attention_mask, global_attention_mask, deterministic)
        h = _dense(cfg, cfg.hidden_size, "attention_output_dense")(h)
        h = nn.Dropout(cfg.hidden_dropout_prob)(h,
                                                deterministic=deterministic)
        hidden = LayerNorm(epsilon=cfg.layer_norm_eps,
                           name="attention_ln")(hidden + h)
        h = _dense(cfg, cfg.intermediate_size, "intermediate_dense")(hidden)
        h = get_activation(cfg.hidden_act)(h)
        h = with_sharding_constraint(h, P(BATCH_AXES, "sequence", "tensor"))
        h = _dense(cfg, cfg.hidden_size, "output_dense")(h)
        h = nn.Dropout(cfg.hidden_dropout_prob)(h,
                                                deterministic=deterministic)
        return LayerNorm(epsilon=cfg.layer_norm_eps,
                         name="output_ln")(hidden + h)


class LongformerModel(nn.Module):
    config: LongformerConfig
    add_pooling_layer: bool = True

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 global_attention_mask=None, position_ids=None,
                 deterministic=True):
        cfg = self.config
        batch, seq = input_ids.shape
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        hidden = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=_dt(cfg),
                          param_dtype=jnp.dtype(cfg.param_dtype),
                          embedding_init=nn.initializers.normal(
                              cfg.initializer_range),
                          name="word_embeddings")(input_ids)
        if not cfg.use_rotary:
            if position_ids is None:
                position_ids = jnp.arange(seq)[None]
            hidden = hidden + nn.Embed(
                cfg.max_position_embeddings, cfg.hidden_size,
                dtype=_dt(cfg), param_dtype=jnp.dtype(cfg.param_dtype),
                embedding_init=nn.initializers.normal(
                    cfg.initializer_range),
                name="position_embeddings")(position_ids)
        hidden = hidden + nn.Embed(
            cfg.type_vocab_size, cfg.hidden_size, dtype=_dt(cfg),
            param_dtype=jnp.dtype(cfg.param_dtype),
            embedding_init=nn.initializers.normal(cfg.initializer_range),
            name="token_type_embeddings")(token_type_ids)
        hidden = LayerNorm(epsilon=cfg.layer_norm_eps,
                           name="embeddings_ln")(hidden)
        hidden = nn.Dropout(cfg.hidden_dropout_prob)(
            hidden, deterministic=deterministic)
        for i in range(cfg.num_hidden_layers):
            hidden = LongformerLayer(cfg, name=f"layer_{i}")(
                hidden, attention_mask, global_attention_mask,
                deterministic)
        pooled = None
        if self.add_pooling_layer:
            pooled = jnp.tanh(_dense(cfg, cfg.hidden_size,
                                     "pooler")(hidden[:, 0]))
        return hidden, pooled

    def partition_rules(self):
        return PARTITION_RULES


class LongformerForMaskedLM(nn.Module):
    config: LongformerConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 global_attention_mask=None, deterministic=True):
        cfg = self.config
        hidden, _ = LongformerModel(cfg, add_pooling_layer=False,
                                    name="longformer")(
            input_ids, attention_mask, token_type_ids,
            global_attention_mask, deterministic=deterministic)
        h = _dense(cfg, cfg.hidden_size, "transform_dense")(hidden)
        h = get_activation(cfg.hidden_act)(h)
        h = LayerNorm(epsilon=cfg.layer_norm_eps, name="transform_ln")(h)
        wte = self.variables["params"]["longformer"]["word_embeddings"][
            "embedding"]
        logits = h @ wte.T.astype(h.dtype)
        bias = self.param("bias", nn.initializers.zeros,
                          (cfg.vocab_size,), jnp.dtype(cfg.param_dtype))
        return logits + bias

    def partition_rules(self):
        return PARTITION_RULES


class LongformerForSequenceClassification(nn.Module):
    config: LongformerConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 global_attention_mask=None, deterministic=True):
        cfg = self.config
        _, pooled = LongformerModel(cfg, name="longformer")(
            input_ids, attention_mask, token_type_ids,
            global_attention_mask, deterministic=deterministic)
        pooled = nn.Dropout(cfg.hidden_dropout_prob)(
            pooled, deterministic=deterministic)
        return _dense(cfg, cfg.num_labels, "classifier")(pooled)

    def partition_rules(self):
        return PARTITION_RULES
