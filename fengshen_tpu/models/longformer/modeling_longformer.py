"""Longformer in flax.

Reference: fengshen/models/longformer/modeling_longformer.py — BERT encoder
whose attention is sliding-window local + designated global tokens, the
reference's long-document NLU answer (SURVEY.md §5.7). Semantics:

- local: token i attends j iff |i-j| ≤ window//2;
- global tokens (from `global_attention_mask`) attend everywhere and are
  attended by everyone, through SEPARATE global q/k/v projections for the
  global-query rows (HF convention).

This implementation expresses the pattern as a mask over dense attention —
on TPU the MXU makes dense-with-mask the right baseline; the block-sparse
layouts in ops.masks + Pallas flash cover the truly long regime. The
reference fork also adds RoPE (`RoPEmbedding`); enabled via
`use_rotary=True` (the Erlangshen-Longformer variant).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from fengshen_tpu.ops.activations import get_activation
from fengshen_tpu.ops.embedding import VocabParallelEmbed
from fengshen_tpu.ops.masks import sliding_window_mask
from fengshen_tpu.ops.norms import LayerNorm
from fengshen_tpu.ops.rotary import apply_rotary_pos_emb
from fengshen_tpu.sharding import (to_partition_rules,
                                    with_logical_constraint)

PARAM_LOGICAL_AXES: list[tuple[str, tuple]] = [
    ("word_embeddings/embedding", ("vocab", None)),
    (r"(query|key|value|query_global|key_global|value_global)/kernel",
     ("embed", "heads")),
    (r"intermediate_dense/kernel", ("embed", "mlp")),
    (r"attention_output_dense/kernel", ("heads", "embed")),
    (r"output_dense/kernel", ("mlp", "embed")),
    (".*", (None,)),
]
PARTITION_RULES = to_partition_rules(PARAM_LOGICAL_AXES)


@dataclasses.dataclass
class LongformerConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 4096
    type_vocab_size: int = 2
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    attention_window: int = 512
    max_global_tokens: int = 64  # static cap on gathered global positions
    use_rotary: bool = False  # Erlangshen fork adds RoPE
    pad_token_id: int = 0
    num_labels: int = 2
    dtype: str = "bfloat16"
    param_dtype: str = "float32"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def from_pretrained(cls, path: str) -> "LongformerConfig":
        cfg_file = os.path.join(path, "config.json") if os.path.isdir(path) \
            else path
        with open(cfg_file) as f:
            raw = json.load(f)
        known = {f.name for f in dataclasses.fields(cls)}
        if isinstance(raw.get("attention_window"), list):
            raw["attention_window"] = raw["attention_window"][0]
        return cls(**{k: v for k, v in raw.items() if k in known})

    @classmethod
    def small_test_config(cls, **overrides: Any) -> "LongformerConfig":
        base = dict(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=64,
                    max_position_embeddings=64, attention_window=8)
        base.update(overrides)
        return cls(**base)


def _dt(cfg):
    return jnp.dtype(cfg.dtype)


def _dense(cfg, feats, name):
    return nn.Dense(feats, dtype=_dt(cfg),
                    param_dtype=jnp.dtype(cfg.param_dtype),
                    kernel_init=nn.initializers.normal(
                        cfg.initializer_range), name=name)


class LongformerSelfAttention(nn.Module):
    """Banded (chunked) sliding-window attention + global tokens.

    Memory scales O(S·w + S·G) — the banded part computes each query chunk
    against only its 3 neighbouring key chunks (the HF/reference chunking
    trick, reference: fengshen/models/longformer/modeling_longformer.py
    `_sliding_chunks_query_key_matmul`), and global-query rows are computed
    only for the G gathered global positions — the full [S, S] score matrix
    of a dense-with-mask formulation is never materialised (VERDICT r1
    weak #6).

    Semantics (identical to the previous dense formulation):
    - local: token i attends j iff |i-j| ≤ window//2 (local projections);
    - column-global: every token also attends all global keys (local k/v,
      the HF convention);
    - global-query rows do FULL attention through the separate global
      q/k/v projections.
    """

    config: LongformerConfig

    @nn.compact
    def __call__(self, hidden, attention_mask=None,
                 global_attention_mask=None, deterministic=True):
        cfg = self.config
        batch, seq, _ = hidden.shape
        n_head, head_dim = cfg.num_attention_heads, cfg.head_dim

        def qkv(prefix):
            q = _dense(cfg, cfg.hidden_size, f"query{prefix}")(hidden)
            k = _dense(cfg, cfg.hidden_size, f"key{prefix}")(hidden)
            v = _dense(cfg, cfg.hidden_size, f"value{prefix}")(hidden)
            shape = (batch, seq, n_head, head_dim)
            q, k, v = (x.reshape(shape) for x in (q, k, v))
            if cfg.use_rotary:
                pos = jnp.arange(seq)[None]
                q, k = apply_rotary_pos_emb(q, k, pos)
            return q, k, v

        q, k, v = qkv("")
        qg, kg, vg = qkv("_global")

        half = max(cfg.attention_window // 2, 1)
        valid = jnp.ones((batch, seq), bool) if attention_mask is None \
            else attention_mask.astype(bool)
        if global_attention_mask is None:
            is_global = jnp.zeros((batch, seq), bool)
        else:
            is_global = global_attention_mask.astype(bool) & valid

        # -- gather up to G global positions (static shape for XLA) --------
        # Overflow beyond the cap degrades gracefully: ungathered global
        # tokens stay ordinary local tokens (kept in the band, local-row
        # output) instead of being silently dropped.
        G = min(cfg.max_global_tokens, seq)
        pos = jnp.arange(seq)[None, :]
        sort_key = jnp.where(is_global, pos, seq + pos)
        g_idx = jnp.argsort(sort_key, axis=1)[:, :G]          # [B, G]
        bidx = jnp.arange(batch)[:, None]
        g_valid = jnp.take_along_axis(is_global, g_idx, 1)    # [B, G]
        # positions actually covered by the column-global/global-row paths
        is_gathered = jnp.zeros((batch, seq), bool).at[bidx, g_idx].set(
            g_valid)

        scale = 1.0 / jnp.sqrt(jnp.asarray(head_dim, jnp.float32))

        # -- banded local scores: chunk q, band k over 3 adjacent chunks ---
        c = half
        pad = (c - seq % c) % c
        n_chunks = (seq + pad) // c
        qp = jnp.pad(q, ((0, 0), (0, pad), (0, 0), (0, 0)))
        kp = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        vp = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
        qc = qp.reshape(batch, n_chunks, c, n_head, head_dim)

        def band(x):
            """[B, S_p, ...] → [B, nc, 3c, ...] (prev | self | next chunk)."""
            xpad = jnp.pad(x, ((0, 0), (c, c)) + ((0, 0),) * (x.ndim - 2))
            xc = xpad.reshape((batch, n_chunks + 2, c) + x.shape[2:])
            return jnp.concatenate(
                [xc[:, :-2], xc[:, 1:-1], xc[:, 2:]], axis=2)

        k3 = band(kp)                                  # [B, nc, 3c, H, D]
        v3 = band(vp)
        band_scores = jnp.einsum(
            "bnqhd,bnkhd->bhnqk", qc, k3,
            preferred_element_type=jnp.float32) * scale  # [B,H,nc,c,3c]

        q_pos = (jnp.arange(n_chunks)[:, None] * c +
                 jnp.arange(c)[None, :])                       # [nc, c]
        k_pos = (jnp.arange(n_chunks)[:, None] * c - c +
                 jnp.arange(3 * c)[None, :])                   # [nc, 3c]
        within = jnp.abs(q_pos[:, :, None] - k_pos[:, None, :]) <= half
        in_range = (k_pos >= 0) & (k_pos < seq)
        # key validity / global-ness gathered in band form
        kv_flags = jnp.stack([valid, is_gathered], -1).astype(jnp.int8)
        kv_flags = jnp.pad(kv_flags, ((0, 0), (0, pad), (0, 0)))
        flags3 = band(kv_flags)                         # [B, nc, 3c, 2]
        k_valid3 = flags3[..., 0].astype(bool)
        k_global3 = flags3[..., 1].astype(bool)
        # gathered global keys are excluded from the band: the column-global
        # part below covers them (exact union, no double counting)
        band_allowed = (within[None] & in_range[None, :, None] &
                        k_valid3[:, :, None, :] & ~k_global3[:, :, None, :])
        band_scores = jnp.where(band_allowed[:, None], band_scores, -1e9)

        # -- column-global scores: every query vs the G global keys --------
        kg_cols = k[bidx, g_idx]                        # [B, G, H, D]
        vg_cols = v[bidx, g_idx]
        col_scores = jnp.einsum(
            "bqhd,bghd->bhqg", q, kg_cols,
            preferred_element_type=jnp.float32) * scale  # [B, H, S, G]
        col_scores = jnp.where(g_valid[:, None, None, :], col_scores, -1e9)
        col_scores = jnp.pad(col_scores, ((0, 0), (0, 0), (0, pad), (0, 0)),
                             constant_values=-1e9)
        col_scores = col_scores.reshape(batch, n_head, n_chunks, c, G)

        # -- joint softmax over band + global columns ----------------------
        joint = jnp.concatenate([band_scores, col_scores], axis=-1)
        probs = jax.nn.softmax(joint, axis=-1)
        band_p, col_p = probs[..., :3 * c], probs[..., 3 * c:]
        out_band = jnp.einsum("bhnqk,bnkhd->bnqhd",
                              band_p.astype(v3.dtype), v3)
        out_cols = jnp.einsum("bhnqg,bghd->bnqhd",
                              col_p.astype(vg_cols.dtype), vg_cols)
        out_local = (out_band + out_cols).reshape(
            batch, n_chunks * c, n_head, head_dim)[:, :seq]

        # -- global-query rows: full attention, global projections, only
        #    for the G gathered rows ---------------------------------------
        qg_rows = qg[bidx, g_idx]                       # [B, G, H, D]
        g_scores = jnp.einsum("bghd,bkhd->bhgk", qg_rows, kg,
                              preferred_element_type=jnp.float32) * scale
        g_scores = jnp.where(valid[:, None, None, :], g_scores, -1e9)
        g_probs = jax.nn.softmax(g_scores, axis=-1)
        out_g_rows = jnp.einsum("bhgk,bkhd->bghd",
                                g_probs.astype(vg.dtype), vg)
        out_global = jnp.zeros_like(out_local)
        out_global = out_global.at[bidx, g_idx].set(out_g_rows)

        out = jnp.where(is_gathered[:, :, None, None], out_global, out_local)
        out = with_logical_constraint(
            out, ("batch", "seq", "heads", None))
        return out.reshape(batch, seq, cfg.hidden_size)


class LongformerLayer(nn.Module):
    config: LongformerConfig

    @nn.compact
    def __call__(self, hidden, attention_mask=None,
                 global_attention_mask=None, deterministic=True):
        cfg = self.config
        h = LongformerSelfAttention(cfg, name="self")(
            hidden, attention_mask, global_attention_mask, deterministic)
        h = _dense(cfg, cfg.hidden_size, "attention_output_dense")(h)
        h = nn.Dropout(cfg.hidden_dropout_prob)(h,
                                                deterministic=deterministic)
        hidden = LayerNorm(epsilon=cfg.layer_norm_eps,
                           name="attention_ln")(hidden + h)
        h = _dense(cfg, cfg.intermediate_size, "intermediate_dense")(hidden)
        h = get_activation(cfg.hidden_act)(h)
        h = with_logical_constraint(h, ("batch", "seq", "mlp"))
        h = _dense(cfg, cfg.hidden_size, "output_dense")(h)
        h = nn.Dropout(cfg.hidden_dropout_prob)(h,
                                                deterministic=deterministic)
        return LayerNorm(epsilon=cfg.layer_norm_eps,
                         name="output_ln")(hidden + h)


class LongformerModel(nn.Module):
    config: LongformerConfig
    add_pooling_layer: bool = True

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 global_attention_mask=None, position_ids=None,
                 deterministic=True):
        cfg = self.config
        batch, seq = input_ids.shape
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        hidden = VocabParallelEmbed(
            cfg.vocab_size, cfg.hidden_size, dtype=_dt(cfg),
            param_dtype=jnp.dtype(cfg.param_dtype),
            embedding_init=nn.initializers.normal(cfg.initializer_range),
            name="word_embeddings")(input_ids)
        if not cfg.use_rotary:
            if position_ids is None:
                position_ids = jnp.arange(seq)[None]
            hidden = hidden + nn.Embed(
                cfg.max_position_embeddings, cfg.hidden_size,
                dtype=_dt(cfg), param_dtype=jnp.dtype(cfg.param_dtype),
                embedding_init=nn.initializers.normal(
                    cfg.initializer_range),
                name="position_embeddings")(position_ids)
        hidden = hidden + nn.Embed(
            cfg.type_vocab_size, cfg.hidden_size, dtype=_dt(cfg),
            param_dtype=jnp.dtype(cfg.param_dtype),
            embedding_init=nn.initializers.normal(cfg.initializer_range),
            name="token_type_embeddings")(token_type_ids)
        hidden = LayerNorm(epsilon=cfg.layer_norm_eps,
                           name="embeddings_ln")(hidden)
        hidden = nn.Dropout(cfg.hidden_dropout_prob)(
            hidden, deterministic=deterministic)
        for i in range(cfg.num_hidden_layers):
            hidden = LongformerLayer(cfg, name=f"layer_{i}")(
                hidden, attention_mask, global_attention_mask,
                deterministic)
        pooled = None
        if self.add_pooling_layer:
            pooled = jnp.tanh(_dense(cfg, cfg.hidden_size,
                                     "pooler")(hidden[:, 0]))
        return hidden, pooled

    def partition_rules(self):
        return to_partition_rules(PARAM_LOGICAL_AXES)


class LongformerForMaskedLM(nn.Module):
    config: LongformerConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 global_attention_mask=None, deterministic=True):
        cfg = self.config
        hidden, _ = LongformerModel(cfg, add_pooling_layer=False,
                                    name="longformer")(
            input_ids, attention_mask, token_type_ids,
            global_attention_mask, deterministic=deterministic)
        h = _dense(cfg, cfg.hidden_size, "transform_dense")(hidden)
        h = get_activation(cfg.hidden_act)(h)
        h = LayerNorm(epsilon=cfg.layer_norm_eps, name="transform_ln")(h)
        wte = self.variables["params"]["longformer"]["word_embeddings"][
            "embedding"]
        logits = h @ wte.T.astype(h.dtype)
        bias = self.param("bias", nn.initializers.zeros,
                          (cfg.vocab_size,), jnp.dtype(cfg.param_dtype))
        return logits + bias

    def partition_rules(self):
        return to_partition_rules(PARAM_LOGICAL_AXES)


class LongformerForSequenceClassification(nn.Module):
    config: LongformerConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 global_attention_mask=None, deterministic=True):
        cfg = self.config
        _, pooled = LongformerModel(cfg, name="longformer")(
            input_ids, attention_mask, token_type_ids,
            global_attention_mask, deterministic=deterministic)
        pooled = nn.Dropout(cfg.hidden_dropout_prob)(
            pooled, deterministic=deterministic)
        return _dense(cfg, cfg.num_labels, "classifier")(pooled)

    def partition_rules(self):
        return to_partition_rules(PARAM_LOGICAL_AXES)
