"""Reference UniEX checkpoint → flax params.

Reference state-dict naming (fengshen/models/uniex/modeling_uniex.py:
885-900): `bert.*` (plain HF BertModel tower), `mlp_start.mlp.0` /
`mlp_end.mlp.0` / `mlp_cls.mlp.0` (Linear+GELU projections), and
`triaffine.weight` of shape [T, T, T] scoring
start_i · W[i,o,j] · end_j · type_o. Our `UniEXBertModel` uses the same
trilinear form with bias-augmented start/end features, so the reference
weight fills `triaffine_u[:T, :, :T]` (axes (start, type, end)) and the
bias rows stay zero.
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from fengshen_tpu.utils.convert_common import (detect_bert_arch,
                                               encoder_tower_params,
                                               make_helpers, tensor,
                                               unwrap_lightning)


def torch_to_params(state_dict: Mapping[str, Any], config,
                    backbone_type: str | None = None) -> dict:
    sd = unwrap_lightning(state_dict)
    if backbone_type is None:
        backbone_type = detect_bert_arch(sd)
    _, lin, _ = make_helpers(sd)
    w = tensor(sd, "triaffine.weight")  # [T, T, T] = (start, type, end)
    d = w.shape[0]
    u = np.zeros((d + 1, d, d + 1), w.dtype)
    u[:d, :, :d] = w
    return {
        "bert": encoder_tower_params(sd, config, backbone_type),
        "start_mlp": lin("mlp_start.mlp.0"),
        "end_mlp": lin("mlp_end.mlp.0"),
        "type_mlp": lin("mlp_cls.mlp.0"),
        "triaffine_u": u,
    }


#: fs→torch export: derived exact inverse of `torch_to_params`
#: (template_state = the source checkpoint: dict, Lightning ckpt, or dir)
from fengshen_tpu.utils.convert_common import (  # noqa: E402
    make_derived_export)

params_to_torch_state = make_derived_export(torch_to_params)
