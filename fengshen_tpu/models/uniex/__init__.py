"""UniEX — unified information extraction with a triaffine scorer
(reference: fengshen/models/uniex/, 2,002 LoC)."""

from fengshen_tpu.models.uniex.modeling_uniex import (UniEXBertModel,
                                                      UniEXPipelines)

__all__ = ["UniEXBertModel", "UniEXPipelines"]
