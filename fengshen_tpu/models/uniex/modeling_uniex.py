"""UniEX: unified IE via triaffine span-type interaction.

Behavioural port of reference: fengshen/models/uniex/ — `UniEXBertModel`
scores (start, end, type) triples with a Triaffine form combining span
start/end representations with type-prompt representations; all extraction
tasks (NER, relation, event) reduce to typed-span scoring.
"""

from __future__ import annotations

import argparse
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from fengshen_tpu.models.megatron_bert import (MegatronBertConfig,
                                               MegatronBertModel)
from fengshen_tpu.models.megatron_bert.modeling_megatron_bert import (
    PARTITION_RULES, _dense)


class UniEXBertModel(nn.Module):
    """Encoder + triaffine (start × type × end) scorer.

    `type_positions` [B, T] marks the token index of each type prompt in the
    input (the reference packs type names into the prompt segment).
    """

    config: MegatronBertConfig
    biaffine_size: int = 128

    @nn.compact
    def __call__(self, input_ids, type_positions, attention_mask=None,
                 token_type_ids=None, span_labels=None, span_mask=None,
                 deterministic=True):
        cfg = self.config
        hidden, _ = MegatronBertModel(cfg, add_pooling_layer=False,
                                      name="bert")(
            input_ids, attention_mask, token_type_ids,
            deterministic=deterministic)
        d = self.biaffine_size
        start = jax.nn.gelu(_dense(cfg, d, "start_mlp")(hidden))
        end = jax.nn.gelu(_dense(cfg, d, "end_mlp")(hidden))
        type_hidden = jnp.take_along_axis(
            hidden, jnp.broadcast_to(
                type_positions[..., None],
                type_positions.shape + (hidden.shape[-1],)), axis=1)
        typ = jax.nn.gelu(_dense(cfg, d, "type_mlp")(type_hidden))

        U = self.param("triaffine_u", nn.initializers.normal(0.02),
                       (d + 1, d, d + 1), jnp.float32)
        ones_s = jnp.ones(start.shape[:-1] + (1,), start.dtype)
        start_1 = jnp.concatenate([start, ones_s], axis=-1)
        end_1 = jnp.concatenate([end, ones_s], axis=-1)
        # contract the small type dim FIRST: [B,T,d+1,d+1] per-type bilinear
        # forms, never a [B,S,d,S]-sized intermediate
        per_type = jnp.einsum("btk,dke->btde", typ, U.astype(typ.dtype))
        logits = jnp.einsum("bid,btde,bje->btij", start_1, per_type, end_1)
        if span_labels is None:
            return jax.nn.sigmoid(logits)
        logp = jax.nn.log_sigmoid(logits)
        lognp = jax.nn.log_sigmoid(-logits)
        loss = -(span_labels * logp + (1 - span_labels) * lognp)
        if span_mask is not None:
            loss = loss * span_mask
            denom = jnp.maximum(span_mask.sum(), 1)
        else:
            denom = loss.size
        return loss.sum() / denom, jax.nn.sigmoid(logits)

    def partition_rules(self):
        return PARTITION_RULES


class UniEXPipelines:
    """Reference contract (fengshen/pipelines/information_extraction.py:27
    style): predict over instruction samples with typed-span decoding."""

    @staticmethod
    def pipelines_args(parent_parser: argparse.ArgumentParser):
        parser = parent_parser.add_argument_group("uniex")
        parser.add_argument("--max_length", default=512, type=int)
        parser.add_argument("--threshold", default=0.5, type=float)
        return parent_parser

    def __init__(self, args=None, model: Optional[str] = None,
                 tokenizer=None, config=None, params=None):
        self.args = args
        if config is None and model is not None:
            config = MegatronBertConfig.from_pretrained(model)
        if config is None:
            config = MegatronBertConfig.small_test_config()
        self.config = config
        if tokenizer is None and model is not None:
            from transformers import AutoTokenizer
            tokenizer = AutoTokenizer.from_pretrained(model)
        self.tokenizer = tokenizer
        self.model = UniEXBertModel(config)
        self.params = params

    def predict(self, data: list[dict]) -> list[dict]:
        """data rows: {text, choices: [entity types]}"""
        if self.params is None:
            self.params = self.model.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32),
                jnp.zeros((1, 1), jnp.int32))["params"]
        from fengshen_tpu.models.span_utils import decode_spans
        tok = self.tokenizer
        threshold = getattr(self.args, "threshold", 0.5) if self.args \
            else 0.5
        max_len = min(getattr(self.args, "max_length", 512) if self.args
                      else 512, self.config.max_position_embeddings)
        results = []
        for row in data:
            types = [c["entity_type"] if isinstance(c, dict) else str(c)
                     for c in row.get("choices", [])]
            ids = [tok.cls_token_id]
            type_positions = []
            for t in types:
                type_positions.append(len(ids))
                ids.extend(tok.encode(t, add_special_tokens=False))
                ids.append(tok.sep_token_id)
            text_offset = len(ids)
            text_ids = tok.encode(row["text"], add_special_tokens=False)
            ids = (ids + text_ids)[: max_len - 1] + [tok.sep_token_id]
            arr = jnp.asarray([ids], jnp.int32)
            tpos = jnp.asarray([type_positions], jnp.int32)
            scores = np.asarray(self.model.apply(
                {"params": self.params}, arr, tpos,
                attention_mask=jnp.ones_like(arr)))[0]
            out = {"text": row["text"], "entity_list": []}
            for ti, tname in enumerate(types):
                for ent in decode_spans(scores[ti], ids, tok, text_offset,
                                        threshold):
                    out["entity_list"].append({"entity_type": tname, **ent})
            results.append(out)
        return results
