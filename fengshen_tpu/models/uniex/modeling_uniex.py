"""UniEX: unified IE via triaffine span-type interaction.

Behavioural port of reference: fengshen/models/uniex/ — `UniEXBertModel`
scores (start, end, type) triples with a Triaffine form combining span
start/end representations with type-prompt representations; all extraction
tasks (NER, relation, event) reduce to typed-span scoring.
"""

from __future__ import annotations

import argparse
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from fengshen_tpu.models.megatron_bert import MegatronBertConfig
from fengshen_tpu.models.towers import gelu_exact
from fengshen_tpu.models.megatron_bert.modeling_megatron_bert import (
    PARTITION_RULES, _dense)


class UniEXBertModel(nn.Module):
    """Encoder + triaffine (start × type × end) scorer.

    `type_positions` [B, T] marks the token index of each type prompt in the
    input (the reference packs type names into the prompt segment).
    """

    config: MegatronBertConfig
    biaffine_size: int = 128
    backbone_type: str = "megatron_bert"

    @nn.compact
    def __call__(self, input_ids, type_positions, attention_mask=None,
                 token_type_ids=None, span_labels=None, span_mask=None,
                 deterministic=True):
        from fengshen_tpu.models.towers import encoder_tower
        cfg = self.config
        hidden, _ = encoder_tower(cfg, self.backbone_type)(
            input_ids, attention_mask, token_type_ids,
            deterministic=deterministic)
        d = self.biaffine_size
        start = gelu_exact(_dense(cfg, d, "start_mlp")(hidden))
        end = gelu_exact(_dense(cfg, d, "end_mlp")(hidden))
        type_hidden = jnp.take_along_axis(
            hidden, jnp.broadcast_to(
                type_positions[..., None],
                type_positions.shape + (hidden.shape[-1],)), axis=1)
        typ = gelu_exact(_dense(cfg, d, "type_mlp")(type_hidden))

        U = self.param("triaffine_u", nn.initializers.normal(0.02),
                       (d + 1, d, d + 1), jnp.float32)
        ones_s = jnp.ones(start.shape[:-1] + (1,), start.dtype)
        start_1 = jnp.concatenate([start, ones_s], axis=-1)
        end_1 = jnp.concatenate([end, ones_s], axis=-1)
        # contract the small type dim FIRST: [B,T,d+1,d+1] per-type bilinear
        # forms, never a [B,S,d,S]-sized intermediate
        per_type = jnp.einsum("btk,dke->btde", typ, U.astype(typ.dtype))
        logits = jnp.einsum("bid,btde,bje->btij", start_1, per_type, end_1)
        if span_labels is None:
            return jax.nn.sigmoid(logits)
        logp = jax.nn.log_sigmoid(logits)
        lognp = jax.nn.log_sigmoid(-logits)
        loss = -(span_labels * logp + (1 - span_labels) * lognp)
        if span_mask is not None:
            loss = loss * span_mask
            denom = jnp.maximum(span_mask.sum(), 1)
        else:
            denom = loss.size
        return loss.sum() / denom, jax.nn.sigmoid(logits)

    def partition_rules(self):
        return PARTITION_RULES


class UniEXPipelines:
    """Reference contract (fengshen/pipelines/information_extraction.py:27
    style): predict over instruction samples with typed-span decoding."""

    @staticmethod
    def pipelines_args(parent_parser: argparse.ArgumentParser):
        parser = parent_parser.add_argument_group("uniex")
        parser.add_argument("--max_length", default=512, type=int)
        parser.add_argument("--threshold", default=0.5, type=float)
        parser.add_argument("--max_entity_types", default=16, type=int)
        from fengshen_tpu.data import UniversalDataModule
        from fengshen_tpu.models.model_utils import add_module_args
        from fengshen_tpu.trainer import add_trainer_args
        from fengshen_tpu.utils import UniversalCheckpoint
        parent_parser = add_module_args(parent_parser)
        parent_parser = add_trainer_args(parent_parser)
        parent_parser = UniversalDataModule.add_data_specific_args(
            parent_parser)
        parent_parser = UniversalCheckpoint.add_argparse_args(parent_parser)
        return parent_parser

    def __init__(self, args=None, model: Optional[str] = None,
                 tokenizer=None, config=None, params=None,
                 backbone_type: str = "megatron_bert"):
        self.args = args
        if config is None and model is not None:
            config = MegatronBertConfig.from_pretrained(model)
        if config is None:
            config = MegatronBertConfig.small_test_config()
        self.config = config
        if tokenizer is None and model is not None:
            from transformers import AutoTokenizer
            tokenizer = AutoTokenizer.from_pretrained(model)
        self.tokenizer = tokenizer
        self.model = UniEXBertModel(config,
                                    backbone_type=backbone_type)
        self.params = params


    def _max_len(self) -> int:
        """Effective max input length — ALWAYS capped by the position
        table (train and predict must agree)."""
        return min(getattr(self.args, "max_length", 512) if self.args
                   else 512, self.config.max_position_embeddings)

    def _encode_instruction(self, text: str, types: list[str]
                            ) -> tuple[list[int], list[int], int]:
        """[CLS] type1 [SEP] type2 [SEP] ... text [SEP] — the ONE encoding
        used by both fit and predict. Returns (ids, type_positions,
        text_offset)."""
        tok = self.tokenizer
        max_len = self._max_len()
        ids = [tok.cls_token_id]
        type_positions = []
        for t in types:
            type_positions.append(len(ids))
            ids.extend(tok.encode(t, add_special_tokens=False))
            ids.append(tok.sep_token_id)
        text_offset = len(ids)
        text_ids = tok.encode(text, add_special_tokens=False)
        ids = (ids + text_ids)[: max_len - 1] + [tok.sep_token_id]
        return ids, type_positions, text_offset

    def _encode_train(self, sample: dict, n_types: int) -> dict:
        """Instruction encoding plus span labels from choices' entity_idx
        (char offsets; one char per wordpiece for Chinese BERT vocab)."""
        choices = sample.get("choices", [])
        types = [c["entity_type"] if isinstance(c, dict) else str(c)
                 for c in choices]
        ids, type_positions, text_offset = self._encode_instruction(
            sample["text"], types)
        spans = []  # (type_idx, start_tok, end_tok)
        for ti, ch in enumerate(choices):
            if isinstance(ch, dict):
                for ent in ch.get("entity_list", []):
                    for s, e in ent.get("entity_idx", []):
                        spans.append((ti, text_offset + s, text_offset + e))
        type_positions = (type_positions + [0] * n_types)[:n_types]
        return {"input_ids": ids, "type_positions": type_positions,
                "text_offset": text_offset, "spans": spans,
                "n_types": len(types)}

    def _collate_train(self, samples: list[dict]) -> dict:
        import numpy as np
        max_len = self._max_len()
        # fixed type-dim so the jitted train step keeps ONE shape across
        # batches (per-batch max would recompile per distinct count)
        n_types = getattr(self.args, "max_entity_types", 16) if self.args \
            else 16
        pad_id = self.tokenizer.pad_token_id or 0
        encoded = [self._encode_train(s, n_types) for s in samples]
        batch = {"input_ids": [], "attention_mask": [],
                 "type_positions": [], "span_labels": [], "span_mask": []}
        for e in encoded:
            ids = e["input_ids"]
            n = len(ids)
            p = max_len - n
            batch["input_ids"].append(ids + [pad_id] * p)
            batch["attention_mask"].append([1] * n + [0] * p)
            batch["type_positions"].append(e["type_positions"])
            labels = np.zeros((n_types, max_len, max_len), np.float32)
            for ti, s, t in e["spans"]:
                if s < n and t < n:
                    labels[ti, s, t] = 1.0
            mask = np.zeros((n_types, max_len, max_len), np.float32)
            off = e["text_offset"]
            width = n - 1 - off
            if width > 0:
                tri = np.triu(np.ones((width, width), np.float32))
                mask[: e["n_types"], off:n - 1, off:n - 1] = tri[None]
            batch["span_labels"].append(labels)
            batch["span_mask"].append(mask)
        return {k: np.asarray(v) for k, v in batch.items()}

    def fit(self, train_data: list[dict],
            dev_data: Optional[list[dict]] = None) -> None:
        """Train on instruction-style samples (reference:
        fengshen/examples/uniex/example.py fit/predict driver)."""
        from fengshen_tpu.data import UniversalDataModule
        from fengshen_tpu.trainer import Trainer
        from fengshen_tpu.trainer.module import TrainModule

        pipe = self

        class _Module(TrainModule):
            def __init__(self, args):
                super().__init__(args)
                self.model = pipe.model

            def init_params(self, rng):
                return pipe.model.init(
                    rng, jnp.zeros((1, 16), jnp.int32),
                    jnp.zeros((1, 1), jnp.int32))["params"]

            def training_loss(self, params, batch, rng):
                loss, _ = pipe.model.apply(
                    {"params": params}, batch["input_ids"],
                    batch["type_positions"],
                    attention_mask=batch["attention_mask"],
                    span_labels=batch["span_labels"],
                    span_mask=batch["span_mask"],
                    deterministic=False, rngs={"dropout": rng})
                return loss, {}

            def partition_rules(self):
                return pipe.model.partition_rules()

        class ListDS:
            def __init__(self, rows):
                self.rows = rows

            def __len__(self):
                return len(self.rows)

            def __getitem__(self, i):
                return self.rows[i]

        datasets = {"train": ListDS(train_data)}
        if dev_data:
            datasets["validation"] = ListDS(dev_data)
        dm = UniversalDataModule(tokenizer=self.tokenizer,
                                 collate_fn=self._collate_train,
                                 args=self.args, datasets=datasets)
        module = _Module(self.args)
        if self.params is not None:
            module.init_params = lambda rng: self.params
        trainer = Trainer(self.args)
        state = trainer.fit(module, dm)
        self.params = state.params

    def predict(self, data: list[dict]) -> list[dict]:
        """data rows: {text, choices: [entity types]}"""
        if self.params is None:
            self.params = self.model.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32),
                jnp.zeros((1, 1), jnp.int32))["params"]
        from fengshen_tpu.models.span_utils import decode_spans
        tok = self.tokenizer
        threshold = getattr(self.args, "threshold", 0.5) if self.args \
            else 0.5
        results = []
        for row in data:
            types = [c["entity_type"] if isinstance(c, dict) else str(c)
                     for c in row.get("choices", [])]
            ids, type_positions, text_offset = self._encode_instruction(
                row["text"], types)
            arr = jnp.asarray([ids], jnp.int32)
            tpos = jnp.asarray([type_positions], jnp.int32)
            scores = np.asarray(self.model.apply(
                {"params": self.params}, arr, tpos,
                attention_mask=jnp.ones_like(arr)))[0]
            out = {"text": row["text"], "entity_list": []}
            for ti, tname in enumerate(types):
                for ent in decode_spans(scores[ti], ids, tok, text_offset,
                                        threshold):
                    out["entity_list"].append({"entity_type": tname, **ent})
            results.append(out)
        return results
