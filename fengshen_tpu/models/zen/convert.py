"""torch → jax weights for ZEN (n-gram enhanced BERT).

Importer for released Erlangshen-ZEN checkpoints
(reference: fengshen/models/zen1/modeling.py — BertEmbeddings for chars,
BertWordEmbeddings for n-grams (:225-248), encoder with `layer` +
`word_layers` side stack (:426-442)).
"""

from __future__ import annotations

from typing import Any, Mapping

from fengshen_tpu.models.zen.modeling_zen import ZenConfig
from fengshen_tpu.utils.convert_common import bert_layer, make_helpers


def torch_to_params(state_dict: Mapping[str, Any],
                    config: ZenConfig) -> dict:
    sd = state_dict
    if not any(k.startswith("bert.") for k in sd):
        sd = {f"bert.{k}": v for k, v in sd.items()}
    t, lin, ln = make_helpers(sd)

    params: dict = {
        "word_embeddings": {
            "embedding": t("bert.embeddings.word_embeddings.weight")},
        "position_embeddings": {
            "embedding": t("bert.embeddings.position_embeddings.weight")},
        "token_type_embeddings": {
            "embedding": t("bert.embeddings.token_type_embeddings.weight")},
        "embeddings_ln": ln("bert.embeddings.LayerNorm"),
        # n-gram side embeddings (reference BertWordEmbeddings :225-248,
        # word + token_type + LayerNorm)
        "ngram_embeddings": {
            "embedding": t("bert.word_embeddings.word_embeddings.weight")},
        "ngram_token_type_embeddings": {
            "embedding": t(
                "bert.word_embeddings.token_type_embeddings.weight")},
        "ngram_ln": ln("bert.word_embeddings.LayerNorm"),
    }
    for i in range(config.num_hidden_layers):
        params[f"layer_{i}"] = bert_layer(sd, f"bert.encoder.layer.{i}")
    for i in range(config.num_ngram_layers):
        params[f"ngram_layer_{i}"] = bert_layer(
            sd, f"bert.encoder.word_layers.{i}")
    if "bert.pooler.dense.weight" in sd:
        params["pooler"] = lin("bert.pooler.dense")
    return params


#: fs→torch export: derived exact inverse of `torch_to_params`
#: (template_state = the source checkpoint: dict, Lightning ckpt, or dir)
from fengshen_tpu.utils.convert_common import (  # noqa: E402
    make_derived_export)

params_to_torch_state = make_derived_export(torch_to_params)
