"""ZEN in flax: BERT char encoder + n-gram side encoder.

Reference: fengshen/models/zen1/modeling.py — `ZenModel`: a BERT backbone
whose layer outputs are enhanced by a parallel transformer over matched
n-gram embeddings; at each fused layer, char hidden states receive the sum
of the hidden states of the n-grams covering them (char↔ngram position
matrix), normalised by the cover count.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from fengshen_tpu.ops.embedding import VocabParallelEmbed
from fengshen_tpu.sharding import to_partition_rules

from fengshen_tpu.models.bert.modeling_bert import (BertConfig, BertLayer,
                                                    LayerNorm, _dense, _dt)

PARAM_LOGICAL_AXES: list[tuple[str, tuple]] = [
    ("(word|ngram)_embeddings/embedding", ("vocab", None)),
    (r"(query|key|value)/kernel", ("embed", "heads")),
    (r"intermediate_dense/kernel", ("embed", "mlp")),
    (r"attention_output_dense/kernel", ("heads", "embed")),
    (r"output_dense/kernel", ("mlp", "embed")),
    (".*", (None,)),
]
PARTITION_RULES = to_partition_rules(PARAM_LOGICAL_AXES)


@dataclasses.dataclass
class ZenConfig(BertConfig):
    ngram_vocab_size: int = 104089
    num_ngram_layers: int = 6  # side-encoder depth; fusion on these layers

    @classmethod
    def small_test_config(cls, **overrides: Any) -> "ZenConfig":
        base = dict(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=64,
                    max_position_embeddings=64, ngram_vocab_size=64,
                    num_ngram_layers=2)
        base.update(overrides)
        return cls(**base)


class ZenModel(nn.Module):
    config: ZenConfig
    add_pooling_layer: bool = True

    @nn.compact
    def __call__(self, input_ids, ngram_ids=None, ngram_positions=None,
                 attention_mask=None, token_type_ids=None,
                 deterministic=True):
        """ngram_ids [B, M]; ngram_positions [B, S, M] (1 = char in gram)."""
        cfg = self.config
        batch, seq = input_ids.shape
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        embed = lambda n, name, cls=nn.Embed: cls(  # noqa: E731
            n, cfg.hidden_size, dtype=_dt(cfg),
            param_dtype=jnp.dtype(cfg.param_dtype),
            embedding_init=nn.initializers.normal(cfg.initializer_range),
            name=name)
        hidden = embed(cfg.vocab_size, "word_embeddings",
                       VocabParallelEmbed)(input_ids) + \
            embed(cfg.max_position_embeddings, "position_embeddings")(
                jnp.arange(seq)[None]) + \
            embed(cfg.type_vocab_size,
                  "token_type_embeddings")(token_type_ids)
        hidden = LayerNorm(epsilon=cfg.layer_norm_eps,
                           name="embeddings_ln")(hidden)
        hidden = nn.Dropout(cfg.hidden_dropout_prob)(
            hidden, deterministic=deterministic)

        ngram_hidden = None
        ngram_mask = None
        if ngram_ids is not None:
            # ngram side carries its own token-type table (reference:
            # zen1/modeling.py:225-249 BertWordEmbeddings — ngram seg ids
            # are 1 for second-sentence ngrams in pair tasks; 0 default)
            ngram_hidden = embed(cfg.ngram_vocab_size, "ngram_embeddings",
                                 VocabParallelEmbed)(ngram_ids) + \
                embed(cfg.type_vocab_size, "ngram_token_type_embeddings")(
                    jnp.zeros_like(ngram_ids))
            ngram_hidden = LayerNorm(epsilon=cfg.layer_norm_eps,
                                     name="ngram_ln")(ngram_hidden)
            ngram_mask = (ngram_ids != 0).astype(jnp.int32)

        for i in range(cfg.num_hidden_layers):
            hidden = BertLayer(cfg, name=f"layer_{i}")(
                hidden, attention_mask, deterministic)
            if ngram_hidden is not None:
                if i < cfg.num_ngram_layers:
                    ngram_hidden = BertLayer(cfg, name=f"ngram_layer_{i}")(
                        ngram_hidden, ngram_mask, deterministic)
                # fusion runs on EVERY layer (reference zen1/modeling.py:
                # 442 — the bmm sits OUTSIDE the word-layer gate, so
                # deeper layers keep receiving the last ngram states);
                # plain matmul: the raw 0/1 matrix sums covering grams
                fused = jnp.einsum(
                    "bsm,bmh->bsh", ngram_positions.astype(jnp.float32),
                    ngram_hidden.astype(jnp.float32))
                hidden = hidden + fused.astype(hidden.dtype)

        pooled = None
        if self.add_pooling_layer:
            pooled = jnp.tanh(_dense(cfg, cfg.hidden_size,
                                     "pooler")(hidden[:, 0]))
        return hidden, pooled

    def partition_rules(self):
        return to_partition_rules(PARAM_LOGICAL_AXES)


class ZenForSequenceClassification(nn.Module):
    config: ZenConfig

    @nn.compact
    def __call__(self, input_ids, ngram_ids=None, ngram_positions=None,
                 attention_mask=None, token_type_ids=None,
                 deterministic=True):
        cfg = self.config
        _, pooled = ZenModel(cfg, name="zen")(
            input_ids, ngram_ids, ngram_positions, attention_mask,
            token_type_ids, deterministic)
        pooled = nn.Dropout(cfg.hidden_dropout_prob)(
            pooled, deterministic=deterministic)
        return _dense(cfg, cfg.num_labels, "classifier")(pooled)

    def partition_rules(self):
        return to_partition_rules(PARAM_LOGICAL_AXES)
