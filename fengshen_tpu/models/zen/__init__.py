"""ZEN1/ZEN2 — n-gram-enhanced Chinese BERT (reference:
fengshen/models/zen1/ 1,715 LoC + fengshen/models/zen2/ 2,129 LoC:
`ZenModel` = BERT + n-gram side encoder fused via a char↔ngram matching
matrix, `ZenNgramDict`)."""

from fengshen_tpu.models.zen.modeling_zen import (ZenConfig, ZenModel,
                                                  ZenForSequenceClassification)
from fengshen_tpu.models.zen.ngram_utils import ZenNgramDict

__all__ = ["ZenConfig", "ZenModel", "ZenForSequenceClassification",
           "ZenNgramDict"]

from fengshen_tpu.models.zen.task_heads import (ZenForTokenClassification, ZenForQuestionAnswering, ZenForMultipleChoice)
__all__ += ['ZenForTokenClassification', 'ZenForQuestionAnswering', 'ZenForMultipleChoice']
