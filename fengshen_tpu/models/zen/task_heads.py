"""Task heads for ZEN (token classification for the zen NER finetunes,
reference: fengshen/examples/zen1_finetune/fengshen_token_level_ft_task.py;
QA/MC complete the HF-style set). N-gram side inputs pass through as
keyword arguments."""

from fengshen_tpu.models.heads import make_task_heads
from fengshen_tpu.models.zen.modeling_zen import ZenModel

from fengshen_tpu.models.bert.modeling_bert import PARTITION_RULES

(_SeqCls, ZenForTokenClassification, ZenForQuestionAnswering,
 ZenForMultipleChoice) = make_task_heads(
    ZenModel, has_pooler=True, encoder_name="zen",
    rules=lambda cfg: PARTITION_RULES)

ZenForTokenClassification.__name__ = "ZenForTokenClassification"
ZenForQuestionAnswering.__name__ = "ZenForQuestionAnswering"
ZenForMultipleChoice.__name__ = "ZenForMultipleChoice"

__all__ = ["ZenForTokenClassification", "ZenForQuestionAnswering",
           "ZenForMultipleChoice"]
