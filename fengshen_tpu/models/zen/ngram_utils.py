"""N-gram dictionary + matching (reference: fengshen/models/zen1/
ngram_utils.py `ZenNgramDict`)."""

from __future__ import annotations

import os
from typing import Optional

import numpy as np


class ZenNgramDict:
    """ngram → id vocabulary with text matching.

    File format: one ngram per line (optionally `ngram\\tfreq`), as in the
    reference's ngram.txt shipped with ZEN checkpoints.
    """

    def __init__(self, ngram_freq_path: Optional[str] = None,
                 ngrams: Optional[list[str]] = None,
                 max_ngram_in_seq: int = 128,
                 max_ngram_len: int = 8):
        self.max_ngram_in_seq = max_ngram_in_seq
        self.max_ngram_len = max_ngram_len
        vocab: list[str] = ["[pad]"]
        freqs: list[float] = [0.0]
        if ngram_freq_path and os.path.isdir(ngram_freq_path):
            # checkpoint dirs ship the dict as ngram.txt (reference:
            # ngram_utils.py NGRAM_DICT_NAME)
            ngram_freq_path = os.path.join(ngram_freq_path, "ngram.txt")
        if ngram_freq_path and os.path.exists(ngram_freq_path):
            with open(ngram_freq_path) as f:
                for line in f:
                    fields = line.strip().replace("\t", ",").split(",")
                    token = fields[0]
                    if token:
                        vocab.append(token)
                        try:
                            freqs.append(float(fields[1]))
                        except (IndexError, ValueError):
                            freqs.append(1.0)
        if ngrams:
            vocab.extend(ngrams)
            freqs.extend([1.0] * len(ngrams))
        self.id_to_ngram_list = vocab
        self.ngram_to_id_dict = {g: i for i, g in enumerate(vocab)}
        # dictionary frequency per id — zen2's fusion weights spans by
        # freq before row-normalising (reference: examples/zen2_finetune/
        # fengshen_sequence_level_ft_task.py:393-404)
        self.id_to_freq = freqs

    def __len__(self) -> int:
        return len(self.id_to_ngram_list)

    def match(self, chars: list[str], with_freqs: bool = False):
        """Return (ngram_ids [M], positions [S, M]) for a char sequence:
        positions[i, j] = 1 iff char i is inside matched ngram j. With
        `with_freqs`, also return the dictionary frequency per match
        (zen2's freq-weighted fusion)."""
        seq_len = len(chars)
        matches: list[tuple[int, int, int]] = []  # (ngram_id, start, length)
        for start in range(seq_len):
            for ln in range(2, min(self.max_ngram_len, seq_len - start) + 1):
                gram = "".join(chars[start:start + ln])
                gid = self.ngram_to_id_dict.get(gram)
                if gid is not None:
                    matches.append((gid, start, ln))
        matches = matches[: self.max_ngram_in_seq]
        ngram_ids = np.zeros((self.max_ngram_in_seq,), np.int32)
        positions = np.zeros((seq_len, self.max_ngram_in_seq), np.int32)
        freqs = np.zeros((self.max_ngram_in_seq,), np.float32)
        for j, (gid, start, ln) in enumerate(matches):
            ngram_ids[j] = gid
            positions[start:start + ln, j] = 1
            freqs[j] = self.id_to_freq[gid] if gid < len(self.id_to_freq) \
                else 1.0
        if with_freqs:
            return ngram_ids, positions, freqs
        return ngram_ids, positions
