"""BART family (reference: fengshen/models/bart/ — `BartForTextInfill` +
Randeng-BART seq2seq examples)."""

from fengshen_tpu.models.bart.modeling_bart import (
    BartConfig, BartModel, BartForConditionalGeneration,
    BartForTextInfill, text_infill_loss)

__all__ = ["BartConfig", "BartModel", "BartForConditionalGeneration",
           "BartForTextInfill", "text_infill_loss"]
