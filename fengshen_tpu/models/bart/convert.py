"""torch(HF) → jax weights for BART."""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from fengshen_tpu.utils.convert_common import tensor as _tensor

from fengshen_tpu.models.bart.modeling_bart import BartConfig


def torch_to_params(state_dict: Mapping[str, Any],
                    config: BartConfig) -> dict:
    def t(name):
        return _tensor(state_dict, name)

    def lin(prefix):
        return {"kernel": t(f"{prefix}.weight").T,
                "bias": t(f"{prefix}.bias")}

    def ln(prefix):
        return {"scale": t(f"{prefix}.weight"), "bias": t(f"{prefix}.bias")}

    def attn(prefix):
        return {p: lin(f"{prefix}.{p}")
                for p in ("q_proj", "k_proj", "v_proj", "out_proj")}

    model: dict = {
        "shared": {"embedding": t("model.shared.weight")},
        "encoder_embed_positions": {
            "embedding": t("model.encoder.embed_positions.weight")},
        "decoder_embed_positions": {
            "embedding": t("model.decoder.embed_positions.weight")},
        "encoder_layernorm_embedding": ln(
            "model.encoder.layernorm_embedding"),
        "decoder_layernorm_embedding": ln(
            "model.decoder.layernorm_embedding"),
    }
    for i in range(config.encoder_layers):
        pre = f"model.encoder.layers.{i}"
        model[f"encoder_layer_{i}"] = {
            "self_attn": attn(f"{pre}.self_attn"),
            "self_attn_layer_norm": ln(f"{pre}.self_attn_layer_norm"),
            "fc1": lin(f"{pre}.fc1"),
            "fc2": lin(f"{pre}.fc2"),
            "final_layer_norm": ln(f"{pre}.final_layer_norm"),
        }
    for i in range(config.decoder_layers):
        pre = f"model.decoder.layers.{i}"
        model[f"decoder_layer_{i}"] = {
            "self_attn": attn(f"{pre}.self_attn"),
            "self_attn_layer_norm": ln(f"{pre}.self_attn_layer_norm"),
            "encoder_attn": attn(f"{pre}.encoder_attn"),
            "encoder_attn_layer_norm": ln(f"{pre}.encoder_attn_layer_norm"),
            "fc1": lin(f"{pre}.fc1"),
            "fc2": lin(f"{pre}.fc2"),
            "final_layer_norm": ln(f"{pre}.final_layer_norm"),
        }
    params: dict = {"model": model}
    if "final_logits_bias" in state_dict:
        params["final_logits_bias"] = t("final_logits_bias").reshape(-1)
    return params


#: fs→torch export: derived exact inverse of `torch_to_params`
#: (template_state = the source checkpoint: dict, Lightning ckpt, or dir)
from fengshen_tpu.utils.convert_common import (  # noqa: E402
    make_derived_export)

params_to_torch_state = make_derived_export(torch_to_params)
