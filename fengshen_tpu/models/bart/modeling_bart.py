"""BART in flax, HF-weight-compatible.

Reference: fengshen/models/bart/ (lexically-constrained `BartForTextInfill`,
Randeng-BART pretrain/QG examples). Post-LN encoder-decoder with learned
positional embeddings offset by 2 (the HF quirk), scaled q attention, tied
LM head with final_logits_bias.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from fengshen_tpu.ops.activations import get_activation
from fengshen_tpu.ops.embedding import VocabParallelEmbed
from fengshen_tpu.ops.attention import dot_product_attention
from fengshen_tpu.ops.masks import causal_mask
from fengshen_tpu.ops.norms import LayerNorm
from fengshen_tpu.sharding import (to_partition_rules,
                                    with_logical_constraint)

PARAM_LOGICAL_AXES: list[tuple[str, tuple]] = [
    ("shared/embedding", ("vocab", "embed")),
    ("embed_positions/embedding", ("relpos", None)),
    (r"(q_proj|k_proj|v_proj)/kernel", ("embed", "heads")),
    (r"fc1/kernel", ("embed", "mlp")),
    (r"out_proj/kernel", ("heads", "embed")),
    (r"fc2/kernel", ("mlp", "embed")),
    (".*", (None,)),
]
PARTITION_RULES = to_partition_rules(PARAM_LOGICAL_AXES)

_POS_OFFSET = 2  # HF BartLearnedPositionalEmbedding offset


@dataclasses.dataclass
class BartConfig:
    vocab_size: int = 50265
    d_model: int = 768
    encoder_layers: int = 6
    decoder_layers: int = 6
    encoder_attention_heads: int = 12
    decoder_attention_heads: int = 12
    encoder_ffn_dim: int = 3072
    decoder_ffn_dim: int = 3072
    activation_function: str = "gelu"
    dropout: float = 0.1
    attention_dropout: float = 0.0
    max_position_embeddings: int = 1024
    init_std: float = 0.02
    scale_embedding: bool = False
    pad_token_id: int = 1
    bos_token_id: int = 0
    eos_token_id: int = 2
    decoder_start_token_id: int = 2
    dtype: str = "bfloat16"
    param_dtype: str = "float32"
    decode_cache_length: int = 512  # KV-cache capacity for generation

    @property
    def hidden_size(self) -> int:
        return self.d_model

    @property
    def num_hidden_layers(self) -> int:
        return self.encoder_layers + self.decoder_layers

    @property
    def intermediate_size(self) -> int:
        return self.encoder_ffn_dim

    @classmethod
    def from_pretrained(cls, path: str) -> "BartConfig":
        cfg_file = os.path.join(path, "config.json") if os.path.isdir(path) \
            else path
        with open(cfg_file) as f:
            raw = json.load(f)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in raw.items() if k in known})

    def save_pretrained(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "config.json"), "w") as f:
            json.dump(dataclasses.asdict(self) |
                      {"model_type": "bart"}, f, indent=2)

    @classmethod
    def small_test_config(cls, **overrides: Any) -> "BartConfig":
        base = dict(vocab_size=128, d_model=32, encoder_layers=2,
                    decoder_layers=2, encoder_attention_heads=4,
                    decoder_attention_heads=4, encoder_ffn_dim=64,
                    decoder_ffn_dim=64, max_position_embeddings=64)
        base.update(overrides)
        return cls(**base)


def _dt(cfg):
    return jnp.dtype(cfg.dtype)


def _dense(cfg, feats, name, bias=True):
    return nn.Dense(feats, use_bias=bias, dtype=_dt(cfg),
                    param_dtype=jnp.dtype(cfg.param_dtype),
                    kernel_init=nn.initializers.normal(cfg.init_std),
                    name=name)


class BartAttention(nn.Module):
    config: BartConfig
    num_heads: int
    causal: bool = False

    @nn.compact
    def __call__(self, hidden, kv=None, attention_mask=None,
                 deterministic=True, init_cache=False,
                 cross_from_cache=False):
        cfg = self.config
        batch, q_len, _ = hidden.shape
        head_dim = cfg.d_model // self.num_heads
        q = _dense(cfg, cfg.d_model, "q_proj")(hidden)
        q = q.reshape(batch, q_len, self.num_heads, head_dim)
        if kv is not None and (cross_from_cache or init_cache or
                               self.has_variable("cache", "cross_key")):
            # cross-attention K/V: projected once on the priming decode
            # call, read back inside the scan (same contract as T5)
            shape = (batch, kv.shape[1], self.num_heads, head_dim)
            ck = self.variable("cache", "cross_key", jnp.zeros, shape,
                               _dt(cfg))
            cv = self.variable("cache", "cross_value", jnp.zeros, shape,
                               _dt(cfg))
            if cross_from_cache:
                k, v = ck.value, cv.value
            else:
                k = _dense(cfg, cfg.d_model, "k_proj")(kv).reshape(shape)
                v = _dense(cfg, cfg.d_model, "v_proj")(kv).reshape(shape)
                ck.value, cv.value = k, v
        else:
            kv_in = hidden if kv is None else kv
            k = _dense(cfg, cfg.d_model, "k_proj")(kv_in)
            v = _dense(cfg, cfg.d_model, "v_proj")(kv_in)
            k = k.reshape(batch, kv_in.shape[1], self.num_heads, head_dim)
            v = v.reshape(batch, kv_in.shape[1], self.num_heads, head_dim)

        use_cache = self.causal and kv is None and (
            self.has_variable("cache", "cached_key") or init_cache)
        if use_cache:
            k, v, decode_mask = self._update_cache(k, v)
            mask = decode_mask[:, None]
        elif self.causal:
            mask = causal_mask(q_len, k.shape[1])[None, None]
            if attention_mask is not None:
                mask = mask & attention_mask[:, None, None, :].astype(bool)
        elif attention_mask is not None:
            mask = attention_mask[:, None, None, :].astype(bool)
        else:
            mask = None

        out = dot_product_attention(q, k, v, mask=mask,
                                    deterministic=deterministic)
        out = with_logical_constraint(
            out, ("batch", "seq", "heads", None))
        out = out.reshape(batch, q_len, cfg.d_model)
        return _dense(cfg, cfg.d_model, "out_proj")(out)

    def _update_cache(self, k, v):
        """Static-shape decoder KV cache (same scheme as llama/T5)."""
        cfg = self.config
        batch, seq, n_heads, head_dim = k.shape
        max_len = getattr(cfg, "decode_cache_length", 512)
        is_initialized = self.has_variable("cache", "cached_key")
        cached_k = self.variable("cache", "cached_key", jnp.zeros,
                                 (batch, max_len, n_heads, head_dim),
                                 k.dtype)
        cached_v = self.variable("cache", "cached_value", jnp.zeros,
                                 (batch, max_len, n_heads, head_dim),
                                 v.dtype)
        cache_index = self.variable("cache", "cache_index",
                                    lambda: jnp.zeros((), jnp.int32))
        if not is_initialized:
            valid = jnp.broadcast_to(
                (jnp.arange(seq)[None, :] <=
                 jnp.arange(seq)[:, None])[None], (batch, seq, seq))
            return k, v, valid
        idx = cache_index.value
        k_all = jax.lax.dynamic_update_slice(cached_k.value, k,
                                             (0, idx, 0, 0))
        v_all = jax.lax.dynamic_update_slice(cached_v.value, v,
                                             (0, idx, 0, 0))
        cached_k.value, cached_v.value = k_all, v_all
        cache_index.value = idx + seq
        q_pos = idx + jnp.arange(seq)
        valid = jnp.arange(max_len)[None, :] <= q_pos[:, None]
        valid = jnp.broadcast_to(valid[None], (batch, seq, max_len))
        return k_all, v_all, valid


class BartEncoderLayer(nn.Module):
    config: BartConfig

    @nn.compact
    def __call__(self, hidden, attention_mask=None, deterministic=True):
        cfg = self.config
        h = BartAttention(cfg, cfg.encoder_attention_heads,
                          name="self_attn")(
            hidden, attention_mask=attention_mask,
            deterministic=deterministic)
        h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        hidden = LayerNorm(name="self_attn_layer_norm")(hidden + h)
        h = get_activation(cfg.activation_function)(
            _dense(cfg, cfg.encoder_ffn_dim, "fc1")(hidden))
        h = with_logical_constraint(h, ("batch", "seq", "mlp"))
        h = _dense(cfg, cfg.d_model, "fc2")(h)
        h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        return LayerNorm(name="final_layer_norm")(hidden + h)


class BartDecoderLayer(nn.Module):
    config: BartConfig

    @nn.compact
    def __call__(self, hidden, encoder_hidden, attention_mask=None,
                 encoder_attention_mask=None, deterministic=True,
                 init_cache=False, cross_from_cache=False):
        cfg = self.config
        h = BartAttention(cfg, cfg.decoder_attention_heads, causal=True,
                          name="self_attn")(
            hidden, attention_mask=attention_mask,
            deterministic=deterministic, init_cache=init_cache)
        h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        hidden = LayerNorm(name="self_attn_layer_norm")(hidden + h)
        h = BartAttention(cfg, cfg.decoder_attention_heads,
                          name="encoder_attn")(
            hidden, kv=encoder_hidden,
            attention_mask=encoder_attention_mask,
            deterministic=deterministic, init_cache=init_cache,
            cross_from_cache=cross_from_cache)
        h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        hidden = LayerNorm(name="encoder_attn_layer_norm")(hidden + h)
        h = get_activation(cfg.activation_function)(
            _dense(cfg, cfg.decoder_ffn_dim, "fc1")(hidden))
        h = _dense(cfg, cfg.d_model, "fc2")(h)
        h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        return LayerNorm(name="final_layer_norm")(hidden + h)


class BartModel(nn.Module):
    config: BartConfig

    def setup(self):
        cfg = self.config
        self.shared = VocabParallelEmbed(
            cfg.vocab_size, cfg.d_model, dtype=_dt(cfg),
            param_dtype=jnp.dtype(cfg.param_dtype),
            embedding_init=nn.initializers.normal(cfg.init_std),
            name="shared")
        self.encoder_embed_positions = nn.Embed(
            cfg.max_position_embeddings + _POS_OFFSET, cfg.d_model,
            dtype=_dt(cfg), param_dtype=jnp.dtype(cfg.param_dtype),
            embedding_init=nn.initializers.normal(cfg.init_std),
            name="encoder_embed_positions")
        self.decoder_embed_positions = nn.Embed(
            cfg.max_position_embeddings + _POS_OFFSET, cfg.d_model,
            dtype=_dt(cfg), param_dtype=jnp.dtype(cfg.param_dtype),
            embedding_init=nn.initializers.normal(cfg.init_std),
            name="decoder_embed_positions")
        self.encoder_layernorm_embedding = LayerNorm(
            name="encoder_layernorm_embedding")
        self.decoder_layernorm_embedding = LayerNorm(
            name="decoder_layernorm_embedding")
        self.encoder_layers = [
            BartEncoderLayer(cfg, name=f"encoder_layer_{i}")
            for i in range(cfg.encoder_layers)]
        self.decoder_layers = [
            BartDecoderLayer(cfg, name=f"decoder_layer_{i}")
            for i in range(cfg.decoder_layers)]
        self.embed_scale = (cfg.d_model ** 0.5) if cfg.scale_embedding \
            else 1.0
        self.dropout_layer = nn.Dropout(cfg.dropout)

    def encode(self, input_ids, attention_mask=None, deterministic=True):
        cfg = self.config
        seq = input_ids.shape[1]
        pos = jnp.arange(seq) + _POS_OFFSET
        hidden = self.shared(input_ids) * self.embed_scale + \
            self.encoder_embed_positions(pos)[None]
        hidden = self.encoder_layernorm_embedding(hidden)
        hidden = self.dropout_layer(hidden, deterministic=deterministic)
        for layer in self.encoder_layers:
            hidden = layer(hidden, attention_mask, deterministic)
        return hidden

    def decode(self, decoder_input_ids, encoder_hidden,
               attention_mask=None, decoder_attention_mask=None,
               deterministic=True, init_cache=False,
               cross_from_cache=False, position_offset=0):
        cfg = self.config
        seq = decoder_input_ids.shape[1]
        pos = position_offset + jnp.arange(seq) + _POS_OFFSET
        hidden = self.shared(decoder_input_ids) * self.embed_scale + \
            self.decoder_embed_positions(pos)[None]
        hidden = self.decoder_layernorm_embedding(hidden)
        hidden = self.dropout_layer(hidden, deterministic=deterministic)
        for layer in self.decoder_layers:
            hidden = layer(hidden, encoder_hidden, decoder_attention_mask,
                           attention_mask, deterministic,
                           init_cache=init_cache,
                           cross_from_cache=cross_from_cache)
        return hidden

    def __call__(self, input_ids, decoder_input_ids, attention_mask=None,
                 decoder_attention_mask=None, deterministic=True):
        enc = self.encode(input_ids, attention_mask, deterministic)
        dec = self.decode(decoder_input_ids, enc, attention_mask,
                          decoder_attention_mask, deterministic)
        return enc, dec


class BartForConditionalGeneration(nn.Module):
    config: BartConfig

    def setup(self):
        self.model = BartModel(self.config, name="model")
        self.final_logits_bias = self.param(
            "final_logits_bias", nn.initializers.zeros,
            (self.config.vocab_size,), jnp.float32)

    def __call__(self, input_ids, decoder_input_ids, attention_mask=None,
                 decoder_attention_mask=None, deterministic=True,
                 init_cache=False):
        enc = self.model.encode(input_ids, attention_mask, deterministic)
        dec = self.model.decode(decoder_input_ids, enc, attention_mask,
                                decoder_attention_mask, deterministic,
                                init_cache=init_cache)
        emb = self.model.shared.embedding
        logits = dec @ emb.T.astype(dec.dtype)
        return logits + self.final_logits_bias.astype(logits.dtype)

    def encode(self, input_ids, attention_mask=None, deterministic=True):
        return self.model.encode(input_ids, attention_mask, deterministic)

    def decode_logits(self, decoder_input_ids, encoder_hidden,
                      attention_mask=None, deterministic=True,
                      init_cache=False, cross_from_cache=False,
                      position_offset=0):
        """Decoder step for the generate loop: the encoder runs once via
        `encode`, self/cross K/V ride the cache when `init_cache`."""
        dec = self.model.decode(decoder_input_ids, encoder_hidden,
                                attention_mask, None, deterministic,
                                init_cache=init_cache,
                                cross_from_cache=cross_from_cache,
                                position_offset=position_offset)
        emb = self.model.shared.embedding
        logits = dec @ emb.T.astype(dec.dtype)
        return logits + self.final_logits_bias.astype(logits.dtype)

    def partition_rules(self):
        return to_partition_rules(PARAM_LOGICAL_AXES)


class BartForTextInfill(nn.Module):
    """CBART lexically-constrained generation head
    (reference: fengshen/models/bart/modeling_bart.py:93-260
    `BartForTextInfill`): the ENCODER carries a per-token classification
    head predicting edit operations (copy / replace / insert counts) over
    the constrained input, while the DECODER reconstructs the full
    sequence; training optimises decoder CE + loss_weight × encoder CE
    with per-label weights (the reference's label_weights buffer).
    """

    config: BartConfig
    num_labels: int = 3  # copy / replace / insert (reference default)
    encoder_loss_type: int = 0  # 0 classification, 1 regression

    def setup(self):
        cfg = self.config
        self.model = BartModel(cfg, name="model")
        self.final_logits_bias = self.param(
            "final_logits_bias", nn.initializers.zeros,
            (cfg.vocab_size,), jnp.float32)
        out_dim = self.num_labels if self.encoder_loss_type == 0 else 1
        self.classification_dense = _dense(cfg, cfg.d_model,
                                           "classification_dense")
        self.classification_out = _dense(cfg, out_dim,
                                         "classification_out")

    def _encoder_logits(self, enc):
        h = jnp.tanh(self.classification_dense(enc))
        return self.classification_out(h)

    def __call__(self, input_ids, decoder_input_ids, attention_mask=None,
                 decoder_attention_mask=None, deterministic=True):
        enc, dec = self.model(input_ids, decoder_input_ids,
                              attention_mask, decoder_attention_mask,
                              deterministic)
        emb = self.model.shared.embedding
        lm_logits = dec @ emb.T.astype(dec.dtype) + \
            self.final_logits_bias.astype(dec.dtype)
        return lm_logits, self._encoder_logits(enc)

    def partition_rules(self):
        return to_partition_rules(PARAM_LOGICAL_AXES)


def text_infill_loss(lm_logits, labels, encoder_logits, encoder_labels,
                     loss_weight: float = 1.0, label_weights=None,
                     encoder_loss_type: int = 0):
    """decoder CE + loss_weight × encoder edit-op loss
    (reference: modeling_bart.py:207-245)."""
    from fengshen_tpu.parallel.cross_entropy import stable_cross_entropy
    dec_loss, _ = stable_cross_entropy(lm_logits, labels)
    if encoder_loss_type == 0:
        valid = encoder_labels != -100
        safe = jnp.where(valid, encoder_labels, 0)
        logp = jax.nn.log_softmax(encoder_logits.astype(jnp.float32), -1)
        token_ce = -jnp.take_along_axis(logp, safe[..., None], -1)[..., 0]
        if label_weights is not None:
            w = jnp.asarray(label_weights)[safe]
            token_ce = token_ce * w
        enc_loss = (token_ce * valid).sum() / jnp.maximum(valid.sum(), 1)
    else:  # regression on insert counts
        valid = encoder_labels >= 0
        diff = (encoder_logits[..., 0] -
                encoder_labels.astype(jnp.float32)) ** 2
        enc_loss = (diff * valid).sum() / jnp.maximum(valid.sum(), 1)
    total = dec_loss + loss_weight * enc_loss
    return total, {"decoder_loss": dec_loss, "encoder_loss": enc_loss}
