"""Reference UBERT checkpoint → flax params.

Reference state-dict naming (fengshen/models/ubert/modeling_ubert.py:
257-267): `bert.*` (plain HF BertModel tower), `query_layer.0` /
`key_layer.0` (Linear+GELU projections feeding the biaffine), and
`biaffine_query_key_cls.U` of shape [d+1, 1, d+1] (out_size=1). Our
`UbertModel` stores the same form as a 2-D `biaffine_u` (the singleton
out axis squeezed); query→start, key→end.
"""

from __future__ import annotations

from typing import Any, Mapping

from fengshen_tpu.utils.convert_common import (detect_bert_arch,
                                               encoder_tower_params,
                                               make_helpers, tensor,
                                               unwrap_lightning)


def torch_to_params(state_dict: Mapping[str, Any], config,
                    backbone_type: str | None = None) -> dict:
    sd = unwrap_lightning(state_dict)
    if backbone_type is None:
        backbone_type = detect_bert_arch(sd)
    _, lin, _ = make_helpers(sd)
    u = tensor(sd, "biaffine_query_key_cls.U")
    assert u.shape[1] == 1, f"ubert biaffine out_size != 1: {u.shape}"
    return {
        "bert": encoder_tower_params(sd, config, backbone_type),
        "start_mlp": lin("query_layer.0"),
        "end_mlp": lin("key_layer.0"),
        "biaffine_u": u[:, 0, :],
    }


#: fs→torch export: derived exact inverse of `torch_to_params`
#: (template_state = the source checkpoint: dict, Lightning ckpt, or dir)
from fengshen_tpu.utils.convert_common import (  # noqa: E402
    make_derived_export)

params_to_torch_state = make_derived_export(torch_to_params)
