"""UBERT — unified extraction via biaffine spans (reference:
fengshen/models/ubert/, 776 LoC self-contained model+pipeline)."""

from fengshen_tpu.models.ubert.modeling_ubert import (UbertModel,
                                                      UbertPipelines)

__all__ = ["UbertModel", "UbertPipelines"]
