"""UBERT: unified information extraction with a biaffine span scorer.

Behavioural port of reference: fengshen/models/ubert/ — task instruction +
entity-type prompt + text in one sequence; a biaffine head scores every
(start, end) span as belonging to the queried type; multi-label BCE loss.
"""

from __future__ import annotations

import argparse
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from fengshen_tpu.models.megatron_bert import MegatronBertConfig
from fengshen_tpu.models.towers import gelu_exact
from fengshen_tpu.models.megatron_bert.modeling_megatron_bert import (
    PARTITION_RULES, _dense)


class UbertModel(nn.Module):
    """Encoder + span biaffine with sigmoid scores.

    `backbone_type="bert"` matches the published Erlangshen-Ubert
    checkpoints (reference: fengshen/models/ubert/modeling_ubert.py:259
    `self.bert = BertModel(config)`)."""

    config: MegatronBertConfig
    biaffine_size: int = 128
    backbone_type: str = "megatron_bert"

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 span_labels=None, span_mask=None, deterministic=True):
        from fengshen_tpu.models.towers import encoder_tower
        cfg = self.config
        hidden, _ = encoder_tower(cfg, self.backbone_type)(
            input_ids, attention_mask, token_type_ids,
            deterministic=deterministic)
        start = gelu_exact(_dense(cfg, self.biaffine_size,
                                   "start_mlp")(hidden))
        end = gelu_exact(_dense(cfg, self.biaffine_size,
                                 "end_mlp")(hidden))
        U = self.param("biaffine_u", nn.initializers.normal(0.02),
                       (self.biaffine_size + 1, self.biaffine_size + 1),
                       jnp.float32)
        ones = jnp.ones(start.shape[:-1] + (1,), start.dtype)
        start = jnp.concatenate([start, ones], axis=-1)
        end = jnp.concatenate([end, ones], axis=-1)
        span_logits = jnp.einsum("bid,de,bje->bij", start,
                                 U.astype(start.dtype), end)
        if span_labels is None:
            return jax.nn.sigmoid(span_logits)
        # multi-label BCE over valid spans
        logp = jax.nn.log_sigmoid(span_logits)
        lognp = jax.nn.log_sigmoid(-span_logits)
        loss = -(span_labels * logp + (1 - span_labels) * lognp)
        if span_mask is not None:
            loss = loss * span_mask
            denom = jnp.maximum(span_mask.sum(), 1)
        else:
            denom = loss.size
        return loss.sum() / denom, jax.nn.sigmoid(span_logits)

    def partition_rules(self):
        return PARTITION_RULES


class UbertPipelines:
    """Reference contract: fengshen/models/ubert `UbertPipelines` —
    fit(train_data, dev_data) / predict(test_data) over instruction-style
    samples {task_type, subtask_type, text, choices:[{entity_type, ...}]}."""

    @staticmethod
    def pipelines_args(parent_parser: argparse.ArgumentParser):
        parser = parent_parser.add_argument_group("ubert")
        parser.add_argument("--max_length", default=512, type=int)
        parser.add_argument("--threshold", default=0.5, type=float)
        from fengshen_tpu.data import UniversalDataModule
        from fengshen_tpu.models.model_utils import add_module_args
        from fengshen_tpu.trainer import add_trainer_args
        from fengshen_tpu.utils import UniversalCheckpoint
        parent_parser = add_module_args(parent_parser)
        parent_parser = add_trainer_args(parent_parser)
        parent_parser = UniversalDataModule.add_data_specific_args(
            parent_parser)
        parent_parser = UniversalCheckpoint.add_argparse_args(parent_parser)
        return parent_parser

    def __init__(self, args=None, model: Optional[str] = None,
                 tokenizer=None, config=None, params=None,
                 backbone_type: str = "megatron_bert"):
        self.args = args
        if config is None and model is not None:
            config = MegatronBertConfig.from_pretrained(model)
        if config is None:
            config = MegatronBertConfig.small_test_config()
        self.config = config
        if tokenizer is None and model is not None:
            from transformers import AutoTokenizer
            tokenizer = AutoTokenizer.from_pretrained(model)
        self.tokenizer = tokenizer
        self.model = UbertModel(config, backbone_type=backbone_type)
        self.params = params

    def _encode(self, sample: dict, entity_type: str) -> dict:
        tok = self.tokenizer
        prompt = f"{sample.get('task_type', '抽取任务')}[SEP]{entity_type}"
        p_ids = tok.encode(prompt, add_special_tokens=False)
        t_ids = tok.encode(sample["text"], add_special_tokens=False)
        ids = [tok.cls_token_id] + p_ids + [tok.sep_token_id] + t_ids + \
            [tok.sep_token_id]
        text_offset = 2 + len(p_ids)
        max_len = getattr(self.args, "max_length", 512) if self.args else 512
        return {"input_ids": ids[:max_len], "text_offset": text_offset}

    def _collate_train(self, pairs: list[tuple]) -> dict:
        """(sample, choice) pairs → padded batch with span-label matrices
        (reference: fengshen/models/ubert UbertDataset span targets;
        entity_idx are char offsets into text — one char per wordpiece for
        Chinese BERT vocab, so token pos = text_offset + char idx)."""
        encoded = []
        for sample, choice in pairs:
            etype = choice["entity_type"] if isinstance(choice, dict) \
                else str(choice)
            enc = self._encode(sample, etype)
            spans = []
            if isinstance(choice, dict):
                for ent in choice.get("entity_list", []):
                    for s, e in ent.get("entity_idx", []):
                        spans.append((enc["text_offset"] + s,
                                      enc["text_offset"] + e))
            enc["spans"] = spans
            encoded.append(enc)
        # fixed max_length padding: per-batch max would give the jitted
        # train step a new shape (and XLA recompile) nearly every batch
        max_len = getattr(self.args, "max_length", 512) if self.args else 512
        pad_id = self.tokenizer.pad_token_id or 0
        batch = {"input_ids": [], "attention_mask": [], "span_labels": [],
                 "span_mask": []}
        for e in encoded:
            ids = e["input_ids"][:max_len]
            n = len(ids)
            p = max_len - n
            batch["input_ids"].append(ids + [pad_id] * p)
            batch["attention_mask"].append([1] * n + [0] * p)
            labels = np.zeros((max_len, max_len), np.float32)
            for s, t in e["spans"]:
                if s < n and t < n:
                    labels[s, t] = 1.0
            mask = np.zeros((max_len, max_len), np.float32)
            off = e["text_offset"]
            width = n - 1 - off
            if width > 0:  # prompt may fill the truncated sequence
                mask[off:n - 1, off:n - 1] = np.triu(
                    np.ones((width, width), np.float32))
            batch["span_labels"].append(labels)
            batch["span_mask"].append(mask)
        return {k: np.asarray(v) for k, v in batch.items()}

    def fit(self, train_data: list[dict],
            dev_data: Optional[list[dict]] = None) -> None:
        """Train on instruction-style samples (reference:
        fengshen/examples/ubert/example.py fit/predict driver)."""
        from fengshen_tpu.data import UniversalDataModule
        from fengshen_tpu.trainer import Trainer
        from fengshen_tpu.trainer.module import TrainModule

        pipe = self

        class _Module(TrainModule):
            def __init__(self, args):
                super().__init__(args)
                self.model = pipe.model

            def init_params(self, rng):
                return pipe.model.init(
                    rng, jnp.zeros((1, 16), jnp.int32))["params"]

            def training_loss(self, params, batch, rng):
                loss, _ = pipe.model.apply(
                    {"params": params}, batch["input_ids"],
                    attention_mask=batch["attention_mask"],
                    span_labels=batch["span_labels"],
                    span_mask=batch["span_mask"],
                    deterministic=False, rngs={"dropout": rng})
                return loss, {}

            def partition_rules(self):
                return pipe.model.partition_rules()

        def expand(rows):
            return [(s, ch) for s in rows for ch in s.get("choices", [])]

        class ListDS:
            def __init__(self, rows):
                self.rows = rows

            def __len__(self):
                return len(self.rows)

            def __getitem__(self, i):
                return self.rows[i]

        datasets = {"train": ListDS(expand(train_data))}
        if dev_data:
            datasets["validation"] = ListDS(expand(dev_data))
        dm = UniversalDataModule(tokenizer=self.tokenizer,
                                 collate_fn=self._collate_train,
                                 args=self.args, datasets=datasets)
        module = _Module(self.args)
        if self.params is not None:
            module.init_params = lambda rng: self.params
        trainer = Trainer(self.args)
        state = trainer.fit(module, dm)
        self.params = state.params

    def predict(self, data: list[dict]) -> list[dict]:
        if self.params is None:
            self.params = self.model.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
            )["params"]
        threshold = getattr(self.args, "threshold", 0.5) if self.args \
            else 0.5
        results = []
        for sample in data:
            out = {"text": sample["text"], "choices": []}
            for choice in sample.get("choices", []):
                etype = choice["entity_type"] if isinstance(choice, dict) \
                    else str(choice)
                enc = self._encode(sample, etype)
                ids = jnp.asarray([enc["input_ids"]], jnp.int32)
                scores = self.model.apply(
                    {"params": self.params}, ids,
                    attention_mask=jnp.ones_like(ids))
                from fengshen_tpu.models.span_utils import decode_spans
                entities = [
                    {"entity_type": etype, **ent}
                    for ent in decode_spans(
                        np.asarray(scores)[0], enc["input_ids"],
                        self.tokenizer, enc["text_offset"], threshold)]
                out["choices"].append({"entity_type": etype,
                                       "entity_list": entities})
            results.append(out)
        return results
