"""torch(HF) → jax weights for Taiyi-CLIP.

Importer for released Taiyi-CLIP checkpoints: a Chinese BertModel text
tower + HF CLIPVisionModel vision tower and the two projection heads
(reference: fengshen/examples/pretrain_taiyi_clip loads
BertForSequenceClassification + CLIPVisionModel from HF).
"""

from __future__ import annotations

from typing import Any, Mapping

from fengshen_tpu.models.clip.modeling_taiyi_clip import CLIPVisionConfig
from fengshen_tpu.utils.convert_common import bert_layer, make_helpers


def vision_to_params(state_dict: Mapping[str, Any],
                     config: CLIPVisionConfig,
                     prefix: str = "vision_model") -> dict:
    """HF CLIPVisionModel state dict → CLIPVisionTransformer params."""
    t, lin, ln = make_helpers(state_dict)

    def layer(i):
        p = f"{prefix}.encoder.layers.{i}"
        return {
            "layer_norm1": ln(f"{p}.layer_norm1"),
            "q_proj": lin(f"{p}.self_attn.q_proj"),
            "k_proj": lin(f"{p}.self_attn.k_proj"),
            "v_proj": lin(f"{p}.self_attn.v_proj"),
            "out_proj": lin(f"{p}.self_attn.out_proj"),
            "layer_norm2": ln(f"{p}.layer_norm2"),
            "fc1": lin(f"{p}.mlp.fc1"),
            "fc2": lin(f"{p}.mlp.fc2"),
        }

    params: dict = {
        # torch Conv2d [out, in, kh, kw] → flax [kh, kw, in, out]
        "patch_embedding": {
            "kernel": t(f"{prefix}.embeddings.patch_embedding.weight"
                        ).transpose(2, 3, 1, 0)},
        "class_embedding": t(f"{prefix}.embeddings.class_embedding"),
        "position_embedding":
            t(f"{prefix}.embeddings.position_embedding.weight"),
        "pre_layrnorm": ln(f"{prefix}.pre_layrnorm"),
        "post_layernorm": ln(f"{prefix}.post_layernorm"),
    }
    for i in range(config.num_hidden_layers):
        params[f"layer_{i}"] = layer(i)
    return params


def torch_to_params(text_state: Mapping[str, Any],
                    vision_state: Mapping[str, Any],
                    text_config, vision_config: CLIPVisionConfig,
                    text_projection=None, visual_projection=None,
                    logit_scale=None) -> dict:
    """Assemble full TaiyiCLIPModel params from the two towers."""
    import numpy as np

    from fengshen_tpu.models.bert.convert import model_to_params
    t, _, _ = make_helpers(vision_state)
    params: dict = {
        "text_model": model_to_params(text_state, text_config),
        "vision_model": vision_to_params(vision_state, vision_config),
    }
    if text_projection is not None:
        x = text_projection
        x = x.detach().cpu().float().numpy() if hasattr(x, "detach") else x
        params["text_projection"] = {"kernel": np.array(x).T}
    if visual_projection is not None:
        x = visual_projection
        x = x.detach().cpu().float().numpy() if hasattr(x, "detach") else x
        params["visual_projection"] = {"kernel": np.array(x).T}
    if logit_scale is not None:
        x = logit_scale
        x = x.detach().cpu().float().numpy() if hasattr(x, "detach") else x
        params["logit_scale"] = np.array(x, copy=True)
    return params


#: CLIPVisionTransformer params → HF vision state dict: derived exact
#: inverse of `vision_to_params` (import kwargs, e.g. `prefix`, pass
#: through so non-default-rooted checkpoints invert correctly)
from fengshen_tpu.utils.convert_common import (  # noqa: E402
    make_derived_export)

vision_params_to_torch_state = make_derived_export(vision_to_params)
