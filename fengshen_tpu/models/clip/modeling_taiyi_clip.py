"""Taiyi-CLIP: Chinese text tower + CLIP ViT, contrastive objective.

Reference: fengshen/models/clip/modeling_taiyi_clip.py — `TaiyiCLIPModel`
pairs an HF BertModel (Chinese text) with a CLIPVisionTransformer; training
is the standard symmetric InfoNCE with a learnable logit scale
(reference workload: fengshen/examples/pretrain_taiyi_clip/pretrain.py with
frozen-tower options).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from fengshen_tpu.sharding import to_partition_rules

from fengshen_tpu.models.bert import BertConfig, BertModel
from fengshen_tpu.ops.norms import LayerNorm

PARAM_LOGICAL_AXES: list[tuple[str, tuple]] = [
    ("word_embeddings/embedding", ("vocab", None)),
    (r"(query|key|value|q_proj|k_proj|v_proj)/kernel", ("embed", "heads")),
    (r"(fc1|intermediate_dense)/kernel", ("embed", "mlp")),
    (r"(attention_output_dense|out_proj)/kernel", ("heads", "embed")),
    (r"(output_dense|fc2)/kernel", ("mlp", "embed")),
    (".*", (None,)),
]
PARTITION_RULES = to_partition_rules(PARAM_LOGICAL_AXES)


@dataclasses.dataclass
class CLIPVisionConfig:
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    image_size: int = 224
    patch_size: int = 32
    projection_dim: int = 512
    layer_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    hidden_act: str = "gelu_new"   # CLIP uses quick_gelu; tanh approx close
    dtype: str = "float32"
    param_dtype: str = "float32"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def small_test_config(cls, **overrides: Any) -> "CLIPVisionConfig":
        base = dict(hidden_size=32, intermediate_size=64,
                    num_hidden_layers=2, num_attention_heads=4,
                    image_size=32, patch_size=8, projection_dim=16)
        base.update(overrides)
        return cls(**base)


def _dt(cfg):
    return jnp.dtype(cfg.dtype)


def quick_gelu(x):
    return x * jax.nn.sigmoid(1.702 * x)


class CLIPVisionLayer(nn.Module):
    """Pre-LN transformer block (CLIP ViT convention)."""

    config: CLIPVisionConfig

    @nn.compact
    def __call__(self, hidden):
        cfg = self.config
        batch, seq, _ = hidden.shape
        n_head, head_dim = cfg.num_attention_heads, cfg.head_dim
        dense = lambda feats, name: nn.Dense(  # noqa: E731
            feats, dtype=_dt(cfg), param_dtype=jnp.dtype(cfg.param_dtype),
            kernel_init=nn.initializers.normal(cfg.initializer_range),
            name=name)
        h = LayerNorm(epsilon=cfg.layer_norm_eps, name="layer_norm1")(hidden)
        q = dense(cfg.hidden_size, "q_proj")(h).reshape(
            batch, seq, n_head, head_dim)
        k = dense(cfg.hidden_size, "k_proj")(h).reshape(
            batch, seq, n_head, head_dim)
        v = dense(cfg.hidden_size, "v_proj")(h).reshape(
            batch, seq, n_head, head_dim)
        scale = 1.0 / jnp.sqrt(jnp.asarray(head_dim, jnp.float32))
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                            preferred_element_type=jnp.float32) * scale
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
        out = dense(cfg.hidden_size, "out_proj")(
            out.reshape(batch, seq, cfg.hidden_size))
        hidden = hidden + out
        h = LayerNorm(epsilon=cfg.layer_norm_eps, name="layer_norm2")(hidden)
        h = quick_gelu(dense(cfg.intermediate_size, "fc1")(h))
        h = dense(cfg.hidden_size, "fc2")(h)
        return hidden + h


class CLIPVisionTransformer(nn.Module):
    config: CLIPVisionConfig

    @nn.compact
    def __call__(self, pixel_values):
        """pixel_values [B, H, W, 3] → (last_hidden [B, 1+P, D],
        pooled [B, D])."""
        cfg = self.config
        batch = pixel_values.shape[0]
        patches = nn.Conv(cfg.hidden_size,
                          (cfg.patch_size, cfg.patch_size),
                          strides=(cfg.patch_size, cfg.patch_size),
                          use_bias=False, dtype=_dt(cfg),
                          param_dtype=jnp.dtype(cfg.param_dtype),
                          name="patch_embedding")(pixel_values)
        patches = patches.reshape(batch, -1, cfg.hidden_size)
        cls = self.param("class_embedding",
                         nn.initializers.normal(cfg.initializer_range),
                         (cfg.hidden_size,), jnp.dtype(cfg.param_dtype))
        hidden = jnp.concatenate(
            [jnp.broadcast_to(cls[None, None],
                              (batch, 1, cfg.hidden_size)).astype(
                patches.dtype), patches], axis=1)
        n_pos = hidden.shape[1]
        pos = self.param("position_embedding",
                         nn.initializers.normal(cfg.initializer_range),
                         (n_pos, cfg.hidden_size),
                         jnp.dtype(cfg.param_dtype))
        hidden = hidden + pos[None].astype(hidden.dtype)
        hidden = LayerNorm(epsilon=cfg.layer_norm_eps,
                           name="pre_layrnorm")(hidden)
        for i in range(cfg.num_hidden_layers):
            hidden = CLIPVisionLayer(cfg, name=f"layer_{i}")(hidden)
        pooled = LayerNorm(epsilon=cfg.layer_norm_eps,
                           name="post_layernorm")(hidden[:, 0])
        return hidden, pooled


class TaiyiCLIPModel(nn.Module):
    """Chinese-BERT text tower + CLIP ViT, joint embedding space."""

    text_config: BertConfig
    vision_config: CLIPVisionConfig

    @nn.compact
    def __call__(self, input_ids=None, pixel_values=None,
                 attention_mask=None, deterministic=True):
        text_emb = image_emb = None
        if input_ids is not None:
            text_emb = self.get_text_features(input_ids, attention_mask,
                                              deterministic)
        if pixel_values is not None:
            image_emb = self.get_image_features(pixel_values)
        scale = self.param("logit_scale",
                           lambda rng, shape: jnp.full(shape,
                                                       np.log(1 / 0.07)),
                           ())
        return text_emb, image_emb, jnp.exp(scale)

    def get_text_features(self, input_ids, attention_mask=None,
                          deterministic=True):
        hidden, _ = BertModel(self.text_config, add_pooling_layer=False,
                              name="text_model")(
            input_ids, attention_mask, deterministic=deterministic)
        # Taiyi uses the [CLS] hidden projected to the shared space
        proj = nn.Dense(self.vision_config.projection_dim, use_bias=False,
                        dtype=_dt(self.vision_config),
                        param_dtype=jnp.dtype(
                            self.vision_config.param_dtype),
                        name="text_projection")(hidden[:, 0])
        return proj / jnp.linalg.norm(proj, axis=-1, keepdims=True)

    def get_image_features(self, pixel_values):
        _, pooled = CLIPVisionTransformer(self.vision_config,
                                          name="vision_model")(pixel_values)
        proj = nn.Dense(self.vision_config.projection_dim, use_bias=False,
                        dtype=_dt(self.vision_config),
                        param_dtype=jnp.dtype(
                            self.vision_config.param_dtype),
                        name="visual_projection")(pooled)
        return proj / jnp.linalg.norm(proj, axis=-1, keepdims=True)

    def partition_rules(self):
        return to_partition_rules(PARAM_LOGICAL_AXES)


def clip_contrastive_loss(text_emb, image_emb, logit_scale):
    """Symmetric InfoNCE (reference:
    fengshen/examples/pretrain_taiyi_clip/pretrain.py training_step)."""
    logits = text_emb @ image_emb.T * logit_scale
    n = logits.shape[0]
    labels = jnp.arange(n)
    loss_t = -jax.nn.log_softmax(logits, axis=1)[labels, labels].mean()
    loss_i = -jax.nn.log_softmax(logits, axis=0)[labels, labels].mean()
    return (loss_t + loss_i) / 2, logits
