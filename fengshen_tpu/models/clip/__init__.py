"""Taiyi-CLIP family (reference: fengshen/models/clip/ — Chinese CLIP:
BertModel text tower + CLIPVisionTransformer,
modeling_taiyi_clip.py:27-29)."""

from fengshen_tpu.models.clip.modeling_taiyi_clip import (
    CLIPVisionConfig, CLIPVisionTransformer, TaiyiCLIPModel,
    clip_contrastive_loss)

__all__ = ["CLIPVisionConfig", "CLIPVisionTransformer", "TaiyiCLIPModel",
           "clip_contrastive_loss"]
