"""Latent-text VAE family (reference: fengshen/models/DAVAE 1,329 LoC,
GAVAE 551, PPVAE 232, deepVAE 947 — GPT2-based latent connectors for
controlled text generation)."""

from fengshen_tpu.models.vae.modeling_vae import (TextVAEConfig,
                                                  LatentConnector,
                                                  TextVAEModel, vae_loss)

__all__ = ["TextVAEConfig", "LatentConnector", "TextVAEModel", "vae_loss"]
