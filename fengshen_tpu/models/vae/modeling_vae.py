"""Latent-text VAE: GPT-2 encoder → gaussian latent → GPT-2 decoder.

Behavioural port of the reference's VAE family core (reference:
fengshen/models/DAVAE/DAVAEModel — GPT2-based latent connectors where the
posterior comes from the encoder's final hidden state and the decoder is
conditioned on the latent via an injected embedding; GAVAE/PPVAE add
GAN/plug-in objectives on the same skeleton; deepVAE's Della stacks
per-layer latents).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from fengshen_tpu.models.gpt2 import GPT2Config, GPT2Model
from fengshen_tpu.parallel.cross_entropy import stable_cross_entropy


@dataclasses.dataclass
class TextVAEConfig:
    latent_size: int = 128
    beta: float = 1.0          # KL weight
    encoder: GPT2Config = None
    decoder: GPT2Config = None

    @classmethod
    def small_test_config(cls, **overrides: Any) -> "TextVAEConfig":
        enc = GPT2Config.small_test_config(dtype="float32")
        dec = GPT2Config.small_test_config(dtype="float32")
        base = dict(latent_size=8, encoder=enc, decoder=dec)
        base.update(overrides)
        return cls(**base)


class LatentConnector(nn.Module):
    """hidden → (mean, logvar); latent → decoder conditioning vector."""

    latent_size: int
    hidden_size: int

    def setup(self):
        self.posterior = nn.Dense(2 * self.latent_size, name="posterior")
        self.latent_proj = nn.Dense(self.hidden_size, name="latent_proj")

    def encode(self, pooled):
        stats = self.posterior(pooled)
        mean, logvar = jnp.split(stats, 2, axis=-1)
        return mean, logvar

    def to_conditioning(self, latent):
        return self.latent_proj(latent)

    def __call__(self, pooled):  # init path
        mean, logvar = self.encode(pooled)
        return self.to_conditioning(mean), mean, logvar


class TextVAEModel(nn.Module):
    config: TextVAEConfig

    def setup(self):
        self.encoder = GPT2Model(self.config.encoder, name="encoder")
        self.decoder = GPT2Model(self.config.decoder, name="decoder")
        self.connector = LatentConnector(
            self.config.latent_size, self.config.decoder.n_embd,
            name="connector")
        self.lm_head = nn.Dense(self.config.decoder.vocab_size,
                                use_bias=False, name="lm_head")

    def encode(self, input_ids, attention_mask=None, deterministic=True):
        hidden = self.encoder(input_ids, attention_mask=attention_mask,
                              deterministic=deterministic)
        # posterior from the last real token's hidden state
        if attention_mask is not None:
            last = attention_mask.sum(-1) - 1
        else:
            last = jnp.full((input_ids.shape[0],), input_ids.shape[1] - 1)
        pooled = jnp.take_along_axis(hidden, last[:, None, None],
                                     axis=1)[:, 0]
        return self.connector.encode(pooled)

    def decode_logits(self, latent, input_ids, deterministic=True):
        """Teacher-forced reconstruction, latent added to every position
        (the reference's embedding-injection conditioning)."""
        cond = self.connector.to_conditioning(latent)[:, None, :]
        hidden = self.decoder(input_ids, deterministic=deterministic)
        hidden = hidden + cond.astype(hidden.dtype)
        return self.lm_head(hidden)

    def __call__(self, input_ids, attention_mask=None, rng=None,
                 deterministic=True):
        mean, logvar = self.encode(input_ids, attention_mask, deterministic)
        if rng is not None:
            eps = jax.random.normal(rng, mean.shape)
            latent = mean + jnp.exp(0.5 * logvar) * eps
        else:
            latent = mean
        logits = self.decode_logits(latent, input_ids, deterministic)
        return logits, mean, logvar


def vae_loss(logits, input_ids, mean, logvar, beta: float = 1.0,
             ignore_index: int = -100):
    """Reconstruction CE + beta·KL(q(z|x) ‖ N(0,I))."""
    recon, _ = stable_cross_entropy(logits[:, :-1], input_ids[:, 1:],
                                    ignore_index)
    kl = 0.5 * (jnp.exp(logvar) + mean ** 2 - 1.0 - logvar).sum(-1).mean()
    return recon + beta * kl, {"recon": recon, "kl": kl}
