"""Model zoo (reference: fengshen/models/ — 25 sub-packages, SURVEY.md §2.5).

Each family lives in its own subpackage with an HF-style ``XConfig`` +
flax module + torch→jax weight importer. Shared optimizer/scheduler
factories live in ``model_utils`` (reference: fengshen/models/model_utils.py).
"""
