"""UniMC — label-as-option MRC classification (reference:
fengshen/models/unimc/, FewCLUE/ZeroCLUE SOTA per SURVEY.md §6)."""

from fengshen_tpu.models.unimc.modeling_unimc import (UniMCModel,
                                                      UniMCPipelines)

__all__ = ["UniMCModel", "UniMCPipelines"]
