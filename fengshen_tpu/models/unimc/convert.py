"""Reference UniMC checkpoint → flax params.

The reference `UniMCModel` is an MLM tower under the attr `bert`
(reference: fengshen/models/unimc/modeling_unimc.py:297-310 — dispatching
on model_type between MegatronBertForMaskedLM / BertForMaskedLM / Albert /
DebertaV2) and NO extra head parameters: option scoring reads the
yes-token logit at each option's mask position. So importing is tower
delegation: strip the `bert.` attr prefix and run the matching backbone
converter with its MLM head.
"""

from __future__ import annotations

from typing import Any, Mapping

from fengshen_tpu.utils.convert_common import (detect_bert_arch,
                                               strip_prefix,
                                               unwrap_lightning)


def torch_to_params(state_dict: Mapping[str, Any], config,
                    backbone_type: str | None = None) -> dict:
    """Returns {"backbone": <ForMaskedLM params>} matching `UniMCModel`.

    Accepts a UniMCLitModel checkpoint (`model.bert.*`), a bare UniMCModel
    state dict (`bert.*`), or a raw ForMaskedLM state dict.
    """
    sd = unwrap_lightning(state_dict)
    if any(k.startswith("bert.bert.") or k.startswith("bert.cls.")
           for k in sd):
        sd = strip_prefix(sd, "bert.")
    if backbone_type is None:
        backbone_type = detect_bert_arch(sd)
    if backbone_type == "bert":
        from fengshen_tpu.models.bert.convert import torch_to_params as conv
        return {"backbone": conv(sd, config)}
    from fengshen_tpu.models.megatron_bert.convert import \
        torch_to_params as conv
    return {"backbone": conv(sd, config, head="masked_lm")}


#: fs→torch export: derived exact inverse of `torch_to_params`
#: (template_state = the source checkpoint: dict, Lightning ckpt, or dir)
from fengshen_tpu.utils.convert_common import (  # noqa: E402
    make_derived_export)

params_to_torch_state = make_derived_export(torch_to_params)
