"""UniMC: unified multiple-choice classification via option masks.

Behavioural port of reference: fengshen/models/unimc/ (`UniMCModel` +
`UniMCPipelines`, 660 LoC) — zero/few-shot classification reformulated as
MRC: every label becomes an option prefixed with a special option-mask
token; the MLM head scores a "yes" token at each option's mask position and
the option with the highest score wins. Training minimises CE over option
positions.
"""

from __future__ import annotations

import argparse
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from fengshen_tpu.models.megatron_bert import MegatronBertConfig


class UniMCModel(nn.Module):
    """MLM backbone + option-position scoring.

    `backbone_type` selects the tower the checkpoint was trained with
    (reference: fengshen/models/unimc/modeling_unimc.py:297-308 dispatches
    on config.model_type between MegatronBert / Bert / Albert / DebertaV2;
    the published UniMC-MegatronBERT-1.3B is megatron_bert, the RoBERTa
    variants are bert-architecture).
    """

    config: MegatronBertConfig
    yes_token_id: int = 1
    backbone_type: str = "megatron_bert"

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 option_positions=None, deterministic=True):
        """option_positions: [B, n_options] indices of each option's mask
        token. Returns per-option scores [B, n_options]."""
        from fengshen_tpu.models.towers import mlm_tower
        logits = mlm_tower(self.config, self.backbone_type)(
            input_ids, attention_mask, token_type_ids,
            deterministic=deterministic)
        if option_positions is None:
            return logits
        # score of the yes-token at each option mask position
        pos_logits = jnp.take_along_axis(
            logits,
            jnp.broadcast_to(option_positions[..., None],
                             option_positions.shape +
                             (logits.shape[-1],)), axis=1)
        return pos_logits[..., self.yes_token_id]

    def partition_rules(self):
        from fengshen_tpu.models.megatron_bert.modeling_megatron_bert \
            import PARTITION_RULES
        return PARTITION_RULES


class UniMCPipelines:
    """Reference: fengshen/pipelines/multiplechoice.py:41 wraps the
    self-contained model; contract: train(data) / predict(data)."""

    @staticmethod
    def add_pipeline_specific_args(parent_parser: argparse.ArgumentParser):
        parser = parent_parser.add_argument_group("unimc")
        parser.add_argument("--max_length", default=512, type=int)
        from fengshen_tpu.data import UniversalDataModule
        from fengshen_tpu.models.model_utils import add_module_args
        from fengshen_tpu.trainer import add_trainer_args
        from fengshen_tpu.utils import UniversalCheckpoint
        parent_parser = add_module_args(parent_parser)
        parent_parser = add_trainer_args(parent_parser)
        parent_parser = UniversalDataModule.add_data_specific_args(
            parent_parser)
        parent_parser = UniversalCheckpoint.add_argparse_args(parent_parser)
        return parent_parser

    def __init__(self, args=None, model: Optional[str] = None,
                 tokenizer=None, config=None, params=None,
                 backbone_type: str = "megatron_bert"):
        self.args = args
        if config is None and model is not None:
            config = MegatronBertConfig.from_pretrained(model)
        if config is None:
            config = MegatronBertConfig.small_test_config()
        self.config = config
        if tokenizer is None and model is not None:
            from transformers import AutoTokenizer
            tokenizer = AutoTokenizer.from_pretrained(model)
        self.tokenizer = tokenizer
        yes_id = 1
        if tokenizer is not None:
            ids = tokenizer.convert_tokens_to_ids(["是"])
            if ids and ids[0] != tokenizer.unk_token_id:
                yes_id = ids[0]
        self.model = UniMCModel(config, yes_token_id=yes_id,
                                backbone_type=backbone_type)
        self.params = params

    def _encode(self, sample: dict) -> dict:
        """sample: {texta, choices: [...], label?}. Layout:
        [CLS] [MASK] opt1 [SEP] [MASK] opt2 [SEP] ... text [SEP]"""
        tok = self.tokenizer
        ids = [tok.cls_token_id]
        option_positions = []
        for choice in sample["choices"]:
            option_positions.append(len(ids))
            ids.append(tok.mask_token_id)
            ids.extend(tok.encode(choice, add_special_tokens=False))
            ids.append(tok.sep_token_id)
        ids.extend(tok.encode(sample.get("texta", ""),
                              add_special_tokens=False))
        ids.append(tok.sep_token_id)
        max_len = getattr(self.args, "max_length", 512) if self.args else 512
        ids = ids[:max_len]
        return {"input_ids": ids, "option_positions": option_positions,
                "label": sample.get("label")}

    def _collate(self, samples: list[dict]) -> dict:
        encoded = [self._encode(s) for s in samples]
        max_len = max(len(e["input_ids"]) for e in encoded)
        n_opt = max(len(e["option_positions"]) for e in encoded)
        pad = self.tokenizer.pad_token_id or 0
        batch = {"input_ids": [], "attention_mask": [],
                 "option_positions": [], "labels": []}
        for e in encoded:
            p = max_len - len(e["input_ids"])
            batch["input_ids"].append(e["input_ids"] + [pad] * p)
            batch["attention_mask"].append([1] * len(e["input_ids"]) +
                                           [0] * p)
            opts = e["option_positions"] + [0] * (
                n_opt - len(e["option_positions"]))
            batch["option_positions"].append(opts)
            batch["labels"].append(e["label"] if e["label"] is not None
                                   else -100)
        return {k: np.asarray(v) for k, v in batch.items()}

    def train(self, train_data: list[dict],
              dev_data: Optional[list[dict]] = None) -> None:
        from fengshen_tpu.data import UniversalDataModule
        from fengshen_tpu.parallel.cross_entropy import stable_cross_entropy
        from fengshen_tpu.trainer import Trainer
        from fengshen_tpu.trainer.module import TrainModule

        pipe = self

        class _Module(TrainModule):
            def __init__(self, args):
                super().__init__(args)
                self.model = pipe.model
                self.config = pipe.config

            def init_params(self, rng):
                return self.model.init(
                    rng, jnp.zeros((1, 16), jnp.int32))["params"]

            def training_loss(self, params, batch, rng):
                scores = self.model.apply(
                    {"params": params}, batch["input_ids"],
                    attention_mask=batch["attention_mask"],
                    option_positions=batch["option_positions"],
                    deterministic=False, rngs={"dropout": rng})
                loss, _ = stable_cross_entropy(scores[:, None, :],
                                               batch["labels"][:, None])
                acc = (scores.argmax(-1) == batch["labels"]).mean()
                return loss, {"acc": acc}

            def partition_rules(self):
                return self.model.partition_rules()

        class ListDS:
            def __init__(self, rows):
                self.rows = rows

            def __len__(self):
                return len(self.rows)

            def __getitem__(self, i):
                return self.rows[i]

        datasets = {"train": ListDS(train_data)}
        if dev_data:
            datasets["validation"] = ListDS(dev_data)
        dm = UniversalDataModule(tokenizer=self.tokenizer,
                                 collate_fn=self._collate, args=self.args,
                                 datasets=datasets)
        module = _Module(self.args)
        if self.params is not None:
            module.init_params = lambda rng: self.params
        trainer = Trainer(self.args)
        state = trainer.fit(module, dm)
        self.params = state.params

    def predict(self, data: list[dict]) -> list[int]:
        if self.params is None:
            self.params = self.model.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
            )["params"]
        batch = self._collate(data)
        scores = self.model.apply(
            {"params": self.params},
            jnp.asarray(batch["input_ids"], jnp.int32),
            attention_mask=jnp.asarray(batch["attention_mask"], jnp.int32),
            option_positions=jnp.asarray(batch["option_positions"],
                                         jnp.int32))
        return [int(x) for x in np.asarray(scores.argmax(-1))]
