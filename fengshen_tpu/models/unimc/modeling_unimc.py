"""UniMC: unified multiple-choice classification via option masks.

Behavioural port of reference: fengshen/models/unimc/ (`UniMCModel` +
`UniMCPipelines`, 660 LoC) — zero/few-shot classification reformulated as
MRC: every label becomes an option prefixed with a special option-mask
token; the MLM head scores a "yes" token at each option's mask position and
the option with the highest score wins. Training minimises CE over option
positions.
"""

from __future__ import annotations

import argparse
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from fengshen_tpu.models.megatron_bert import MegatronBertConfig


class UniMCModel(nn.Module):
    """MLM backbone + option-position scoring.

    `backbone_type` selects the tower the checkpoint was trained with
    (reference: fengshen/models/unimc/modeling_unimc.py:297-308 dispatches
    on config.model_type between MegatronBert / Bert / Albert / DebertaV2;
    the published UniMC-MegatronBERT-1.3B is megatron_bert, the RoBERTa
    variants are bert-architecture).
    """

    config: MegatronBertConfig
    yes_token_id: int = 1
    backbone_type: str = "megatron_bert"

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 option_positions=None, position_ids=None,
                 deterministic=True):
        """option_positions: [B, n_options] indices of each option's mask
        token. Returns per-option scores [B, n_options].

        attention_mask may be [B, S] (padding) or [B, S, S] (the
        reference's block-diagonal option masking); position_ids carry
        the reference's option-wise position restarts (megatron backbone
        only — reference: modeling_unimc.py:73-113)."""
        from fengshen_tpu.models.towers import mlm_tower
        logits = mlm_tower(self.config, self.backbone_type)(
            input_ids, attention_mask, token_type_ids,
            position_ids=position_ids, deterministic=deterministic)
        if option_positions is None:
            return logits
        # score of the yes-token at each option mask position
        pos_logits = jnp.take_along_axis(
            logits,
            jnp.broadcast_to(option_positions[..., None],
                             option_positions.shape +
                             (logits.shape[-1],)), axis=1)
        return pos_logits[..., self.yes_token_id]

    def partition_rules(self):
        from fengshen_tpu.models.megatron_bert.modeling_megatron_bert \
            import PARTITION_RULES
        return PARTITION_RULES


def encode_unimc(item: dict, tokenizer, max_length: int = 512) -> dict:
    """THE UniMC encoding, shared by training, predict, and the CLUE
    harness — a faithful restatement of the reference UniMCDataset.encode
    (modeling_unimc.py:140-231, minus the MLM corruption): '[MASK]'-joined
    options, block-diagonal option attention, option-wise position
    restarts, yes-token scoring positions. Accepts the reference data
    format ({texta, textb, question, choice, label}) and the legacy
    `choices` key."""
    choice = list(item.get("choice") or item.get("choices") or [])
    while len(tokenizer.encode("[MASK]".join(choice))) > max_length - 32 \
            and any(len(c) > 1 for c in choice):
        choice = [c[: max(len(c) // 2, 1)] for c in choice]

    texta = "[MASK]" + "[MASK]".join(choice)
    if item.get("question"):
        texta += "[SEP]" + item["question"]
    texta += "[SEP]" + item.get("texta", "")
    if item.get("textb"):
        texta += "[SEP]" + item["textb"]

    enc = tokenizer.encode_plus(texta, max_length=max_length,
                                truncation="longest_first")
    ids = enc["input_ids"]
    n = len(ids)

    question_len = 1
    label_idx = [question_len]
    for c in choice:
        label_idx.append(label_idx[-1] + 1 + len(
            tokenizer.encode(c, add_special_tokens=False)))

    # block-diagonal option attention (reference get_att_mask :92-113):
    # options cannot see each other; everything else attends fully
    att = np.ones((n, n), np.int32)
    lo, hi = question_len, min(label_idx[-1], n)
    att[lo:hi, lo:hi] = 0
    for i in range(len(label_idx) - 1):
        a, b = min(label_idx[i], n), min(label_idx[i + 1], n)
        att[a:b, a:b] = 1

    # option-wise position restarts (reference get_position_ids :73-90)
    pos = list(range(question_len))
    for i in range(len(label_idx) - 1):
        pos.extend(range(question_len,
                         question_len + label_idx[i + 1] - label_idx[i]))
    start = max(pos) + 1 if pos else 1
    pos.extend(range(start, start + max(n - label_idx[-1], 0)))
    pos = [min(p, 511) for p in (pos + [511] * n)[:n]]

    token_type = [0] * question_len + [1] * (label_idx[-1] - label_idx[0]
                                             + 1)
    token_type = (token_type + [0] * n)[:n]

    ids = np.asarray(ids)
    opt_pos = [p for p in label_idx[:-1] if p < n]
    ids[opt_pos] = tokenizer.mask_token_id
    label = item.get("label")
    return {"input_ids": ids, "attention_mask": att,
            "token_type_ids": np.asarray(token_type),
            "position_ids": np.asarray(pos),
            "option_positions": opt_pos,
            "label": int(label) if label is not None else -100}


def collate_unimc(encoded: list[dict]) -> dict:
    """Pad a list of encode_unimc outputs into one batch (2-D per-sample
    attention masks, option_mask marking real options)."""
    max_len = max(len(e["input_ids"]) for e in encoded)
    n_opt = max(len(e["option_positions"]) for e in encoded)
    batch = {k: [] for k in ("input_ids", "attention_mask",
                             "token_type_ids", "position_ids",
                             "option_positions", "option_mask", "labels")}
    for e in encoded:
        n = len(e["input_ids"])
        p = max_len - n
        batch["input_ids"].append(np.pad(e["input_ids"], (0, p)))
        att = np.zeros((max_len, max_len), np.int32)
        att[:n, :n] = e["attention_mask"]
        batch["attention_mask"].append(att)
        batch["token_type_ids"].append(np.pad(e["token_type_ids"],
                                              (0, p)))
        batch["position_ids"].append(np.pad(e["position_ids"], (0, p)))
        opts = e["option_positions"]
        batch["option_positions"].append(opts + [0] * (n_opt - len(opts)))
        batch["option_mask"].append([1] * len(opts) +
                                    [0] * (n_opt - len(opts)))
        batch["labels"].append(e["label"])
    return {k: np.asarray(v) for k, v in batch.items()}


class UniMCPipelines:
    """Reference: fengshen/pipelines/multiplechoice.py:41 wraps the
    self-contained model; contract: train(data) / predict(data)."""

    @staticmethod
    def add_pipeline_specific_args(parent_parser: argparse.ArgumentParser):
        parser = parent_parser.add_argument_group("unimc")
        parser.add_argument("--max_length", default=512, type=int)
        from fengshen_tpu.data import UniversalDataModule
        from fengshen_tpu.models.model_utils import add_module_args
        from fengshen_tpu.trainer import add_trainer_args
        from fengshen_tpu.utils import UniversalCheckpoint
        parent_parser = add_module_args(parent_parser)
        parent_parser = add_trainer_args(parent_parser)
        parent_parser = UniversalDataModule.add_data_specific_args(
            parent_parser)
        parent_parser = UniversalCheckpoint.add_argparse_args(parent_parser)
        return parent_parser

    def __init__(self, args=None, model: Optional[str] = None,
                 tokenizer=None, config=None, params=None,
                 backbone_type: str = "megatron_bert"):
        self.args = args
        if config is None and model is not None:
            config = MegatronBertConfig.from_pretrained(model)
        if config is None:
            config = MegatronBertConfig.small_test_config()
        self.config = config
        if tokenizer is None and model is not None:
            from transformers import AutoTokenizer
            tokenizer = AutoTokenizer.from_pretrained(model)
        self.tokenizer = tokenizer
        yes_id = 1
        if tokenizer is not None:
            ids = tokenizer.convert_tokens_to_ids(["是"])
            if ids and ids[0] != tokenizer.unk_token_id:
                yes_id = ids[0]
        self.model = UniMCModel(config, yes_token_id=yes_id,
                                backbone_type=backbone_type)
        if params is None and model is not None:
            # import reference-format torch weights when the dir has them
            from fengshen_tpu.models.unimc.convert import torch_to_params
            from fengshen_tpu.utils.convert_common import \
                load_torch_checkpoint
            try:
                state = load_torch_checkpoint(model)
            except FileNotFoundError:
                state = None
            if state is not None:
                params = torch_to_params(state, config,
                                         backbone_type=backbone_type)
        self.params = params

    def _encode(self, sample: dict) -> dict:
        max_len = getattr(self.args, "max_length", 512) if self.args else 512
        return encode_unimc(sample, self.tokenizer, max_len)

    def _collate(self, samples: list[dict]) -> dict:
        return collate_unimc([self._encode(s) for s in samples])

    def train(self, train_data: list[dict],
              dev_data: Optional[list[dict]] = None) -> None:
        from fengshen_tpu.data import UniversalDataModule
        from fengshen_tpu.parallel.cross_entropy import stable_cross_entropy
        from fengshen_tpu.trainer import Trainer
        from fengshen_tpu.trainer.module import TrainModule

        pipe = self

        class _Module(TrainModule):
            def __init__(self, args):
                super().__init__(args)
                self.model = pipe.model
                self.config = pipe.config

            def init_params(self, rng):
                return self.model.init(
                    rng, jnp.zeros((1, 16), jnp.int32))["params"]

            def training_loss(self, params, batch, rng):
                scores = self.model.apply(
                    {"params": params}, batch["input_ids"],
                    attention_mask=batch["attention_mask"],
                    token_type_ids=batch["token_type_ids"],
                    option_positions=batch["option_positions"],
                    position_ids=batch["position_ids"],
                    deterministic=False, rngs={"dropout": rng})
                scores = scores + (batch["option_mask"] - 1.0) * 1e4
                loss, _ = stable_cross_entropy(scores[:, None, :],
                                               batch["labels"][:, None])
                acc = (scores.argmax(-1) == batch["labels"]).mean()
                return loss, {"acc": acc}

            def partition_rules(self):
                return self.model.partition_rules()

        class ListDS:
            def __init__(self, rows):
                self.rows = rows

            def __len__(self):
                return len(self.rows)

            def __getitem__(self, i):
                return self.rows[i]

        datasets = {"train": ListDS(train_data)}
        if dev_data:
            datasets["validation"] = ListDS(dev_data)
        dm = UniversalDataModule(tokenizer=self.tokenizer,
                                 collate_fn=self._collate, args=self.args,
                                 datasets=datasets)
        module = _Module(self.args)
        if self.params is not None:
            module.init_params = lambda rng: self.params
        trainer = Trainer(self.args)
        state = trainer.fit(module, dm)
        self.params = state.params

    def predict(self, data: list[dict]) -> list[int]:
        if self.params is None:
            self.params = self.model.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
            )["params"]
        batch = self._collate(data)
        scores = self.model.apply(
            {"params": self.params},
            jnp.asarray(batch["input_ids"], jnp.int32),
            attention_mask=jnp.asarray(batch["attention_mask"], jnp.int32),
            token_type_ids=jnp.asarray(batch["token_type_ids"],
                                       jnp.int32),
            option_positions=jnp.asarray(batch["option_positions"],
                                         jnp.int32),
            position_ids=jnp.asarray(batch["position_ids"], jnp.int32))
        scores = np.asarray(scores) + (batch["option_mask"] - 1.0) * 1e4
        return [int(x) for x in scores.argmax(-1)]
