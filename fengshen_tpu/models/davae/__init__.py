"""DAVAE family (reference: fengshen/models/DAVAE/, 1,329 LoC)."""

from fengshen_tpu.models.davae.modeling_davae import (
    DAVAEConfig, DAVAEModel, LatentCritic, davae_losses, word_dropout,
    latent_code_from_text_batch, text_from_latent_code_batch,
    simulate_batch)

__all__ = ["DAVAEConfig", "DAVAEModel", "LatentCritic", "davae_losses",
           "word_dropout", "latent_code_from_text_batch",
           "text_from_latent_code_batch", "simulate_batch"]
