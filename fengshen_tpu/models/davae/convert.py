"""torch → jax weights for DAVAE (adversarial text VAE).

Reference state-dict naming (fengshen/models/DAVAE/DAVAEModel.py:35-140):
everything lives under `vae_model.` (the EncDecAAE) —
`vae_model.encoder.*` is a BertForLatentConnector (bert tower held
directly: embeddings/encoder/pooler + bias-free `linear` → 2·latent,
BertForLatentConnector.py:64-71), `vae_model.decoder.*` is a
GPT2ModelForLatent (the GLM relative transformer + `transformer.
linear_emb`, GPT2ModelForLatent.py:581-620), and `vae_model.Disc.{0,2}`
is the AAE critic. Import target: DAVAEModel(relative_decoder=True).
"""

from __future__ import annotations

from typing import Any, Mapping

from fengshen_tpu.utils.convert_common import (make_helpers, strip_prefix,
                                               tensor, unwrap_lightning)


def torch_to_params(state_dict: Mapping[str, Any], config) -> dict:
    sd = unwrap_lightning(state_dict)
    if any(k.startswith("vae_model.") for k in sd):
        sd = strip_prefix(sd, "vae_model.")

    # encoder tower: BertForLatentConnector holds embeddings/encoder/
    # pooler at its top level, like a bare BertModel state dict
    enc_sd = strip_prefix(sd, "encoder.")
    from fengshen_tpu.models.bert.convert import model_to_params
    encoder = model_to_params(
        {k: v for k, v in enc_sd.items() if not k.startswith("linear.")},
        config.encoder)

    # decoder: reuse the transfo_xl importer (same GLM layer naming),
    # then graft the latent projection that lives inside the transformer
    from fengshen_tpu.models.transfo_xl_denoise.convert import \
        torch_to_params as xl_convert
    dec_sd = strip_prefix(sd, "decoder.")
    decoder = xl_convert(dec_sd, config.decoder)["backbone"]
    decoder["linear_emb"] = {
        "kernel": tensor(dec_sd, "transformer.linear_emb.weight").T}

    params: dict = {
        "encoder": encoder,
        "posterior": {"kernel": tensor(sd, "encoder.linear.weight").T},
        "decoder": decoder,
    }
    return params


def critic_to_params(state_dict: Mapping[str, Any]) -> dict:
    """The AAE discriminator → LatentCritic (reference Disc indices 0/2
    of the Sequential, DAVAEModel.py:131-132)."""
    sd = unwrap_lightning(state_dict)
    if any(k.startswith("vae_model.") for k in sd):
        sd = strip_prefix(sd, "vae_model.")
    _, lin, _ = make_helpers(sd)
    return {"fc1": lin("Disc.0"), "out": lin("Disc.2")}


#: fs→torch export: derived exact inverse of `torch_to_params`
#: (template_state = the source checkpoint: dict, Lightning ckpt, or dir)
from fengshen_tpu.utils.convert_common import (  # noqa: E402
    make_derived_export)

params_to_torch_state = make_derived_export(torch_to_params)
