"""DAVAE — denoising adversarial autoencoder for text.

Behavioural port of reference: fengshen/models/DAVAE/ (1,329 LoC):
`BertForLatentConnector` encoder → gaussian latent (std_scale sampling,
DAVAEModel.py:65-83) → GPT2 decoder conditioned on the latent
(GPT2ModelForLatent) with an adversarial critic matching the aggregate
posterior to the prior (the EncDecAAE objective, DAVAEModel.py:49), plus
denoising word-dropout on the encoder input. Public surface mirrors the
reference: `latent_code_from_text_batch` / `text_from_latent_code_batch` /
`simulate_batch` (data augmentation by round-tripping text through the
latent).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from fengshen_tpu.models.bert import BertConfig
from fengshen_tpu.models.bert.modeling_bert import BertModel
from fengshen_tpu.models.gpt2 import GPT2Config
from fengshen_tpu.models.gpt2.modeling_gpt2 import GPT2Model
from fengshen_tpu.parallel.cross_entropy import stable_cross_entropy


@dataclasses.dataclass
class DAVAEConfig:
    latent_size: int = 128
    std_scale: float = 1.0   # posterior sampling temperature (ref :82)
    word_dropout: float = 0.2  # denoising corruption rate
    encoder: BertConfig = None
    decoder: GPT2Config = None
    # The published DAVAE checkpoints decode with the GLM-style relative
    # transformer (reference: DAVAEModel.py:44-50 — GPT2ModelForLatent on
    # a TransfoXLConfig) and encode to the POOLED bert output through a
    # bias-free linear (BertForLatentConnector.py:64-71). True switches
    # both so imports are exact; False keeps the original
    # absolute-position design.
    relative_decoder: bool = False

    @classmethod
    def small_test_config(cls, **overrides: Any) -> "DAVAEConfig":
        base = dict(latent_size=8,
                    encoder=BertConfig.small_test_config(dtype="float32"),
                    decoder=GPT2Config.small_test_config(dtype="float32"))
        base.update(overrides)
        return cls(**base)


class DAVAEModel(nn.Module):
    """encoder→latent→decoder with latent injected at every position."""

    config: DAVAEConfig

    def setup(self):
        cfg = self.config
        if cfg.relative_decoder:
            from fengshen_tpu.models.transfo_xl_denoise \
                .modeling_transfo_xl import (TransfoXLConfig,
                                             TransfoXLModel)
            dec = cfg.decoder
            self.encoder = BertModel(cfg.encoder, add_pooling_layer=True,
                                     name="encoder")
            # the reference decoder IS the GLM relative transformer with
            # latent injection (GPT2ModelForLatent) — one shared module
            self.decoder = TransfoXLModel(TransfoXLConfig(
                vocab_size=dec.vocab_size, hidden_size=dec.n_embd,
                num_layers=dec.n_layer, num_attention_heads=dec.n_head,
                max_sequence_length=dec.n_positions,
                embedding_dropout_prob=dec.embd_pdrop,
                attention_dropout_prob=dec.attn_pdrop,
                output_dropout_prob=dec.resid_pdrop,
                layernorm_epsilon=dec.layer_norm_epsilon,
                dtype=dec.dtype, param_dtype=dec.param_dtype),
                latent_size=cfg.latent_size, name="decoder")
            # reference encoder.linear is bias-free (:71)
            self.posterior = nn.Dense(2 * cfg.latent_size, use_bias=False,
                                      name="posterior")
            self.latent_proj = None
            self.lm_head = None
        else:
            self.encoder = BertModel(cfg.encoder, add_pooling_layer=False,
                                     name="encoder")
            self.decoder = GPT2Model(cfg.decoder, name="decoder")
            self.posterior = nn.Dense(2 * cfg.latent_size,
                                      name="posterior")
            self.latent_proj = nn.Dense(cfg.decoder.n_embd,
                                        name="latent_proj")
            self.lm_head = nn.Dense(cfg.decoder.vocab_size, use_bias=False,
                                    name="lm_head")

    def encode(self, input_ids, attention_mask=None, deterministic=True):
        hidden, pooled = self.encoder(input_ids, attention_mask,
                                      deterministic=deterministic)
        feat = pooled if self.config.relative_decoder else hidden[:, 0]
        stats = self.posterior(feat)
        mean, logvar = jnp.split(stats, 2, axis=-1)
        return mean, logvar

    def sample_latent(self, mean, logvar, rng):
        eps = jax.random.normal(rng, mean.shape)
        return mean + jnp.exp(0.5 * logvar) * eps * self.config.std_scale

    def decode_logits(self, latent, decoder_input_ids, deterministic=True):
        if self.config.relative_decoder:
            logits, _ = self.decoder(decoder_input_ids, latent=latent,
                                     deterministic=deterministic)
            return logits
        cond = self.latent_proj(latent)[:, None, :]
        hidden = self.decoder(decoder_input_ids,
                              deterministic=deterministic)
        return self.lm_head(hidden + cond.astype(hidden.dtype))

    def __call__(self, input_ids, decoder_input_ids=None,
                 attention_mask=None, rng=None, deterministic=True):
        if decoder_input_ids is None:
            decoder_input_ids = input_ids
        mean, logvar = self.encode(input_ids, attention_mask,
                                   deterministic)
        latent = self.sample_latent(mean, logvar, rng) if rng is not None \
            else mean
        logits = self.decode_logits(latent, decoder_input_ids,
                                    deterministic)
        return logits, mean, logvar, latent


class LatentCritic(nn.Module):
    """Adversarial critic on the latent — the AAE discriminator
    (reference: DAVAEModel.py:131-132 `Disc = Sequential(Linear(L, 4L),
    ReLU, Linear(4L, 1))`). `hidden` should be 4 × latent_size to match
    imported checkpoints."""

    hidden: int = 128

    @nn.compact
    def __call__(self, z):
        h = jax.nn.relu(nn.Dense(self.hidden, name="fc1")(z))
        return nn.Dense(1, name="out")(h)[..., 0]


def word_dropout(input_ids, rate: float, unk_id: int, rng,
                 special_mask=None):
    """Denoising corruption: replace non-special tokens with UNK
    (the 'denoising' in DAVAE)."""
    drop = jax.random.bernoulli(rng, rate, input_ids.shape)
    if special_mask is not None:
        drop = drop & ~special_mask
    return jnp.where(drop, unk_id, input_ids)


def davae_losses(logits, target_ids, mean, logvar,
                 critic_real=None, critic_fake=None,
                 kl_weight: float = 1.0, adv_weight: float = 1.0):
    """recon CE + KL + (optional) adversarial generator/critic terms.

    critic_real: critic logits on prior samples; critic_fake: critic logits
    on posterior samples. Returns (vae_loss, critic_loss, metrics)."""
    recon, _ = stable_cross_entropy(logits[:, :-1], target_ids[:, 1:])
    kl = 0.5 * (jnp.exp(logvar) + mean ** 2 - 1.0 - logvar).sum(-1).mean()
    vae_loss = recon + kl_weight * kl
    metrics = {"recon": recon, "kl": kl}
    critic_loss = None
    if critic_real is not None and critic_fake is not None:
        # non-saturating GAN: critic separates prior (real) from posterior
        # (fake); the encoder is rewarded for fooling it
        bce = lambda logit, y: jnp.mean(  # noqa: E731
            jnp.maximum(logit, 0) - logit * y +
            jnp.log1p(jnp.exp(-jnp.abs(logit))))
        critic_loss = bce(critic_real, 1.0) + bce(critic_fake, 0.0)
        gen_loss = bce(critic_fake, 1.0)
        vae_loss = vae_loss + adv_weight * gen_loss
        metrics.update({"critic": critic_loss, "adv": gen_loss})
    return vae_loss, critic_loss, metrics


# -- reference-surface helpers (DAVAEModel.py:58-110) -----------------------

def latent_code_from_text_batch(model: DAVAEModel, params, input_ids,
                                attention_mask=None, rng=None):
    mean, logvar = model.apply({"params": params}, input_ids,
                               attention_mask, method=DAVAEModel.encode)
    if rng is None:
        return mean
    eps = jax.random.normal(rng, mean.shape)
    return mean + jnp.exp(0.5 * logvar) * eps * model.config.std_scale


def text_from_latent_code_batch(model: DAVAEModel, params, latent,
                                max_length: int = 32, bos_id: int = 0,
                                eos_id: Optional[int] = None):
    """Greedy decode conditioned on the latent (scan-based, jit-safe):
    a static [B, max_length] buffer is filled position-by-position — the
    decoder is causal, so logits at position t only see tokens ≤ t and
    the padded tail is inert."""
    batch = latent.shape[0]

    def step(tokens, t):
        logits = model.apply({"params": params}, latent, tokens,
                             method=DAVAEModel.decode_logits,
                             deterministic=True)
        step_logits = jax.lax.dynamic_index_in_dim(logits, t, axis=1,
                                                   keepdims=False)
        nxt = step_logits.argmax(-1).astype(jnp.int32)
        return tokens.at[:, t + 1].set(nxt), nxt

    tokens = jnp.full((batch, max_length), bos_id, jnp.int32)
    seq, _ = jax.lax.scan(step, tokens, jnp.arange(max_length - 1))
    if eos_id is not None:
        seen = jnp.cumsum(seq == eos_id, axis=1) > 0
        seq = jnp.where(seen & (seq != eos_id), eos_id, seq)
    return seq


def simulate_batch(model: DAVAEModel, params, input_ids,
                   attention_mask=None, rng=None, max_length: int = 32,
                   bos_id: int = 0):
    """text → latent → text (reference's data-augmentation entry,
    DAVAEModel.py:58-63)."""
    latent = latent_code_from_text_batch(model, params, input_ids,
                                         attention_mask, rng)
    return text_from_latent_code_batch(model, params, latent,
                                       max_length=max_length, bos_id=bos_id)
