"""Optimizer / LR-scheduler factory and shared argparse groups.

Port of the reference's shared model utilities
(reference: fengshen/models/model_utils.py:13-209):
- `add_module_args` — the canonical hyperparameter flag group (:13-28)
- no-decay parameter grouping (:39-47)
- `configure_optimizers` — optimizer + scheduler selection (:50-98)
- schedulers: polynomial / constant / cosine + custom inverse_square_root
  and Direct_LR passthrough (:101-192)
- `get_total_steps` (:194-209)

TPU-native differences: `optax.adamw` replaces FusedAdam/DeepSpeedCPUAdam
(XLA already fuses the update), and "CPU offload" of optimizer state is a
sharding/placement decision (see trainer), not a different optimizer.
"""

from __future__ import annotations

import argparse
from typing import Any, Optional

import jax
import optax


def add_module_args(parent_parser: argparse.ArgumentParser):
    """Reference: fengshen/models/model_utils.py:13-28 (same flag names)."""
    parser = parent_parser.add_argument_group("Basic Module")
    parser.add_argument("--learning_rate", default=5e-5, type=float)
    parser.add_argument("--min_learning_rate", default=1e-7, type=float)
    parser.add_argument("--lr_decay_steps", default=0, type=int)
    parser.add_argument("--lr_decay_ratio", default=1.0, type=float)
    parser.add_argument("--warmup_steps", default=0, type=int)
    parser.add_argument("--warmup_ratio", default=0.1, type=float)
    parser.add_argument("--weight_decay", default=1e-1, type=float)
    parser.add_argument("--adam_beta1", default=0.9, type=float)
    parser.add_argument("--adam_beta2", default=0.999, type=float)
    parser.add_argument("--adam_epsilon", default=1e-8, type=float)
    parser.add_argument("--model_path", default=None, type=str)
    parser.add_argument(
        "--scheduler_type", default="polynomial", type=str,
        choices=["polynomial", "constant", "cosine", "inverse_sqrt",
                 "constant_with_warmup", "direct"])
    return parent_parser


def add_inverse_square_args(parent_parser: argparse.ArgumentParser):
    """Reference: fengshen/models/model_utils.py:31-36."""
    parser = parent_parser.add_argument_group("Inverse Square")
    parser.add_argument("--warmup_min_lr", default=1e-9, type=float)
    parser.add_argument("--warmup_max_lr", default=1e-4, type=float)
    return parent_parser


NO_DECAY_PATTERNS = ("bias", "scale", "layernorm", "layer_norm", "ln_",
                     "norm")


def decay_mask_fn(params: Any) -> Any:
    """True where weight decay applies. Port of the no-decay grouping
    (reference: fengshen/models/model_utils.py:39-47 — biases and LayerNorm
    weights are excluded)."""
    from fengshen_tpu.parallel.partition import tree_paths
    paths = tree_paths(params)

    def keep(path: str, leaf) -> bool:
        low = path.lower()
        if any(p in low for p in NO_DECAY_PATTERNS):
            return False
        return getattr(leaf, "ndim", 0) >= 2

    return jax.tree_util.tree_map(keep, paths, params)


def get_scheduler(args, total_steps: int) -> optax.Schedule:
    """LR schedule factory (reference: fengshen/models/model_utils.py:85-192).

    warmup_steps wins over warmup_ratio, as in the reference's
    `get_warmup_steps` (:194-198).
    """
    lr = args.learning_rate
    warmup = args.warmup_steps if args.warmup_steps > 0 else int(
        args.warmup_ratio * total_steps)
    decay_steps = args.lr_decay_steps if getattr(
        args, "lr_decay_steps", 0) > 0 else total_steps
    stype = getattr(args, "scheduler_type", "polynomial")

    if stype == "direct":
        # Direct_LR: constant lr, no warmup (reference custom scheduler)
        return optax.constant_schedule(lr)
    if stype in ("constant", "constant_with_warmup"):
        return optax.join_schedules(
            [optax.linear_schedule(0.0, lr, max(warmup, 1)),
             optax.constant_schedule(lr)], [warmup])
    if stype == "cosine":
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0, peak_value=lr, warmup_steps=warmup,
            decay_steps=decay_steps,
            end_value=getattr(args, "min_learning_rate", 0.0))
    if stype == "inverse_sqrt":
        warmup_min = getattr(args, "warmup_min_lr", 1e-9)
        warmup_max = getattr(args, "warmup_max_lr", lr)

        def inv_sqrt(step):
            w = max(warmup, 1)
            warm = warmup_min + (warmup_max - warmup_min) * (step / w)
            decay = warmup_max * (w ** 0.5) / (jax.numpy.maximum(
                step, 1) ** 0.5)
            return jax.numpy.where(step < w, warm, decay)

        return inv_sqrt
    # polynomial (HF get_polynomial_decay_schedule_with_warmup parity)
    end_lr = getattr(args, "min_learning_rate", 0.0)
    return optax.join_schedules(
        [optax.linear_schedule(0.0, lr, max(warmup, 1)),
         optax.polynomial_schedule(
             init_value=lr, end_value=end_lr, power=1.0,
             transition_steps=max(decay_steps - warmup, 1))],
        [warmup])


def configure_optimizers(args, total_steps: int,
                         params: Optional[Any] = None
                         ) -> tuple[optax.GradientTransformation,
                                    optax.Schedule]:
    """Optimizer factory (reference: fengshen/models/model_utils.py:50-98).

    Returns (tx, schedule). `params` enables the no-decay mask; without it
    decay applies everywhere (callers should pass params).
    """
    schedule = get_scheduler(args, total_steps)
    # the mask goes in as a CALLABLE so optax evaluates it on whatever
    # tree the transform actually sees — identical for plain training,
    # and under optax.masked / multi_transform (the LoRA path) it
    # adapts to the masked subtree instead of relying on optax to line
    # up an eagerly-built full-tree mask
    mask = decay_mask_fn if params is not None else None
    tx = optax.adamw(
        learning_rate=schedule,
        b1=getattr(args, "adam_beta1", 0.9),
        b2=getattr(args, "adam_beta2", 0.999),
        eps=getattr(args, "adam_epsilon", 1e-8),
        weight_decay=getattr(args, "weight_decay", 0.0),
        mask=mask,
    )
    if getattr(args, "gradient_clip_val", 0.0):
        tx = optax.chain(
            optax.clip_by_global_norm(args.gradient_clip_val), tx)
    return tx, schedule


def get_total_steps(args, dataset_len: int, world_batch: int) -> int:
    """Total optimizer steps (reference: fengshen/models/model_utils.py:194-209,
    mpu-aware world size folded into `world_batch` by the caller)."""
    if getattr(args, "max_steps", 0) and args.max_steps > 0:
        return args.max_steps
    epochs = getattr(args, "max_epochs", 1) or 1
    return max(1, epochs * dataset_len // max(world_batch, 1))
