"""torch(HF) → jax weights for RoFormer."""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from fengshen_tpu.utils.convert_common import tensor as _tensor

from fengshen_tpu.models.roformer.modeling_roformer import RoFormerConfig


def torch_to_params(state_dict: Mapping[str, Any], config: RoFormerConfig,
                    head: str = "none") -> dict:
    def t(name):
        return _tensor(state_dict, name)

    def lin(prefix):
        return {"kernel": t(f"{prefix}.weight").T,
                "bias": t(f"{prefix}.bias")}

    def ln(prefix):
        return {"scale": t(f"{prefix}.weight"), "bias": t(f"{prefix}.bias")}

    ro: dict = {
        "word_embeddings": {
            "embedding": t("roformer.embeddings.word_embeddings.weight")},
        "token_type_embeddings": {
            "embedding":
                t("roformer.embeddings.token_type_embeddings.weight")},
        "embeddings_ln": ln("roformer.embeddings.LayerNorm"),
    }
    for i in range(config.num_hidden_layers):
        pre = f"roformer.encoder.layer.{i}"
        ro[f"layer_{i}"] = {
            "query": lin(f"{pre}.attention.self.query"),
            "key": lin(f"{pre}.attention.self.key"),
            "value": lin(f"{pre}.attention.self.value"),
            "attention_output_dense": lin(f"{pre}.attention.output.dense"),
            "attention_ln": ln(f"{pre}.attention.output.LayerNorm"),
            "intermediate_dense": lin(f"{pre}.intermediate.dense"),
            "output_dense": lin(f"{pre}.output.dense"),
            "output_ln": ln(f"{pre}.output.LayerNorm"),
        }
    params: dict = {"roformer": ro}
    if head == "masked_lm":
        params["transform_dense"] = lin("cls.predictions.transform.dense")
        params["transform_ln"] = ln("cls.predictions.transform.LayerNorm")
        params["bias"] = t("cls.predictions.bias")
    return params


#: fs→torch export: derived exact inverse of `torch_to_params`
#: (template_state = the source checkpoint: dict, Lightning ckpt, or dir)
from fengshen_tpu.utils.convert_common import (  # noqa: E402
    make_derived_export)

params_to_torch_state = make_derived_export(torch_to_params)
