"""RoFormer in flax, HF-weight-compatible.

Reference: fengshen/models/roformer/ (rotary BERT for Chinese NLU). Post-LN
BERT encoder whose q/k (optionally v) get INTERLEAVED rotary embeddings —
RoFormer's convention pairs adjacent dims (-q1,q0,-q3,q2,…), unlike the
half-rotation layout in ops.rotary used by LLaMA.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from fengshen_tpu.ops.activations import get_activation
from fengshen_tpu.ops.embedding import VocabParallelEmbed
from fengshen_tpu.ops.attention import dot_product_attention
from fengshen_tpu.ops.norms import LayerNorm
from fengshen_tpu.sharding import (to_partition_rules,
                                    with_logical_constraint)

PARAM_LOGICAL_AXES: list[tuple[str, tuple]] = [
    ("word_embeddings/embedding", ("vocab", "embed")),
    ("token_type_embeddings/embedding", (None, None)),
    (r"(query|key|value)/kernel", ("embed", "heads")),
    (r"intermediate_dense/kernel", ("embed", "mlp")),
    (r"attention_output_dense/kernel", ("heads", "embed")),
    (r"output_dense/kernel", ("mlp", "embed")),
    (".*", (None,)),
]
PARTITION_RULES = to_partition_rules(PARAM_LOGICAL_AXES)


@dataclasses.dataclass
class RoFormerConfig:
    vocab_size: int = 50000
    embedding_size: Optional[int] = None
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 1536
    type_vocab_size: int = 2
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    rotary_value: bool = False
    pad_token_id: int = 0
    num_labels: int = 2
    dtype: str = "bfloat16"
    param_dtype: str = "float32"

    def __post_init__(self):
        if self.embedding_size is None:
            self.embedding_size = self.hidden_size

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def from_pretrained(cls, path: str) -> "RoFormerConfig":
        cfg_file = os.path.join(path, "config.json") if os.path.isdir(path) \
            else path
        with open(cfg_file) as f:
            raw = json.load(f)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in raw.items() if k in known})

    @classmethod
    def small_test_config(cls, **overrides: Any) -> "RoFormerConfig":
        base = dict(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=64,
                    max_position_embeddings=64)
        base.update(overrides)
        return cls(**base)


def _dt(cfg):
    return jnp.dtype(cfg.dtype)


def _dense(cfg, feats, name):
    return nn.Dense(feats, dtype=_dt(cfg),
                    param_dtype=jnp.dtype(cfg.param_dtype),
                    kernel_init=nn.initializers.normal(
                        cfg.initializer_range), name=name)


def interleaved_rotary(x: jax.Array, positions: jax.Array) -> jax.Array:
    """RoFormer rotary: adjacent-dim pairing. x [B,S,H,D], positions [S]."""
    dim = x.shape[-1]
    theta = 1.0 / (10000.0 ** (2 * (jnp.arange(dim // 2)) / dim))
    angles = positions[:, None].astype(jnp.float32) * theta[None]  # [S,D/2]
    sin = jnp.repeat(jnp.sin(angles), 2, axis=-1)[None, :, None, :]
    cos = jnp.repeat(jnp.cos(angles), 2, axis=-1)[None, :, None, :]
    x1 = x[..., 0::2]
    x2 = x[..., 1::2]
    rotated = jnp.stack([-x2, x1], axis=-1).reshape(x.shape)
    return x * cos.astype(x.dtype) + rotated * sin.astype(x.dtype)


class RoFormerLayer(nn.Module):
    config: RoFormerConfig

    @nn.compact
    def __call__(self, hidden, attention_mask=None, deterministic=True):
        cfg = self.config
        batch, seq, _ = hidden.shape
        n_head, head_dim = cfg.num_attention_heads, cfg.head_dim

        q = _dense(cfg, cfg.hidden_size, "query")(hidden)
        k = _dense(cfg, cfg.hidden_size, "key")(hidden)
        v = _dense(cfg, cfg.hidden_size, "value")(hidden)
        q = q.reshape(batch, seq, n_head, head_dim)
        k = k.reshape(batch, seq, n_head, head_dim)
        v = v.reshape(batch, seq, n_head, head_dim)
        positions = jnp.arange(seq)
        q = interleaved_rotary(q, positions)
        k = interleaved_rotary(k, positions)
        if cfg.rotary_value:
            v = interleaved_rotary(v, positions)

        mask = None
        if attention_mask is not None:
            mask = attention_mask[:, None, None, :].astype(bool)
        drop_rng = None
        if not deterministic and cfg.attention_probs_dropout_prob > 0:
            drop_rng = self.make_rng("dropout")
        out = dot_product_attention(
            q, k, v, mask=mask, dropout_rng=drop_rng,
            dropout_rate=cfg.attention_probs_dropout_prob,
            deterministic=deterministic)
        out = with_logical_constraint(
            out, ("batch", "seq", "heads", None))
        out = out.reshape(batch, seq, cfg.hidden_size)
        out = _dense(cfg, cfg.hidden_size, "attention_output_dense")(out)
        out = nn.Dropout(cfg.hidden_dropout_prob)(
            out, deterministic=deterministic)
        hidden = LayerNorm(epsilon=cfg.layer_norm_eps,
                           name="attention_ln")(hidden + out)

        h = _dense(cfg, cfg.intermediate_size, "intermediate_dense")(hidden)
        h = get_activation(cfg.hidden_act)(h)
        h = with_logical_constraint(h, ("batch", "seq", "mlp"))
        h = _dense(cfg, cfg.hidden_size, "output_dense")(h)
        h = nn.Dropout(cfg.hidden_dropout_prob)(h,
                                                deterministic=deterministic)
        return LayerNorm(epsilon=cfg.layer_norm_eps,
                         name="output_ln")(hidden + h)


class RoFormerModel(nn.Module):
    config: RoFormerConfig
    add_pooling_layer: bool = True

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic=True):
        cfg = self.config
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        hidden = VocabParallelEmbed(
            cfg.vocab_size, cfg.embedding_size, dtype=_dt(cfg),
            param_dtype=jnp.dtype(cfg.param_dtype),
            embedding_init=nn.initializers.normal(cfg.initializer_range),
            name="word_embeddings")(input_ids)
        hidden = hidden + nn.Embed(
            cfg.type_vocab_size, cfg.embedding_size, dtype=_dt(cfg),
            param_dtype=jnp.dtype(cfg.param_dtype),
            embedding_init=nn.initializers.normal(cfg.initializer_range),
            name="token_type_embeddings")(token_type_ids)
        hidden = LayerNorm(epsilon=cfg.layer_norm_eps,
                           name="embeddings_ln")(hidden)
        hidden = nn.Dropout(cfg.hidden_dropout_prob)(
            hidden, deterministic=deterministic)
        if cfg.embedding_size != cfg.hidden_size:
            hidden = _dense(cfg, cfg.hidden_size, "embeddings_project")(
                hidden)
        hidden = with_logical_constraint(
            hidden, ("batch", "seq", None))
        for i in range(cfg.num_hidden_layers):
            hidden = RoFormerLayer(cfg, name=f"layer_{i}")(
                hidden, attention_mask, deterministic)
        pooled = None
        if self.add_pooling_layer:
            pooled = jnp.tanh(_dense(cfg, cfg.hidden_size,
                                     "pooler")(hidden[:, 0]))
        return hidden, pooled

    def partition_rules(self):
        return to_partition_rules(PARAM_LOGICAL_AXES)


class RoFormerForMaskedLM(nn.Module):
    config: RoFormerConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic=True):
        cfg = self.config
        hidden, _ = RoFormerModel(cfg, add_pooling_layer=False,
                                  name="roformer")(
            input_ids, attention_mask, token_type_ids, deterministic)
        h = _dense(cfg, cfg.embedding_size, "transform_dense")(hidden)
        h = get_activation(cfg.hidden_act)(h)
        h = LayerNorm(epsilon=cfg.layer_norm_eps, name="transform_ln")(h)
        wte = self.variables["params"]["roformer"]["word_embeddings"][
            "embedding"]
        logits = h @ wte.T.astype(h.dtype)
        bias = self.param("bias", nn.initializers.zeros,
                          (cfg.vocab_size,), jnp.dtype(cfg.param_dtype))
        return logits + bias

    def partition_rules(self):
        return to_partition_rules(PARAM_LOGICAL_AXES)


class RoFormerForSequenceClassification(nn.Module):
    config: RoFormerConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic=True):
        cfg = self.config
        hidden, _ = RoFormerModel(cfg, add_pooling_layer=False,
                                  name="roformer")(
            input_ids, attention_mask, token_type_ids, deterministic)
        # HF RoFormer classification head: dense+tanh over [CLS] then proj
        h = nn.Dropout(cfg.hidden_dropout_prob)(
            hidden[:, 0], deterministic=deterministic)
        h = jnp.tanh(_dense(cfg, cfg.hidden_size, "classifier_dense")(h))
        h = nn.Dropout(cfg.hidden_dropout_prob)(h,
                                                deterministic=deterministic)
        return _dense(cfg, cfg.num_labels, "classifier_out")(h)

    def partition_rules(self):
        return to_partition_rules(PARAM_LOGICAL_AXES)
