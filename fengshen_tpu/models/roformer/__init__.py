"""RoFormer family (reference: fengshen/models/roformer/ — rotary BERT with
the full head set, 2,160 LoC)."""

from fengshen_tpu.models.roformer.modeling_roformer import (
    RoFormerConfig, RoFormerModel, RoFormerForMaskedLM,
    RoFormerForSequenceClassification)

__all__ = ["RoFormerConfig", "RoFormerModel", "RoFormerForMaskedLM",
           "RoFormerForSequenceClassification"]

from fengshen_tpu.models.roformer.task_heads import (RoFormerForTokenClassification, RoFormerForQuestionAnswering, RoFormerForMultipleChoice)
__all__ += ['RoFormerForTokenClassification', 'RoFormerForQuestionAnswering', 'RoFormerForMultipleChoice']
