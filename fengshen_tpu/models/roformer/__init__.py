"""RoFormer family (reference: fengshen/models/roformer/ — rotary BERT with
the full head set, 2,160 LoC)."""

from fengshen_tpu.models.roformer.modeling_roformer import (
    RoFormerConfig, RoFormerModel, RoFormerForMaskedLM,
    RoFormerForSequenceClassification)

__all__ = ["RoFormerConfig", "RoFormerModel", "RoFormerForMaskedLM",
           "RoFormerForSequenceClassification"]
