"""torch → jax weights for DeltaLM.

Importer for the reference's DeltaLM checkpoints
(reference: fengshen/models/deltalm/modeling_deltalm.py — encoder layers
use self_attn/fc1/fc2, decoder layers interleave self_attn → fc3/fc4
(ffn_layer_norm) → encoder_attn → fc1/fc2 (final_layer_norm),
:258-440). In this flax family the decoder's FIRST ffn is named fc1/fc2
(ffn1_layer_norm) and the SECOND fc3/fc4 (ffn2_layer_norm) in execution
order, so the mapping swaps the reference's pairs accordingly.
"""

from __future__ import annotations

from typing import Any, Mapping

from fengshen_tpu.models.deltalm.modeling_deltalm import DeltaLMConfig
from fengshen_tpu.utils.convert_common import (make_helpers,
                                               seq2seq_attention)


def _strip(state_dict: Mapping[str, Any]) -> dict:
    """Accept raw fairseq-style dicts with or without a `model.` prefix."""
    if any(k.startswith("model.") for k in state_dict):
        return {k[len("model."):]: v for k, v in state_dict.items()
                if k.startswith("model.")}
    return dict(state_dict)


def torch_to_params(state_dict: Mapping[str, Any],
                    config: DeltaLMConfig) -> dict:
    sd = _strip(state_dict)
    t, lin, ln = make_helpers(sd)

    def enc_layer(i):
        p = f"encoder.layers.{i}"
        return {
            "self_attn": seq2seq_attention(sd, f"{p}.self_attn"),
            "self_attn_layer_norm": ln(f"{p}.self_attn_layer_norm"),
            "fc1": lin(f"{p}.fc1"),
            "fc2": lin(f"{p}.fc2"),
            "final_layer_norm": ln(f"{p}.final_layer_norm"),
        }

    def dec_layer(i):
        p = f"decoder.layers.{i}"
        return {
            "self_attn": seq2seq_attention(sd, f"{p}.self_attn"),
            "self_attn_layer_norm": ln(f"{p}.self_attn_layer_norm"),
            # reference fc3/fc4 run FIRST (after self-attn) → flax fc1/fc2
            "fc1": lin(f"{p}.fc3"),
            "fc2": lin(f"{p}.fc4"),
            "ffn1_layer_norm": ln(f"{p}.ffn_layer_norm"),
            "encoder_attn": seq2seq_attention(sd, f"{p}.encoder_attn"),
            "encoder_attn_layer_norm": ln(f"{p}.encoder_attn_layer_norm"),
            # reference fc1/fc2 run LAST → flax fc3/fc4
            "fc3": lin(f"{p}.fc1"),
            "fc4": lin(f"{p}.fc2"),
            "ffn2_layer_norm": ln(f"{p}.final_layer_norm"),
        }

    embed_key = "encoder.embed_tokens.weight" if \
        "encoder.embed_tokens.weight" in sd else "shared.weight"
    pos_key = "encoder.embed_positions.weight"
    params: dict = {
        "shared": {"embedding": t(embed_key)},
    }
    if pos_key in sd:
        params["embed_positions"] = {"embedding": t(pos_key)}
    for src, dst in (("encoder.layernorm_embedding",
                      "encoder_emb_layer_norm"),
                     ("decoder.layernorm_embedding",
                      "decoder_emb_layer_norm"),
                     ("encoder.layer_norm", "encoder_layer_norm"),
                     ("decoder.layer_norm", "decoder_layer_norm")):
        if f"{src}.weight" in sd:
            params[dst] = ln(src)
    for i in range(config.encoder_layers):
        params[f"encoder_layer_{i}"] = enc_layer(i)
    for i in range(config.decoder_layers):
        params[f"decoder_layer_{i}"] = dec_layer(i)
    return params


#: fs→torch export: derived exact inverse of `torch_to_params`
#: (template_state = the source checkpoint: dict, Lightning ckpt, or dir)
from fengshen_tpu.utils.convert_common import (  # noqa: E402
    make_derived_export)

params_to_torch_state = make_derived_export(torch_to_params)
