"""DeltaLM in flax.

Behavioural port of reference: fengshen/models/deltalm/ (used by
fengshen/examples/translate/finetune_deltalm.py). DeltaLM's signature
architecture is the INTERLEAVED decoder: each decoder block runs
self-attn → FFN → cross-attn → FFN (two FFN sublayers), so decoder weights
can be initialised from a pretrained encoder's attn/FFN pairs. Pre-LN
residuals, learned positions offset like BART.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from fengshen_tpu.models.bart.modeling_bart import BartAttention
from fengshen_tpu.ops.activations import get_activation
from fengshen_tpu.ops.embedding import VocabParallelEmbed
from fengshen_tpu.ops.norms import LayerNorm
from fengshen_tpu.sharding import (to_partition_rules,
                                    with_logical_constraint)

PARAM_LOGICAL_AXES: list[tuple[str, tuple]] = [
    ("shared/embedding", ("vocab", "embed")),
    ("embed_positions/embedding", ("relpos", None)),
    (r"(q_proj|k_proj|v_proj)/kernel", ("embed", "heads")),
    (r"(fc1|fc3)/kernel", ("embed", "mlp")),
    (r"out_proj/kernel", ("heads", "embed")),
    (r"(fc2|fc4)/kernel", ("mlp", "embed")),
    (".*", (None,)),
]
PARTITION_RULES = to_partition_rules(PARAM_LOGICAL_AXES)

_POS_OFFSET = 2


@dataclasses.dataclass
class DeltaLMConfig:
    vocab_size: int = 250001
    d_model: int = 768
    encoder_layers: int = 12
    decoder_layers: int = 6
    encoder_attention_heads: int = 12
    decoder_attention_heads: int = 12
    encoder_ffn_dim: int = 3072
    decoder_ffn_dim: int = 3072
    activation_function: str = "gelu"
    dropout: float = 0.1
    max_position_embeddings: int = 512
    decode_cache_length: int = 512  # KV-cache capacity for generation
    init_std: float = 0.02
    scale_embedding: bool = False
    pad_token_id: int = 1
    bos_token_id: int = 0
    eos_token_id: int = 2
    decoder_start_token_id: int = 0
    dtype: str = "bfloat16"
    param_dtype: str = "float32"

    @property
    def hidden_size(self) -> int:
        return self.d_model

    @property
    def num_hidden_layers(self) -> int:
        return self.encoder_layers + self.decoder_layers

    @property
    def intermediate_size(self) -> int:
        return self.encoder_ffn_dim

    @classmethod
    def from_pretrained(cls, path: str) -> "DeltaLMConfig":
        cfg_file = os.path.join(path, "config.json") if os.path.isdir(path) \
            else path
        with open(cfg_file) as f:
            raw = json.load(f)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in raw.items() if k in known})

    @classmethod
    def small_test_config(cls, **overrides: Any) -> "DeltaLMConfig":
        base = dict(vocab_size=128, d_model=32, encoder_layers=2,
                    decoder_layers=2, encoder_attention_heads=4,
                    decoder_attention_heads=4, encoder_ffn_dim=64,
                    decoder_ffn_dim=64, max_position_embeddings=64)
        base.update(overrides)
        return cls(**base)


def _dt(cfg):
    return jnp.dtype(cfg.dtype)


def _ffn(cfg, hidden, prefix_fc1, prefix_fc2, deterministic):
    h = get_activation(cfg.activation_function)(
        nn.Dense(cfg.decoder_ffn_dim, dtype=_dt(cfg),
                 param_dtype=jnp.dtype(cfg.param_dtype),
                 name=prefix_fc1)(hidden))
    h = with_logical_constraint(h, ("batch", "seq", "mlp"))
    return nn.Dense(cfg.d_model, dtype=_dt(cfg),
                    param_dtype=jnp.dtype(cfg.param_dtype),
                    name=prefix_fc2)(h)


class DeltaLMEncoderLayer(nn.Module):
    config: DeltaLMConfig

    @nn.compact
    def __call__(self, hidden, attention_mask=None, deterministic=True):
        cfg = self.config
        h = LayerNorm(name="self_attn_layer_norm")(hidden)
        h = BartAttention(cfg, cfg.encoder_attention_heads,
                          name="self_attn")(
            h, attention_mask=attention_mask, deterministic=deterministic)
        hidden = hidden + h
        h = LayerNorm(name="final_layer_norm")(hidden)
        h = _ffn(cfg, h, "fc1", "fc2", deterministic)
        return hidden + h


class DeltaLMDecoderLayer(nn.Module):
    """Interleaved: self-attn → FFN → cross-attn → FFN."""

    config: DeltaLMConfig

    @nn.compact
    def __call__(self, hidden, encoder_hidden, attention_mask=None,
                 encoder_attention_mask=None, deterministic=True,
                 init_cache=False, cross_from_cache=False):
        cfg = self.config
        h = LayerNorm(name="self_attn_layer_norm")(hidden)
        h = BartAttention(cfg, cfg.decoder_attention_heads, causal=True,
                          name="self_attn")(
            h, attention_mask=attention_mask, deterministic=deterministic,
            init_cache=init_cache)
        hidden = hidden + h
        h = LayerNorm(name="ffn1_layer_norm")(hidden)
        h = _ffn(cfg, h, "fc1", "fc2", deterministic)
        hidden = hidden + h
        h = LayerNorm(name="encoder_attn_layer_norm")(hidden)
        h = BartAttention(cfg, cfg.decoder_attention_heads,
                          name="encoder_attn")(
            h, kv=encoder_hidden, attention_mask=encoder_attention_mask,
            deterministic=deterministic, init_cache=init_cache,
            cross_from_cache=cross_from_cache)
        hidden = hidden + h
        h = LayerNorm(name="ffn2_layer_norm")(hidden)
        h = _ffn(cfg, h, "fc3", "fc4", deterministic)
        return hidden + h


class DeltaLMForConditionalGeneration(nn.Module):
    """setup-based (not @nn.compact) so the generate loop can run the
    encoder ONCE via `encode` and re-run only `decode_logits` per step;
    attribute names keep the original parameter paths."""

    config: DeltaLMConfig

    def setup(self):
        cfg = self.config
        self.shared = VocabParallelEmbed(
            cfg.vocab_size, cfg.d_model, dtype=_dt(cfg),
            param_dtype=jnp.dtype(cfg.param_dtype),
            embedding_init=nn.initializers.normal(cfg.init_std))
        self.embed_positions = nn.Embed(
            cfg.max_position_embeddings + _POS_OFFSET, cfg.d_model,
            dtype=_dt(cfg), param_dtype=jnp.dtype(cfg.param_dtype),
            embedding_init=nn.initializers.normal(cfg.init_std))
        self.encoder_emb_layer_norm = LayerNorm()
        for i in range(cfg.encoder_layers):
            setattr(self, f"encoder_layer_{i}", DeltaLMEncoderLayer(cfg))
        self.encoder_layer_norm = LayerNorm()
        self.decoder_emb_layer_norm = LayerNorm()
        for i in range(cfg.decoder_layers):
            setattr(self, f"decoder_layer_{i}", DeltaLMDecoderLayer(cfg))
        self.decoder_layer_norm = LayerNorm()

    def _embed(self, ids, position_offset=0):
        cfg = self.config
        scale = (cfg.d_model ** 0.5) if cfg.scale_embedding else 1.0
        pos = position_offset + jnp.arange(ids.shape[1]) + _POS_OFFSET
        return self.shared(ids) * scale + self.embed_positions(pos)[None]

    def encode(self, input_ids, attention_mask=None, deterministic=True):
        enc = self.encoder_emb_layer_norm(self._embed(input_ids))
        for i in range(self.config.encoder_layers):
            enc = getattr(self, f"encoder_layer_{i}")(
                enc, attention_mask, deterministic)
        return self.encoder_layer_norm(enc)

    def _decode(self, decoder_input_ids, encoder_hidden,
                decoder_attention_mask, encoder_attention_mask,
                deterministic, init_cache=False, cross_from_cache=False,
                position_offset=0):
        dec = self.decoder_emb_layer_norm(
            self._embed(decoder_input_ids, position_offset))
        for i in range(self.config.decoder_layers):
            dec = getattr(self, f"decoder_layer_{i}")(
                dec, encoder_hidden, decoder_attention_mask,
                encoder_attention_mask, deterministic,
                init_cache=init_cache, cross_from_cache=cross_from_cache)
        dec = self.decoder_layer_norm(dec)
        return dec @ self.shared.embedding.T.astype(dec.dtype)

    def decode_logits(self, decoder_input_ids, encoder_hidden,
                      attention_mask=None, deterministic=True,
                      init_cache=False, cross_from_cache=False,
                      position_offset=0):
        return self._decode(decoder_input_ids, encoder_hidden, None,
                            attention_mask, deterministic, init_cache,
                            cross_from_cache, position_offset)

    def __call__(self, input_ids, decoder_input_ids, attention_mask=None,
                 decoder_attention_mask=None, deterministic=True,
                 init_cache=False):
        enc = self.encode(input_ids, attention_mask, deterministic)
        return self._decode(decoder_input_ids, enc, decoder_attention_mask,
                            attention_mask, deterministic,
                            init_cache=init_cache)

    def partition_rules(self):
        return to_partition_rules(PARAM_LOGICAL_AXES)
