"""DeltaLM family (reference: fengshen/models/deltalm/, 1,978 LoC —
encoder-decoder for translation with an interleaved decoder initialised
from the encoder)."""

from fengshen_tpu.models.deltalm.modeling_deltalm import (
    DeltaLMConfig, DeltaLMForConditionalGeneration)

__all__ = ["DeltaLMConfig", "DeltaLMForConditionalGeneration"]
