"""GAVAE family (reference: fengshen/models/GAVAE/, 551 LoC)."""

from fengshen_tpu.models.gavae.modeling_gavae import (
    GAVAEConfig, GAVAEModel, LatentGenerator, LatentDiscriminator,
    gan_d_step, gan_g_step)

__all__ = ["GAVAEConfig", "GAVAEModel", "LatentGenerator",
           "LatentDiscriminator", "gan_d_step", "gan_g_step"]
