"""GAVAE — GAN-augmented VAE for labelled text generation.

Behavioural port of reference: fengshen/models/GAVAE/ (551 LoC):
a latent-space GAN on top of the DAVAE text autoencoder — `gans_process`
trains a generator MLP (noise+label → latent) against a
discriminator/classifier MLP over latents (gans_model.py:37-135), and
`GAVAEModel.generate(n)` decodes generator samples back to text
(GAVAEModel.py:44-66). Here generator/discriminator are flax modules with
optax training steps; decoding reuses the DAVAE surface.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn

from fengshen_tpu.models.davae.modeling_davae import (
    DAVAEConfig, DAVAEModel, text_from_latent_code_batch)


@dataclasses.dataclass
class GAVAEConfig:
    latent_size: int = 128
    noise_size: int = 64
    cls_num: int = 2
    gan_lr: float = 1e-4
    vae: DAVAEConfig = None

    @classmethod
    def small_test_config(cls, **overrides: Any) -> "GAVAEConfig":
        vae = DAVAEConfig.small_test_config()
        base = dict(latent_size=vae.latent_size, noise_size=8,
                    vae=vae)
        base.update(overrides)
        return cls(**base)


class LatentGenerator(nn.Module):
    """noise (+ one-hot label) → latent. Reference Gen_Net structure
    (gans_model.py:99-133): x2_input → 60, then 60→128→256→128→latent
    with ReLU between the fc layers."""

    latent_size: int

    @nn.compact
    def __call__(self, noise, labels_onehot=None):
        x = noise if labels_onehot is None else \
            jnp.concatenate([noise, labels_onehot], -1)
        x = nn.Dense(60, name="x2_input")(x)
        x = jax.nn.relu(nn.Dense(128, name="fc1")(x))
        x = jax.nn.relu(nn.Dense(256, name="fc2")(x))
        x = jax.nn.relu(nn.Dense(128, name="fc3")(x))
        return nn.Dense(self.latent_size, name="out")(x)


class LatentDiscriminator(nn.Module):
    """latent → [real classes..., fake] logits. Reference CLS_Net
    structure (gans_model.py:35-93): fc1 → 256, ReLU, fc2 → 64, dropout,
    ReLU, out (we append one fake class for the adversarial target)."""

    cls_num: int = 2

    @nn.compact
    def __call__(self, z, deterministic=True):
        h = jax.nn.relu(nn.Dense(256, name="fc1")(z))
        h = nn.Dense(64, name="fc2")(h)
        h = nn.Dropout(0.1)(h, deterministic=deterministic)
        h = jax.nn.relu(h)
        return nn.Dense(self.cls_num + 1, name="out")(h)  # +1 = fake class


def _ce(logits, labels):
    logp = jax.nn.log_softmax(logits, -1)
    return -jnp.take_along_axis(logp, labels[:, None], 1).mean()


def gan_d_step(disc, d_params, gen, g_params, real_latents, real_labels,
               rng, noise_size: int):
    """Discriminator update target: real latents → their class, generated
    latents → the fake class."""
    batch = real_latents.shape[0]
    fake_cls = disc.cls_num
    rng, nk, dk1, dk2 = jax.random.split(rng, 4)
    noise = jax.random.normal(nk, (batch, noise_size))
    onehot = jax.nn.one_hot(real_labels, disc.cls_num)
    fake = gen.apply({"params": g_params}, noise, onehot)

    def loss_fn(p):
        real_logits = disc.apply({"params": p}, real_latents,
                                 deterministic=False,
                                 rngs={"dropout": dk1})
        fake_logits = disc.apply({"params": p}, fake,
                                 deterministic=False,
                                 rngs={"dropout": dk2})
        return (_ce(real_logits, real_labels) +
                _ce(fake_logits,
                    jnp.full((batch,), fake_cls, jnp.int32)))

    return jax.value_and_grad(loss_fn)(d_params)


def gan_g_step(disc, d_params, gen, g_params, labels, rng,
               noise_size: int):
    """Generator update target: generated latents classified as their
    conditioning class (not fake)."""
    batch = labels.shape[0]
    rng, nk, dk = jax.random.split(rng, 3)
    noise = jax.random.normal(nk, (batch, noise_size))
    onehot = jax.nn.one_hot(labels, disc.cls_num)

    def loss_fn(p):
        fake = gen.apply({"params": p}, noise, onehot)
        logits = disc.apply({"params": d_params}, fake,
                            deterministic=False, rngs={"dropout": dk})
        return _ce(logits, labels)

    return jax.value_and_grad(loss_fn)(g_params)


class GAVAEModel:
    """train_gan / generate surface (reference: GAVAEModel.py:35-66)."""

    def __init__(self, config: GAVAEConfig,
                 vae_model: Optional[DAVAEModel] = None,
                 vae_params=None):
        self.config = config
        self.vae_model = vae_model or DAVAEModel(config.vae)
        self.vae_params = vae_params
        self.gen = LatentGenerator(config.latent_size)
        self.disc = LatentDiscriminator(config.cls_num)
        self.g_params = None
        self.d_params = None

    def train_gan(self, latents, labels, steps: int = 200, seed: int = 0):
        """Adversarial training over encoded latents
        (reference: GAVAEModel.py:60-66 gan_training)."""
        cfg = self.config
        rng = jax.random.PRNGKey(seed)
        rng, gk, dk = jax.random.split(rng, 3)
        noise = jnp.zeros((1, cfg.noise_size))
        onehot = jnp.zeros((1, cfg.cls_num))
        self.g_params = self.gen.init(gk, noise, onehot)["params"]
        self.d_params = self.disc.init(
            dk, jnp.zeros((1, cfg.latent_size)))["params"]
        g_tx = optax.adam(cfg.gan_lr)
        d_tx = optax.adam(cfg.gan_lr)
        g_opt = g_tx.init(self.g_params)
        d_opt = d_tx.init(self.d_params)

        @jax.jit
        def one_round(g_params, d_params, g_opt, d_opt, rng):
            rng, k1, k2 = jax.random.split(rng, 3)
            d_loss, d_grads = gan_d_step(self.disc, d_params, self.gen,
                                         g_params, latents, labels, k1,
                                         cfg.noise_size)
            upd, d_opt = d_tx.update(d_grads, d_opt, d_params)
            d_params = optax.apply_updates(d_params, upd)
            g_loss, g_grads = gan_g_step(self.disc, d_params, self.gen,
                                         g_params, labels, k2,
                                         cfg.noise_size)
            upd, g_opt = g_tx.update(g_grads, g_opt, g_params)
            g_params = optax.apply_updates(g_params, upd)
            return g_params, d_params, g_opt, d_opt, rng, d_loss, g_loss

        d_loss = g_loss = None
        for _ in range(steps):
            (self.g_params, self.d_params, g_opt, d_opt, rng, d_loss,
             g_loss) = one_round(self.g_params, self.d_params, g_opt,
                                 d_opt, rng)
        return float(d_loss), float(g_loss)

    def sample_latents(self, n: int, label: int = 0, seed: int = 0):
        rng = jax.random.PRNGKey(seed)
        noise = jax.random.normal(rng, (n, self.config.noise_size))
        onehot = jax.nn.one_hot(
            jnp.full((n,), label, jnp.int32), self.config.cls_num)
        return self.gen.apply({"params": self.g_params}, noise, onehot)

    def generate(self, n: int, label: int = 0, seed: int = 0,
                 max_length: int = 32, bos_id: int = 0):
        """noise → latent → text (reference: GAVAEModel.py:55-58)."""
        assert self.vae_params is not None, "needs trained DAVAE params"
        latents = self.sample_latents(n, label, seed)
        return text_from_latent_code_batch(self.vae_model, self.vae_params,
                                           latents, max_length=max_length,
                                           bos_id=bos_id)
