"""torch → jax weights for GAVAE (GAN over DAVAE latents).

The published GAVAE checkpoint is the DAVAE (`vae_model.*` — import via
davae.convert); the GAN nets live in `gans_process` (plain attrs, not a
registered submodule: fengshen/models/GAVAE/GAVAEModel.py:41 +
gans_model.py:136-180), so when they are saved it is as standalone
Gen_Net / CLS_Net state dicts — mapped here.
"""

from __future__ import annotations

from typing import Any, Mapping

from fengshen_tpu.utils.convert_common import (make_helpers,
                                               unwrap_lightning)


def gen_to_params(state_dict: Mapping[str, Any]) -> dict:
    """Gen_Net (gans_model.py:99-133) → LatentGenerator."""
    sd = unwrap_lightning(state_dict)
    _, lin, _ = make_helpers(sd)
    return {"x2_input": lin("x2_input"), "fc1": lin("fc1"),
            "fc2": lin("fc2"), "fc3": lin("fc3"), "out": lin("out")}


def cls_to_params(state_dict: Mapping[str, Any]) -> dict:
    """CLS_Net (gans_model.py:35-93) → LatentDiscriminator. The torch
    `out` maps onto the first cls_num rows of ours (we keep one extra
    fake-class row, zero-initialised on import)."""
    import numpy as np

    sd = unwrap_lightning(state_dict)
    _, lin, _ = make_helpers(sd)
    out = lin("out")
    k, b = out["kernel"], out["bias"]
    out = {"kernel": np.concatenate(
        [k, np.zeros((k.shape[0], 1), k.dtype)], 1),
        "bias": np.concatenate([b, np.zeros((1,), b.dtype)])}
    return {"fc1": lin("fc1"), "fc2": lin("fc2"), "out": out}


#: fs→torch exports: derived exact inverses of the two importers
from fengshen_tpu.utils.convert_common import (  # noqa: E402
    make_derived_export)

gen_params_to_torch_state = make_derived_export(gen_to_params)
cls_params_to_torch_state = make_derived_export(cls_to_params)
