"""MegatronBert config (HF-compatible field names)."""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any


@dataclasses.dataclass
class MegatronBertConfig:
    vocab_size: int = 29056
    hidden_size: int = 1024
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    intermediate_size: int = 4096
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    pad_token_id: int = 0
    num_labels: int = 2
    # TPU-native knobs
    dtype: str = "bfloat16"
    param_dtype: str = "float32"
    gradient_checkpointing: bool = False
    scan_layers: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def from_pretrained(cls, path: str) -> "MegatronBertConfig":
        cfg_file = os.path.join(path, "config.json") if os.path.isdir(path) \
            else path
        with open(cfg_file) as f:
            raw = json.load(f)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in raw.items() if k in known})

    def save_pretrained(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "config.json"), "w") as f:
            json.dump(dataclasses.asdict(self) |
                      {"model_type": "megatron-bert"}, f, indent=2)

    @classmethod
    def small_test_config(cls, **overrides: Any) -> "MegatronBertConfig":
        base = dict(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=64,
                    max_position_embeddings=64)
        base.update(overrides)
        return cls(**base)
