"""torch(HF) → jax weights for MegatronBert."""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from fengshen_tpu.utils.convert_common import tensor as _tensor

from fengshen_tpu.models.megatron_bert.configuration_megatron_bert import (
    MegatronBertConfig)


def torch_to_params(state_dict: Mapping[str, Any],
                    config: MegatronBertConfig,
                    head: str = "pretraining") -> dict:
    """Map HF MegatronBert* state_dict → flax params.

    torch Linear [out, in] → kernel.T; LayerNorm weight → scale.
    `head` ∈ {pretraining, masked_lm, sequence_classification,
    token_classification, none}.
    """

    def t(name):
        return _tensor(state_dict, name)

    def lin(prefix):
        return {"kernel": t(f"{prefix}.weight").T,
                "bias": t(f"{prefix}.bias")}

    def ln(prefix):
        return {"scale": t(f"{prefix}.weight"), "bias": t(f"{prefix}.bias")}

    def layer_tree(i: int) -> dict:
        pre = f"bert.encoder.layer.{i}"
        return {
            "attention_ln": ln(f"{pre}.attention.ln"),
            "self": {"query": lin(f"{pre}.attention.self.query"),
                     "key": lin(f"{pre}.attention.self.key"),
                     "value": lin(f"{pre}.attention.self.value")},
            "attention_output_dense": lin(f"{pre}.attention.output.dense"),
            "ln": ln(f"{pre}.ln"),
            "intermediate_dense": lin(f"{pre}.intermediate.dense"),
            "output_dense": lin(f"{pre}.output.dense"),
        }

    bert: dict = {
        "word_embeddings": {
            "embedding": t("bert.embeddings.word_embeddings.weight")},
        "position_embeddings": {
            "embedding": t("bert.embeddings.position_embeddings.weight")},
        "token_type_embeddings": {
            "embedding": t("bert.embeddings.token_type_embeddings.weight")},
        "ln": ln("bert.encoder.ln"),
    }
    if config.scan_layers:
        import jax
        trees = [layer_tree(i) for i in range(config.num_hidden_layers)]
        bert["layer"] = {"block": jax.tree_util.tree_map(
            lambda *xs: np.stack(xs), *trees)}
    else:
        for i in range(config.num_hidden_layers):
            bert[f"layer_{i}"] = layer_tree(i)
    if "bert.pooler.dense.weight" in state_dict:
        bert["pooler"] = lin("bert.pooler.dense")

    params: dict = {"bert": bert}
    if head in ("pretraining", "masked_lm") and \
            "cls.predictions.transform.dense.weight" in state_dict:
        params["cls_predictions"] = {
            "transform_dense": lin("cls.predictions.transform.dense"),
            "transform_ln": ln("cls.predictions.transform.LayerNorm"),
            "bias": t("cls.predictions.bias"),
        }
    if head == "pretraining" and \
            "cls.seq_relationship.weight" in state_dict:
        params["cls_seq_relationship"] = lin("cls.seq_relationship")
    if head == "sequence_classification" and "classifier.weight" in \
            state_dict:
        params["classifier"] = lin("classifier")
    if head == "token_classification" and "classifier.weight" in state_dict:
        params["classifier"] = lin("classifier")
    return params


def params_to_torch_state(params: Mapping[str, Any],
                          config: MegatronBertConfig) -> dict:
    """Inverse of `torch_to_params`: flax params → an HF
    MegatronBert-style state_dict (numpy values), so checkpoints trained
    here publish back into the reference's torch ecosystem
    (`transformers.MegatronBertModel.load_state_dict`). Layer trees are
    un-stacked from the scan layout when present."""
    import jax

    def arr(x):
        return np.asarray(x)

    def lin(prefix, tree):
        return {f"{prefix}.weight": arr(tree["kernel"]).T,
                f"{prefix}.bias": arr(tree["bias"])}

    def ln(prefix, tree):
        return {f"{prefix}.weight": arr(tree["scale"]),
                f"{prefix}.bias": arr(tree["bias"])}

    bert = params["bert"]
    state: dict = {
        "bert.embeddings.word_embeddings.weight":
            arr(bert["word_embeddings"]["embedding"]),
        "bert.embeddings.position_embeddings.weight":
            arr(bert["position_embeddings"]["embedding"]),
        "bert.embeddings.token_type_embeddings.weight":
            arr(bert["token_type_embeddings"]["embedding"]),
    }
    state.update(ln("bert.encoder.ln", bert["ln"]))

    if config.scan_layers:
        stacked = bert["layer"]["block"]
        layers = [jax.tree_util.tree_map(lambda x, i=i: np.asarray(x)[i],
                                         stacked)
                  for i in range(config.num_hidden_layers)]
    else:
        layers = [bert[f"layer_{i}"]
                  for i in range(config.num_hidden_layers)]
    for i, tree in enumerate(layers):
        pre = f"bert.encoder.layer.{i}"
        state.update(ln(f"{pre}.attention.ln", tree["attention_ln"]))
        for name in ("query", "key", "value"):
            state.update(lin(f"{pre}.attention.self.{name}",
                             tree["self"][name]))
        state.update(lin(f"{pre}.attention.output.dense",
                         tree["attention_output_dense"]))
        state.update(ln(f"{pre}.ln", tree["ln"]))
        state.update(lin(f"{pre}.intermediate.dense",
                         tree["intermediate_dense"]))
        state.update(lin(f"{pre}.output.dense", tree["output_dense"]))

    if "pooler" in bert:
        state.update(lin("bert.pooler.dense", bert["pooler"]))
    if "cls_predictions" in params:
        cp = params["cls_predictions"]
        state.update(lin("cls.predictions.transform.dense",
                         cp["transform_dense"]))
        state.update(ln("cls.predictions.transform.LayerNorm",
                        cp["transform_ln"]))
        state["cls.predictions.bias"] = arr(cp["bias"])
        # HF ties the decoder to the word embeddings
        state["cls.predictions.decoder.weight"] = arr(
            bert["word_embeddings"]["embedding"])
        state["cls.predictions.decoder.bias"] = arr(cp["bias"])
    if "cls_seq_relationship" in params:
        state.update(lin("cls.seq_relationship",
                         params["cls_seq_relationship"]))
    if "classifier" in params:
        state.update(lin("classifier", params["classifier"]))
    return state
