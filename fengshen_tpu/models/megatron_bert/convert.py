"""torch(HF) → jax weights for MegatronBert."""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from fengshen_tpu.utils.convert_common import tensor as _tensor

from fengshen_tpu.models.megatron_bert.configuration_megatron_bert import (
    MegatronBertConfig)


def torch_to_params(state_dict: Mapping[str, Any],
                    config: MegatronBertConfig,
                    head: str = "pretraining") -> dict:
    """Map HF MegatronBert* state_dict → flax params.

    torch Linear [out, in] → kernel.T; LayerNorm weight → scale.
    `head` ∈ {pretraining, masked_lm, sequence_classification,
    token_classification, none}.
    """

    def t(name):
        return _tensor(state_dict, name)

    def lin(prefix):
        return {"kernel": t(f"{prefix}.weight").T,
                "bias": t(f"{prefix}.bias")}

    def ln(prefix):
        return {"scale": t(f"{prefix}.weight"), "bias": t(f"{prefix}.bias")}

    def layer_tree(i: int) -> dict:
        pre = f"bert.encoder.layer.{i}"
        return {
            "attention_ln": ln(f"{pre}.attention.ln"),
            "self": {"query": lin(f"{pre}.attention.self.query"),
                     "key": lin(f"{pre}.attention.self.key"),
                     "value": lin(f"{pre}.attention.self.value")},
            "attention_output_dense": lin(f"{pre}.attention.output.dense"),
            "ln": ln(f"{pre}.ln"),
            "intermediate_dense": lin(f"{pre}.intermediate.dense"),
            "output_dense": lin(f"{pre}.output.dense"),
        }

    bert: dict = {
        "word_embeddings": {
            "embedding": t("bert.embeddings.word_embeddings.weight")},
        "position_embeddings": {
            "embedding": t("bert.embeddings.position_embeddings.weight")},
        "token_type_embeddings": {
            "embedding": t("bert.embeddings.token_type_embeddings.weight")},
        "ln": ln("bert.encoder.ln"),
    }
    if config.scan_layers:
        import jax
        trees = [layer_tree(i) for i in range(config.num_hidden_layers)]
        bert["layer"] = {"block": jax.tree_util.tree_map(
            lambda *xs: np.stack(xs), *trees)}
    else:
        for i in range(config.num_hidden_layers):
            bert[f"layer_{i}"] = layer_tree(i)
    if "bert.pooler.dense.weight" in state_dict:
        bert["pooler"] = lin("bert.pooler.dense")

    params: dict = {"bert": bert}
    if head in ("pretraining", "masked_lm") and \
            "cls.predictions.transform.dense.weight" in state_dict:
        params["cls_predictions"] = {
            "transform_dense": lin("cls.predictions.transform.dense"),
            "transform_ln": ln("cls.predictions.transform.LayerNorm"),
            "bias": t("cls.predictions.bias"),
        }
    if head == "pretraining" and \
            "cls.seq_relationship.weight" in state_dict:
        params["cls_seq_relationship"] = lin("cls.seq_relationship")
    if head == "sequence_classification" and "classifier.weight" in \
            state_dict:
        params["classifier"] = lin("classifier")
    if head == "token_classification" and "classifier.weight" in state_dict:
        params["classifier"] = lin("classifier")
    return params
