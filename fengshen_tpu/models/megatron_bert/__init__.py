"""MegatronBert / Erlangshen family.

The reference trains Erlangshen with HF's MegatronBertForPreTraining
(reference: fengshen/examples/pretrain_erlangshen_bert/
pretrain_erlangshen.py:2-6,141); here it is a native flax implementation
(pre-LN Megatron residual ordering) with an HF torch weight importer.
"""

from fengshen_tpu.models.megatron_bert.configuration_megatron_bert import (
    MegatronBertConfig)
from fengshen_tpu.models.megatron_bert.modeling_megatron_bert import (
    MegatronBertModel, MegatronBertForPreTraining, MegatronBertForMaskedLM,
    MegatronBertForSequenceClassification,
    MegatronBertForTokenClassification)

__all__ = ["MegatronBertConfig", "MegatronBertModel",
           "MegatronBertForPreTraining", "MegatronBertForMaskedLM",
           "MegatronBertForSequenceClassification",
           "MegatronBertForTokenClassification"]

from fengshen_tpu.models.megatron_bert.task_heads import (MegatronBertForQuestionAnswering, MegatronBertForMultipleChoice)
__all__ += ['MegatronBertForQuestionAnswering', 'MegatronBertForMultipleChoice']
