"""MegatronBert in flax (pre-LN residual ordering), HF-weight-compatible.

Layer semantics match HF's MegatronBert (itself NVIDIA Megatron-derived):
attention = dense(self(ln(h))) + h; ffn = dense(act(dense(ln(h)))) + h; a
final encoder LayerNorm; embeddings = word+pos+tokentype then dropout (the
embedding LayerNorm of vanilla BERT moved into the first layer's pre-LN).
The pretrain head is MLM + sentence-order (the reference trains SOP via its
Erlangshen collator, reference: fengshen/examples/pretrain_erlangshen_bert/
pretrain_erlangshen.py:35-123).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from fengshen_tpu.models.megatron_bert.configuration_megatron_bert import (
    MegatronBertConfig)
from fengshen_tpu.ops.activations import get_activation
from fengshen_tpu.ops.embedding import VocabParallelEmbed
from fengshen_tpu.ops.attention import dot_product_attention
from fengshen_tpu.ops.norms import LayerNorm
from fengshen_tpu.sharding import (to_partition_rules,
                                    with_logical_constraint)

PARAM_LOGICAL_AXES: list[tuple[str, tuple]] = [
    ("word_embeddings/embedding", ("vocab", "embed")),
    ("position_embeddings/embedding", ("relpos", None)),
    ("token_type_embeddings/embedding", (None, None)),
    (r"(query|key|value)/kernel", ("embed", "heads")),
    (r"attention/output_dense/kernel", ("heads", "embed")),
    (r"intermediate_dense/kernel", ("embed", "mlp")),
    (r"output_dense/kernel", ("mlp", "embed")),
    (r"(pooler|transform|seq_relationship|classifier)", (None,)),
    ("ln", ("norm",)),
    (".*", (None,)),
]
PARTITION_RULES = to_partition_rules(PARAM_LOGICAL_AXES)

SCAN_PARAM_LOGICAL_AXES: list[tuple[str, tuple]] = [
    ("word_embeddings/embedding", ("vocab", "embed")),
    ("position_embeddings/embedding", ("relpos", None)),
    ("token_type_embeddings/embedding", (None, None)),
    (r"layer/.*(query|key|value)/kernel", ("layers", "embed", "heads")),
    (r"layer/.*attention/output_dense/kernel", ("layers", "heads", "embed")),
    (r"layer/.*intermediate_dense/kernel", ("layers", "embed", "mlp")),
    (r"layer/.*output_dense/kernel", ("layers", "mlp", "embed")),
    (r"(pooler|transform|seq_relationship|classifier)", (None,)),
    ("ln", ("norm",)),
    (".*", (None,)),
]
SCAN_PARTITION_RULES = to_partition_rules(SCAN_PARAM_LOGICAL_AXES)


def _dt(config):
    return jnp.dtype(config.dtype)


def _dense(cfg, feats, name):
    return nn.Dense(feats, dtype=_dt(cfg),
                    param_dtype=jnp.dtype(cfg.param_dtype),
                    kernel_init=nn.initializers.normal(
                        cfg.initializer_range), name=name)


class MegatronBertSelfAttention(nn.Module):
    config: MegatronBertConfig

    @nn.compact
    def __call__(self, hidden, attention_mask=None, deterministic=True):
        cfg = self.config
        batch, seq, _ = hidden.shape
        n_head, head_dim = cfg.num_attention_heads, cfg.head_dim
        q = _dense(cfg, cfg.hidden_size, "query")(hidden)
        k = _dense(cfg, cfg.hidden_size, "key")(hidden)
        v = _dense(cfg, cfg.hidden_size, "value")(hidden)
        q = q.reshape(batch, seq, n_head, head_dim)
        k = k.reshape(batch, seq, n_head, head_dim)
        v = v.reshape(batch, seq, n_head, head_dim)
        mask = None
        if attention_mask is not None:
            if attention_mask.ndim == 3:
                # per-sample [B, S, S] mask (UniMC's block-diagonal option
                # masking, reference: fengshen/models/unimc/
                # modeling_unimc.py:92-113 get_att_mask)
                mask = attention_mask[:, None].astype(bool)
            else:
                mask = attention_mask[:, None, None, :].astype(bool)
        drop_rng = None
        if not deterministic and cfg.attention_probs_dropout_prob > 0.0:
            drop_rng = self.make_rng("dropout")
        out = dot_product_attention(
            q, k, v, mask=mask, dropout_rng=drop_rng,
            dropout_rate=cfg.attention_probs_dropout_prob,
            deterministic=deterministic)
        out = with_logical_constraint(
            out, ("batch", "seq", "heads", None))
        return out.reshape(batch, seq, cfg.hidden_size)


class MegatronBertLayer(nn.Module):
    config: MegatronBertConfig

    @nn.compact
    def __call__(self, hidden, attention_mask=None, deterministic=True):
        cfg = self.config
        # attention: residual + dense(dropout(self(ln(h))))
        h = LayerNorm(epsilon=cfg.layer_norm_eps, name="attention_ln")(hidden)
        h = MegatronBertSelfAttention(cfg, name="self")(
            h, attention_mask, deterministic)
        h = _dense(cfg, cfg.hidden_size, "attention_output_dense")(h)
        h = nn.Dropout(cfg.hidden_dropout_prob)(h,
                                                deterministic=deterministic)
        hidden = hidden + h
        # ffn: residual + dense(dropout(act(dense(ln(h)))))
        h = LayerNorm(epsilon=cfg.layer_norm_eps, name="ln")(hidden)
        h = _dense(cfg, cfg.intermediate_size, "intermediate_dense")(h)
        h = get_activation(cfg.hidden_act)(h)
        h = with_logical_constraint(h, ("batch", "seq", "mlp"))
        h = _dense(cfg, cfg.hidden_size, "output_dense")(h)
        h = nn.Dropout(cfg.hidden_dropout_prob)(h,
                                                deterministic=deterministic)
        return hidden + h


class _ScanBertLayer(nn.Module):
    config: MegatronBertConfig

    @nn.compact
    def __call__(self, hidden, attention_mask, deterministic):
        out = MegatronBertLayer(self.config, name="block")(
            hidden, attention_mask, deterministic)
        return out, None


class MegatronBertModel(nn.Module):
    config: MegatronBertConfig
    add_pooling_layer: bool = True

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 position_ids=None, deterministic=True):
        cfg = self.config
        batch, seq = input_ids.shape
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        if position_ids is None:
            position_ids = jnp.arange(seq)[None, :]

        embed = lambda n, v, name, cls=nn.Embed: cls(  # noqa: E731
            n, cfg.hidden_size, dtype=_dt(cfg),
            param_dtype=jnp.dtype(cfg.param_dtype),
            embedding_init=nn.initializers.normal(cfg.initializer_range),
            name=name)
        hidden = embed(cfg.vocab_size, cfg.hidden_size,
                       "word_embeddings", VocabParallelEmbed)(input_ids) \
            + embed(cfg.max_position_embeddings, cfg.hidden_size,
                    "position_embeddings")(position_ids) \
            + embed(cfg.type_vocab_size, cfg.hidden_size,
                    "token_type_embeddings")(token_type_ids)
        hidden = nn.Dropout(cfg.hidden_dropout_prob)(
            hidden, deterministic=deterministic)
        hidden = with_logical_constraint(
            hidden, ("batch", "seq", None))

        if cfg.scan_layers:
            body = _ScanBertLayer
            if cfg.gradient_checkpointing:
                body = nn.remat(body, static_argnums=(3,),
                                policy=jax.checkpoint_policies
                                .nothing_saveable, prevent_cse=False)
            scan = nn.scan(body, variable_axes={"params": 0},
                           split_rngs={"params": True, "dropout": True},
                           in_axes=(nn.broadcast,) * 2,
                           length=cfg.num_hidden_layers)
            hidden, _ = scan(cfg, name="layer")(hidden, attention_mask,
                                                deterministic)
        else:
            layer_cls = MegatronBertLayer
            if cfg.gradient_checkpointing:
                layer_cls = nn.remat(
                    layer_cls, static_argnums=(3,),
                    policy=jax.checkpoint_policies.nothing_saveable)
            for i in range(cfg.num_hidden_layers):
                hidden = layer_cls(cfg, name=f"layer_{i}")(
                    hidden, attention_mask, deterministic)
        hidden = LayerNorm(epsilon=cfg.layer_norm_eps, name="ln")(hidden)

        pooled = None
        if self.add_pooling_layer:
            pooled = jnp.tanh(_dense(cfg, cfg.hidden_size,
                                     "pooler")(hidden[:, 0]))
        return hidden, pooled


class MLMHead(nn.Module):
    """cls.predictions: transform (dense+act+LN) + tied decoder + bias."""

    config: MegatronBertConfig

    @nn.compact
    def __call__(self, hidden, word_embedding):
        cfg = self.config
        h = _dense(cfg, cfg.hidden_size, "transform_dense")(hidden)
        h = get_activation(cfg.hidden_act)(h)
        h = LayerNorm(epsilon=cfg.layer_norm_eps, name="transform_ln")(h)
        logits = h @ word_embedding.T.astype(h.dtype)
        bias = self.param("bias", nn.initializers.zeros,
                          (cfg.vocab_size,), jnp.dtype(cfg.param_dtype))
        return logits + bias


class MegatronBertForPreTraining(nn.Module):
    """MLM + sentence-order head (the Erlangshen pretrain objective)."""

    config: MegatronBertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 position_ids=None, deterministic=True):
        hidden, pooled = MegatronBertModel(self.config, name="bert")(
            input_ids, attention_mask, token_type_ids, position_ids,
            deterministic)
        wte = self.variables["params"]["bert"]["word_embeddings"][
            "embedding"]
        mlm_logits = MLMHead(self.config, name="cls_predictions")(
            hidden, wte)
        sop_logits = _dense(self.config, 2, "cls_seq_relationship")(pooled)
        return mlm_logits, sop_logits

    def partition_rules(self):
        return to_partition_rules(
            SCAN_PARAM_LOGICAL_AXES if self.config.scan_layers
            else PARAM_LOGICAL_AXES)


class MegatronBertForMaskedLM(nn.Module):
    config: MegatronBertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 position_ids=None, deterministic=True,
                 return_hidden=False):
        hidden, _ = MegatronBertModel(self.config, add_pooling_layer=False,
                                      name="bert")(
            input_ids, attention_mask, token_type_ids, position_ids,
            deterministic)
        wte = self.variables["params"]["bert"]["word_embeddings"][
            "embedding"]
        logits = MLMHead(self.config, name="cls_predictions")(hidden, wte)
        return (logits, hidden) if return_hidden else logits

    def partition_rules(self):
        return to_partition_rules(
            SCAN_PARAM_LOGICAL_AXES if self.config.scan_layers
            else PARAM_LOGICAL_AXES)


class MegatronBertForSequenceClassification(nn.Module):
    config: MegatronBertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 position_ids=None, deterministic=True):
        cfg = self.config
        _, pooled = MegatronBertModel(cfg, name="bert")(
            input_ids, attention_mask, token_type_ids, position_ids,
            deterministic)
        pooled = nn.Dropout(cfg.hidden_dropout_prob)(
            pooled, deterministic=deterministic)
        return _dense(cfg, cfg.num_labels, "classifier")(pooled)

    def partition_rules(self):
        return to_partition_rules(
            SCAN_PARAM_LOGICAL_AXES if self.config.scan_layers
            else PARAM_LOGICAL_AXES)


class MegatronBertForTokenClassification(nn.Module):
    config: MegatronBertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 position_ids=None, deterministic=True):
        cfg = self.config
        hidden, _ = MegatronBertModel(cfg, add_pooling_layer=False,
                                      name="bert")(
            input_ids, attention_mask, token_type_ids, position_ids,
            deterministic)
        hidden = nn.Dropout(cfg.hidden_dropout_prob)(
            hidden, deterministic=deterministic)
        return _dense(cfg, cfg.num_labels, "classifier")(hidden)

    def partition_rules(self):
        return to_partition_rules(
            SCAN_PARAM_LOGICAL_AXES if self.config.scan_layers
            else PARAM_LOGICAL_AXES)
