"""Shared typed-span decoding for the extraction pipelines (UBERT/UniEX)."""

from __future__ import annotations

from typing import Any

import numpy as np


def decode_spans(scores: np.ndarray, ids: list[int], tokenizer: Any,
                 text_offset: int, threshold: float,
                 max_span_len: int = 32) -> list[dict]:
    """scores [S, S] (start × end) → entity dicts above threshold.

    Spans start within the text region (after `text_offset`), skip the final
    [SEP], and are capped at `max_span_len` tokens.
    """
    entities: list[dict] = []
    n = len(ids) - 1  # drop final [SEP]
    for i in range(text_offset, n):
        for j in range(i, min(i + max_span_len, n)):
            if scores[i, j] > threshold:
                entities.append({
                    "entity_name": tokenizer.decode(
                        ids[i:j + 1]).replace(" ", ""),
                    "score": float(scores[i, j]),
                    "start": i - text_offset,
                    "end": j - text_offset,
                })
    return entities
