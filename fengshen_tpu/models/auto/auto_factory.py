"""Lazy auto registry for fengshen-tpu model families."""

from __future__ import annotations

import importlib
import json
import os
from typing import Any, Optional

#: model_type → (module, config class, {head: model class}) — names only,
#: imported lazily like the reference's _LazyAutoMapping
#: (reference: fengshen/models/auto/auto_factory.py:553)
MODEL_REGISTRY: dict[str, tuple[str, str, dict[str, str]]] = {
    "llama": ("fengshen_tpu.models.llama", "LlamaConfig",
              {"causal_lm": "LlamaForCausalLM", "base": "LlamaModel"}),
    "ziya_llama": ("fengshen_tpu.models.llama", "LlamaConfig",
                   {"causal_lm": "LlamaForCausalLM"}),
    "gpt2": ("fengshen_tpu.models.gpt2", "GPT2Config",
             {"causal_lm": "GPT2LMHeadModel", "base": "GPT2Model"}),
    "megatron-bert": ("fengshen_tpu.models.megatron_bert",
                      "MegatronBertConfig",
                      {"base": "MegatronBertModel",
                       "pretraining": "MegatronBertForPreTraining",
                       "masked_lm": "MegatronBertForMaskedLM",
                       "sequence_classification":
                           "MegatronBertForSequenceClassification",
                       "token_classification":
                           "MegatronBertForTokenClassification"}),
    "t5": ("fengshen_tpu.models.t5", "T5Config",
           {"base": "T5Model",
            "conditional_generation": "T5ForConditionalGeneration",
            "encoder": "T5EncoderModel"}),
    "bart": ("fengshen_tpu.models.bart", "BartConfig",
             {"base": "BartModel",
              "conditional_generation": "BartForConditionalGeneration"}),
    "roformer": ("fengshen_tpu.models.roformer", "RoFormerConfig",
                 {"base": "RoFormerModel",
                  "masked_lm": "RoFormerForMaskedLM",
                  "sequence_classification":
                      "RoFormerForSequenceClassification"}),
    "albert": ("fengshen_tpu.models.albert", "AlbertConfig",
               {"base": "AlbertModel", "masked_lm": "AlbertForMaskedLM",
                "sequence_classification":
                    "AlbertForSequenceClassification"}),
    "deberta-v2": ("fengshen_tpu.models.deberta_v2", "DebertaV2Config",
                   {"base": "DebertaV2Model",
                    "masked_lm": "DebertaV2ForMaskedLM",
                    "sequence_classification":
                        "DebertaV2ForSequenceClassification"}),
    "longformer": ("fengshen_tpu.models.longformer", "LongformerConfig",
                   {"base": "LongformerModel",
                    "masked_lm": "LongformerForMaskedLM",
                    "sequence_classification":
                        "LongformerForSequenceClassification"}),
    "bert": ("fengshen_tpu.models.bert", "BertConfig",
             {"base": "BertModel", "masked_lm": "BertForMaskedLM"}),
    "pegasus": ("fengshen_tpu.models.pegasus", "PegasusConfig",
                {"conditional_generation":
                     "PegasusForConditionalGeneration"}),
    "zen": ("fengshen_tpu.models.zen", "ZenConfig",
            {"base": "ZenModel",
             "sequence_classification": "ZenForSequenceClassification"}),
    "deltalm": ("fengshen_tpu.models.deltalm", "DeltaLMConfig",
                {"conditional_generation":
                     "DeltaLMForConditionalGeneration"}),
    "zen2": ("fengshen_tpu.models.zen2", "Zen2Config",
             {"base": "Zen2Model", "masked_lm": "Zen2ForMaskedLM",
              "sequence_classification": "Zen2ForSequenceClassification",
              "token_classification": "Zen2ForTokenClassification",
              "question_answering": "Zen2ForQuestionAnswering"}),
    "davae": ("fengshen_tpu.models.davae", "DAVAEConfig",
              {"base": "DAVAEModel"}),
    "gavae": ("fengshen_tpu.models.gavae", "GAVAEConfig",
              {"base": "GAVAEModel"}),
    "ppvae": ("fengshen_tpu.models.ppvae", "PPVAEConfig",
              {"base": "PPVAEModel"}),
    "della": ("fengshen_tpu.models.deepvae", "DellaConfig",
              {"base": "DellaModel"}),
    "transfo-xl-denoise": ("fengshen_tpu.models.transfo_xl_denoise",
                           "TransfoXLDenoiseConfig",
                           {"base": "TransfoXLDenoiseModel"}),
    "transfo-xl-paraphrase": ("fengshen_tpu.models.transfo_xl_paraphrase",
                              "TransfoXLParaphraseConfig",
                              {"base": "TransfoXLParaphraseModel"}),
    "transfo-xl-reasoning": ("fengshen_tpu.models.transfo_xl_reasoning",
                             "TransfoXLReasoningConfig",
                             {"base": "TransfoXLReasoningModel"}),
}


def register_model(model_type: str, module: str, config_cls: str,
                   heads: dict[str, str]) -> None:
    """Extend the registry (the reference's trust-remote-code loader role,
    reference: fengshen/models/auto/dynamic.py:107)."""
    MODEL_REGISTRY[model_type] = (module, config_cls, heads)


def _resolve(model_type: str):
    if model_type not in MODEL_REGISTRY:
        raise KeyError(
            f"unknown model_type {model_type!r}; known: "
            f"{sorted(MODEL_REGISTRY)}")
    module_name, config_name, heads = MODEL_REGISTRY[model_type]
    module = importlib.import_module(module_name)
    return module, config_name, heads


def _model_type_from_path(path: str) -> str:
    cfg_file = os.path.join(path, "config.json") if os.path.isdir(path) \
        else path
    with open(cfg_file) as f:
        raw = json.load(f)
    return raw.get("fengshen_model_type", raw.get("model_type", ""))


class AutoConfig:
    @staticmethod
    def from_pretrained(path: str, **kwargs) -> Any:
        model_type = _model_type_from_path(path)
        module, config_name, _ = _resolve(model_type)
        return getattr(module, config_name).from_pretrained(path)

    @staticmethod
    def for_model(model_type: str, **kwargs) -> Any:
        module, config_name, _ = _resolve(model_type)
        return getattr(module, config_name)(**kwargs)


class AutoModel:
    @staticmethod
    def from_config(config: Any, head: str = "base") -> Any:
        for model_type, (module_name, config_name, heads) in \
                MODEL_REGISTRY.items():
            if type(config).__name__ == config_name and head in heads:
                module = importlib.import_module(module_name)
                return getattr(module, heads[head])(config)
        raise KeyError(
            f"no registered model for config {type(config).__name__} "
            f"with head {head!r}")

    @staticmethod
    def from_pretrained(path: str, head: str = "base") -> tuple[Any, Any]:
        """Returns (model, params) for checkpoints with a converter."""
        model_type = _model_type_from_path(path)
        module, config_name, heads = _resolve(model_type)
        config = getattr(module, config_name).from_pretrained(path)
        if head not in heads:
            raise KeyError(f"model_type {model_type!r} has no head "
                           f"{head!r}; known: {sorted(heads)}")
        model = getattr(module, heads[head])(config)
        params = None
        try:
            convert = importlib.import_module(module.__name__ + ".convert")
        except ModuleNotFoundError:
            return model, params
        try:
            if hasattr(convert, "load_hf_pretrained"):
                _, params = convert.load_hf_pretrained(path, config)
            elif hasattr(convert, "torch_to_params"):
                # generic path: reference-format torch weights in the dir
                # → the family converter (passing the requested head when
                # the converter dispatches on it)
                import inspect

                from fengshen_tpu.utils.convert_common import \
                    load_torch_checkpoint
                state = load_torch_checkpoint(path)
                kwargs = {}
                if "head" in inspect.signature(
                        convert.torch_to_params).parameters:
                    kwargs["head"] = head
                elif head != "base":
                    import logging
                    logging.getLogger("fengshen_tpu").warning(
                        "%s.convert.torch_to_params does not dispatch on "
                        "heads; the tree returned for head=%r may miss "
                        "head weights — flax will error at apply if so. "
                        "Use the family converter directly for full "
                        "control.", module.__name__, head)
                params = convert.torch_to_params(state, config, **kwargs)
        except FileNotFoundError:
            pass  # config-only dir: return a randomly initialisable model
        except ModuleNotFoundError:
            pass  # torch-less install: model with params=None, as before
        return model, params


#: model_type → (module, factory attr) for tokenizers that HF
#: AutoTokenizer cannot resolve (reference:
#: fengshen/models/auto/tokenization_auto.py TOKENIZER_MAPPING)
TOKENIZER_REGISTRY: dict[str, tuple[str, str]] = {
    # char-level Randeng T5: BERT vocab behind a T5 surface
    "megatron_t5": ("fengshen_tpu.models.t5", "T5Tokenizer"),
    "t5_char": ("fengshen_tpu.models.t5", "T5Tokenizer"),
}


class AutoTokenizer:
    """Resolve fengshen-specific tokenizers by the checkpoint's
    config.json (``tokenizer_class``/``fengshen_model_type``/
    ``model_type``), falling through to HF AutoTokenizer."""

    @staticmethod
    def from_pretrained(path: str, **kwargs) -> Any:
        keys = []
        cfg_file = os.path.join(path, "config.json") \
            if os.path.isdir(path) else None
        if cfg_file and os.path.exists(cfg_file):
            with open(cfg_file) as f:
                raw = json.load(f)
            keys = [raw.get("tokenizer_class", ""),
                    raw.get("fengshen_model_type", ""),
                    raw.get("model_type", "")]
        for key in keys:
            if key in TOKENIZER_REGISTRY:
                module_name, attr = TOKENIZER_REGISTRY[key]
                cls = getattr(importlib.import_module(module_name), attr)
                return cls.from_pretrained(path, **kwargs)
        import transformers
        return transformers.AutoTokenizer.from_pretrained(path, **kwargs)
