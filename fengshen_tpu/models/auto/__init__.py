"""Auto classes — lazy model/config/tokenizer registry.

Port of the reference's forked HF auto classes
(reference: fengshen/models/auto/ — `CONFIG_MAPPING_NAMES` at
configuration_auto.py:30-35, `_LazyAutoMapping` at auto_factory.py:553).
Resolution order: model_type from config.json → registry entry → class.
"""

from fengshen_tpu.models.auto.auto_factory import (AutoConfig, AutoModel,
                                                   AutoTokenizer,
                                                   register_model)

__all__ = ["AutoConfig", "AutoModel", "AutoTokenizer", "register_model"]
