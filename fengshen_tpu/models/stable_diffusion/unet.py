"""Conditional UNet for latent diffusion.

The denoiser of the Taiyi-SD workload (reference: finetune.py:139-144
`unet(noisy_latents, timesteps, encoder_hidden_states)`), compact but
structurally faithful: sinusoidal time embedding → MLP; down path of
resblocks (+ cross-attention on text states) with downsampling; middle
block; up path with skip connections and upsampling.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn


@dataclasses.dataclass
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    base_channels: int = 320
    channel_mults: Sequence[int] = (1, 2, 4, 4)
    num_heads: int = 8
    cross_attention_dim: int = 768
    dtype: str = "float32"

    @classmethod
    def small_test_config(cls, **overrides: Any) -> "UNetConfig":
        base = dict(base_channels=32, channel_mults=(1, 2), num_heads=2,
                    cross_attention_dim=32)
        base.update(overrides)
        return cls(**base)


def timestep_embedding(timesteps: jax.Array, dim: int) -> jax.Array:
    half = dim // 2
    freqs = jnp.exp(-math.log(10000.0) *
                    jnp.arange(half, dtype=jnp.float32) / half)
    args = timesteps.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


class _TimeResBlock(nn.Module):
    channels: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, temb):
        h = nn.GroupNorm(num_groups=min(8, x.shape[-1]), name="norm1")(x)
        h = nn.Conv(self.channels, (3, 3), padding="SAME",
                    dtype=self.dtype, name="conv1")(jax.nn.silu(h))
        h = h + nn.Dense(self.channels, dtype=self.dtype,
                         name="time_proj")(jax.nn.silu(temb))[:, None, None]
        h = nn.GroupNorm(num_groups=min(8, self.channels), name="norm2")(h)
        h = nn.Conv(self.channels, (3, 3), padding="SAME",
                    dtype=self.dtype, name="conv2")(jax.nn.silu(h))
        if x.shape[-1] != self.channels:
            x = nn.Conv(self.channels, (1, 1), dtype=self.dtype,
                        name="skip")(x)
        return x + h


class _CrossAttnBlock(nn.Module):
    channels: int
    num_heads: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, context):
        """x [B,H,W,C]; context [B,T,D] (text states)."""
        b, hh, ww, c = x.shape
        head_dim = self.channels // self.num_heads
        flat = x.reshape(b, hh * ww, c)
        h = nn.LayerNorm(name="norm")(flat)
        q = nn.Dense(self.channels, use_bias=False, dtype=self.dtype,
                     name="to_q")(h)
        k = nn.Dense(self.channels, use_bias=False, dtype=self.dtype,
                     name="to_k")(context)
        v = nn.Dense(self.channels, use_bias=False, dtype=self.dtype,
                     name="to_v")(context)
        q = q.reshape(b, -1, self.num_heads, head_dim)
        k = k.reshape(b, -1, self.num_heads, head_dim)
        v = v.reshape(b, -1, self.num_heads, head_dim)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                            preferred_element_type=jnp.float32)
        scores = scores / jnp.sqrt(jnp.asarray(head_dim, jnp.float32))
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
        out = nn.Dense(self.channels, dtype=self.dtype, name="to_out")(
            out.reshape(b, -1, self.channels))
        return x + out.reshape(b, hh, ww, c)


class UNet2DConditionModel(nn.Module):
    config: UNetConfig

    @nn.compact
    def __call__(self, latents, timesteps, encoder_hidden_states):
        """latents [B,H,W,C_in], timesteps [B], text states [B,T,D] →
        predicted noise/velocity [B,H,W,C_out]."""
        cfg = self.config
        dt = jnp.dtype(cfg.dtype)
        tdim = cfg.base_channels * 4
        temb = timestep_embedding(timesteps, cfg.base_channels)
        temb = nn.Dense(tdim, dtype=dt, name="time_mlp1")(temb)
        temb = nn.Dense(tdim, dtype=dt, name="time_mlp2")(
            jax.nn.silu(temb))

        h = nn.Conv(cfg.base_channels, (3, 3), padding="SAME", dtype=dt,
                    name="conv_in")(latents)
        skips = []
        for i, mult in enumerate(cfg.channel_mults):
            ch = cfg.base_channels * mult
            h = _TimeResBlock(ch, dt, name=f"down_{i}_res")(h, temb)
            h = _CrossAttnBlock(ch, cfg.num_heads,
                                dt, name=f"down_{i}_attn")(
                h, encoder_hidden_states)
            skips.append(h)  # one skip per resolution level
            if i < len(cfg.channel_mults) - 1:
                h = nn.Conv(ch, (3, 3), strides=(2, 2), padding="SAME",
                            dtype=dt, name=f"down_{i}_downsample")(h)

        h = _TimeResBlock(h.shape[-1], dt, name="mid_res1")(h, temb)
        h = _CrossAttnBlock(h.shape[-1], cfg.num_heads, dt,
                            name="mid_attn")(h, encoder_hidden_states)
        h = _TimeResBlock(h.shape[-1], dt, name="mid_res2")(h, temb)

        for i, mult in enumerate(reversed(cfg.channel_mults)):
            ch = cfg.base_channels * mult
            skip = skips.pop()
            if skip.shape[1] != h.shape[1]:
                b, hh, ww, c = h.shape
                h = jax.image.resize(h, (b, skip.shape[1], skip.shape[2],
                                         c), "nearest")
                h = nn.Conv(c, (3, 3), padding="SAME", dtype=dt,
                            name=f"up_{i}_upconv")(h)
            h = jnp.concatenate([h, skip], axis=-1)
            h = _TimeResBlock(ch, dt, name=f"up_{i}_res")(h, temb)
            h = _CrossAttnBlock(ch, cfg.num_heads, dt,
                                name=f"up_{i}_attn")(
                h, encoder_hidden_states)

        h = nn.GroupNorm(num_groups=min(8, h.shape[-1]),
                         name="norm_out")(h)
        return nn.Conv(cfg.out_channels, (3, 3), padding="SAME", dtype=dt,
                       name="conv_out")(jax.nn.silu(h))
