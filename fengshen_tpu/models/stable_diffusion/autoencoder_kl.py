"""KL autoencoder (the SD VAE): image ↔ latent.

Reference workload usage: vae.encode(pixels).latent_dist.sample() × 0.18215
(reference: finetune_taiyi_stable_diffusion/finetune.py:112-120). Compact
conv encoder/decoder with the same latent contract (4-channel latents at
1/8 resolution, scaling factor 0.18215).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

SCALING_FACTOR = 0.18215


@dataclasses.dataclass
class VAEConfig:
    in_channels: int = 3
    latent_channels: int = 4
    base_channels: int = 128
    channel_mults: Sequence[int] = (1, 2, 4, 4)
    dtype: str = "float32"

    @classmethod
    def small_test_config(cls, **overrides: Any) -> "VAEConfig":
        base = dict(base_channels=16, channel_mults=(1, 2))
        base.update(overrides)
        return cls(**base)

    def latent_shape(self, image_size: int) -> tuple[int, int, int]:
        """(H', W', C) of the latent for a square input: one 2x downsample
        per channel-mult stage after the first."""
        factor = 2 ** (len(self.channel_mults) - 1)
        return (image_size // factor, image_size // factor,
                self.latent_channels)


class _ResBlock(nn.Module):
    channels: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.GroupNorm(num_groups=min(8, x.shape[-1]),
                         name="norm1")(x)
        h = nn.Conv(self.channels, (3, 3), padding="SAME", dtype=self.dtype,
                    name="conv1")(jax.nn.silu(h))
        h = nn.GroupNorm(num_groups=min(8, self.channels), name="norm2")(h)
        h = nn.Conv(self.channels, (3, 3), padding="SAME", dtype=self.dtype,
                    name="conv2")(jax.nn.silu(h))
        if x.shape[-1] != self.channels:
            x = nn.Conv(self.channels, (1, 1), dtype=self.dtype,
                        name="skip")(x)
        return x + h


class AutoencoderKL(nn.Module):
    config: VAEConfig

    @nn.compact
    def __call__(self, pixels, rng=None):
        mean, logvar = self.encode(pixels)
        if rng is not None:
            latent = mean + jnp.exp(0.5 * logvar) * \
                jax.random.normal(rng, mean.shape)
        else:
            latent = mean
        recon = self.decode(latent)
        return recon, mean, logvar

    @nn.compact
    def encode(self, pixels):
        """pixels [B, H, W, C] → (mean, logvar) latents at 1/2^n res."""
        cfg = self.config
        dt = jnp.dtype(cfg.dtype)
        h = nn.Conv(cfg.base_channels, (3, 3), padding="SAME", dtype=dt,
                    name="conv_in")(pixels)
        for i, mult in enumerate(cfg.channel_mults):
            ch = cfg.base_channels * mult
            h = _ResBlock(ch, dt, name=f"down_{i}_res")(h)
            if i < len(cfg.channel_mults) - 1:
                h = nn.Conv(ch, (3, 3), strides=(2, 2), padding="SAME",
                            dtype=dt, name=f"down_{i}_downsample")(h)
        h = _ResBlock(h.shape[-1], dt, name="mid_res")(h)
        h = nn.GroupNorm(num_groups=min(8, h.shape[-1]),
                         name="norm_out")(h)
        stats = nn.Conv(2 * cfg.latent_channels, (3, 3), padding="SAME",
                        dtype=dt, name="conv_out")(jax.nn.silu(h))
        mean, logvar = jnp.split(stats, 2, axis=-1)
        return mean, jnp.clip(logvar, -30.0, 20.0)

    @nn.compact
    def decode(self, latent):
        cfg = self.config
        dt = jnp.dtype(cfg.dtype)
        mults = list(reversed(cfg.channel_mults))
        h = nn.Conv(cfg.base_channels * mults[0], (3, 3), padding="SAME",
                    dtype=dt, name="dec_conv_in")(latent)
        h = _ResBlock(h.shape[-1], dt, name="dec_mid_res")(h)
        for i, mult in enumerate(mults):
            ch = cfg.base_channels * mult
            h = _ResBlock(ch, dt, name=f"up_{i}_res")(h)
            if i < len(mults) - 1:
                b, hh, ww, c = h.shape
                h = jax.image.resize(h, (b, hh * 2, ww * 2, c), "nearest")
                h = nn.Conv(ch, (3, 3), padding="SAME", dtype=dt,
                            name=f"up_{i}_conv")(h)
        h = nn.GroupNorm(num_groups=min(8, h.shape[-1]),
                         name="dec_norm_out")(h)
        return nn.Conv(cfg.in_channels, (3, 3), padding="SAME", dtype=dt,
                       name="dec_conv_out")(jax.nn.silu(h))
