"""Diffusers-faithful AutoencoderKL (the SD first-stage VAE).

Reproduces the architecture of the released Taiyi-SD/SD-1.x VAE
(reference workload: fengshen/examples/finetune_taiyi_stable_diffusion/
finetune.py:112-120 — `vae.encode(...).latent_dist.sample() × 0.18215`)
with a parameter tree mirroring the diffusers state-dict keys so the
importer in `convert.py` loads released weights directly: 32-group
GroupNorm (eps 1e-6), 2 resnets per encoder block / 3 per decoder
block, single-head mid-block spatial attention, asymmetric (0,1)
downsample padding, and the quant/post-quant 1x1 convs.

The compact `autoencoder_kl.VAEConfig` tower remains as the small test
config for trainer plumbing. Layout NHWC (TPU-native).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from fengshen_tpu.models.stable_diffusion.unet_sd import (
    SD_PARTITION_RULES, Attention, Downsample2D, ResnetBlock2D,
    Upsample2D)

SCALING_FACTOR = 0.18215


@dataclasses.dataclass
class SDVAEConfig:
    """Field names follow diffusers' AutoencoderKL config."""

    in_channels: int = 3
    out_channels: int = 3
    latent_channels: int = 4
    block_out_channels: Sequence[int] = (128, 256, 512, 512)
    layers_per_block: int = 2
    norm_num_groups: int = 32
    dtype: str = "float32"

    @classmethod
    def small_test_config(cls, **overrides: Any) -> "SDVAEConfig":
        base = dict(block_out_channels=(16, 32), layers_per_block=1,
                    norm_num_groups=4)
        base.update(overrides)
        return cls(**base)

    def latent_shape(self, image_size: int) -> tuple[int, int, int]:
        factor = 2 ** (len(self.block_out_channels) - 1)
        return (image_size // factor, image_size // factor,
                self.latent_channels)


class VAEAttention(nn.Module):
    """diffusers VAE mid-block attention: group_norm inside the module,
    single head over the flattened spatial dim, residual add."""

    channels: int
    groups: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, hh, ww, c = x.shape
        h = nn.GroupNorm(num_groups=self.groups, epsilon=1e-6,
                         name="group_norm")(x)
        h = h.reshape(b, hh * ww, c)
        # to_q/to_k/to_v carry biases here (unlike the UNet attention) —
        # diffusers' VAE attention is nn.Linear with default bias
        q = nn.Dense(c, dtype=self.dtype, name="to_q")(h)
        k = nn.Dense(c, dtype=self.dtype, name="to_k")(h)
        v = nn.Dense(c, dtype=self.dtype, name="to_v")(h)
        scores = jnp.einsum("bqd,bkd->bqk", q, k,
                            preferred_element_type=jnp.float32)
        scores = scores / jnp.sqrt(jnp.asarray(c, jnp.float32))
        probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
        out = jnp.einsum("bqk,bkd->bqd", probs, v)
        out = nn.Dense(c, dtype=self.dtype, name="to_out_0")(out)
        return x + out.reshape(b, hh, ww, c)


class _VAEMidBlock(nn.Module):
    cfg: SDVAEConfig
    channels: int

    @nn.compact
    def __call__(self, h):
        cfg, dt = self.cfg, jnp.dtype(self.cfg.dtype)
        h = ResnetBlock2D(self.channels, cfg.norm_num_groups, 1e-6,
                          use_temb=False, dtype=dt, name="resnets_0")(h)
        h = VAEAttention(self.channels, cfg.norm_num_groups, dt,
                         name="attentions_0")(h)
        return ResnetBlock2D(self.channels, cfg.norm_num_groups, 1e-6,
                             use_temb=False, dtype=dt,
                             name="resnets_1")(h)


class _EncoderDownBlock(nn.Module):
    cfg: SDVAEConfig
    channels: int
    is_last: bool

    @nn.compact
    def __call__(self, h):
        cfg, dt = self.cfg, jnp.dtype(self.cfg.dtype)
        for j in range(cfg.layers_per_block):
            h = ResnetBlock2D(self.channels, cfg.norm_num_groups, 1e-6,
                              use_temb=False, dtype=dt,
                              name=f"resnets_{j}")(h)
        if not self.is_last:
            # diffusers VAE downsample pads (0,1) right/bottom only
            h = Downsample2D(self.channels, pad=((0, 1), (0, 1)),
                             dtype=dt, name="downsamplers_0")(h)
        return h


class _DecoderUpBlock(nn.Module):
    cfg: SDVAEConfig
    channels: int
    is_last: bool

    @nn.compact
    def __call__(self, h):
        cfg, dt = self.cfg, jnp.dtype(self.cfg.dtype)
        for j in range(cfg.layers_per_block + 1):
            h = ResnetBlock2D(self.channels, cfg.norm_num_groups, 1e-6,
                              use_temb=False, dtype=dt,
                              name=f"resnets_{j}")(h)
        if not self.is_last:
            h = Upsample2D(self.channels, dtype=dt,
                           name="upsamplers_0")(h)
        return h


class Encoder(nn.Module):
    cfg: SDVAEConfig

    @nn.compact
    def __call__(self, pixels):
        cfg, dt = self.cfg, jnp.dtype(self.cfg.dtype)
        h = nn.Conv(cfg.block_out_channels[0], (3, 3),
                    padding=((1, 1), (1, 1)), dtype=dt,
                    name="conv_in")(pixels)
        n = len(cfg.block_out_channels)
        for i, ch in enumerate(cfg.block_out_channels):
            h = _EncoderDownBlock(cfg, ch, is_last=(i == n - 1),
                                  name=f"down_blocks_{i}")(h)
        h = _VAEMidBlock(cfg, cfg.block_out_channels[-1],
                         name="mid_block")(h)
        h = nn.GroupNorm(num_groups=cfg.norm_num_groups, epsilon=1e-6,
                         name="conv_norm_out")(h)
        return nn.Conv(2 * cfg.latent_channels, (3, 3),
                       padding=((1, 1), (1, 1)), dtype=dt,
                       name="conv_out")(jax.nn.silu(h))


class Decoder(nn.Module):
    cfg: SDVAEConfig

    @nn.compact
    def __call__(self, latent):
        cfg, dt = self.cfg, jnp.dtype(self.cfg.dtype)
        rev = list(reversed(cfg.block_out_channels))
        h = nn.Conv(rev[0], (3, 3), padding=((1, 1), (1, 1)), dtype=dt,
                    name="conv_in")(latent)
        h = _VAEMidBlock(cfg, rev[0], name="mid_block")(h)
        n = len(rev)
        for i, ch in enumerate(rev):
            h = _DecoderUpBlock(cfg, ch, is_last=(i == n - 1),
                                name=f"up_blocks_{i}")(h)
        h = nn.GroupNorm(num_groups=cfg.norm_num_groups, epsilon=1e-6,
                         name="conv_norm_out")(h)
        return nn.Conv(cfg.out_channels, (3, 3), padding=((1, 1), (1, 1)),
                       dtype=dt, name="conv_out")(jax.nn.silu(h))


class SDAutoencoderKL(nn.Module):
    """encode → diagonal Gaussian moments; decode ← latents. Forward
    contract matches the compact tower (`autoencoder_kl.AutoencoderKL`)."""

    config: SDVAEConfig

    def setup(self):
        cfg = self.config
        dt = jnp.dtype(cfg.dtype)
        self.encoder = Encoder(cfg, name="encoder")
        self.decoder = Decoder(cfg, name="decoder")
        self.quant_conv = nn.Conv(2 * cfg.latent_channels, (1, 1),
                                  dtype=dt, name="quant_conv")
        self.post_quant_conv = nn.Conv(cfg.latent_channels, (1, 1),
                                       dtype=dt, name="post_quant_conv")

    def encode(self, pixels):
        moments = self.quant_conv(self.encoder(pixels))
        mean, logvar = jnp.split(moments, 2, axis=-1)
        return mean, jnp.clip(logvar, -30.0, 20.0)

    def decode(self, latent):
        return self.decoder(self.post_quant_conv(latent))

    def __call__(self, pixels, rng=None):
        mean, logvar = self.encode(pixels)
        if rng is not None:
            latent = mean + jnp.exp(0.5 * logvar) * \
                jax.random.normal(rng, mean.shape)
        else:
            latent = mean
        return self.decode(latent), mean, logvar

    def partition_rules(self):
        return SD_PARTITION_RULES
