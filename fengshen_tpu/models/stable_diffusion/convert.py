"""Stable Diffusion checkpoint conversion.

Two surfaces:

1. `diffusers_to_original(...)` — behavioural port of the reference's
   format converter (reference:
   fengshen/utils/convert_diffusers_to_original_stable_diffusion.py:17-235):
   remap a HF-diffusers pipeline state dict (unet/vae/text_encoder) into the
   original CompVis single-checkpoint layout. Pure key arithmetic — works on
   any Mapping of arrays, no torch required.

2. `text_encoder_to_params(...)` — import the Taiyi-SD Chinese text encoder
   (a BertModel) into the flax TaiyiStableDiffusion text tower. The UNet /
   VAE towers of this family are TPU-native re-designs, not diffusers
   clones, so their released weights go through `diffusers_to_original` for
   interchange rather than direct tower import.
"""

from __future__ import annotations

from typing import Any, Mapping

# -- (stable-diffusion, diffusers) fixed renames (reference :17-29) ---------
_UNET_TOP = [
    ("time_embed.0.", "time_embedding.linear_1."),
    ("time_embed.2.", "time_embedding.linear_2."),
    ("input_blocks.0.0.", "conv_in."),
    ("out.0.", "conv_norm_out."),
    ("out.2.", "conv_out."),
]

_UNET_RESNET = [
    ("in_layers.0.", "norm1."),
    ("in_layers.2.", "conv1."),
    ("out_layers.0.", "norm2."),
    ("out_layers.3.", "conv2."),
    ("emb_layers.1.", "time_emb_proj."),
    ("skip_connection.", "conv_shortcut."),
]


def _unet_layer_map() -> list[tuple[str, str]]:
    """Block-index arithmetic between the two layouts (reference :41-90)."""
    pairs = []
    for i in range(4):
        for j in range(2):
            pairs.append((f"input_blocks.{3 * i + j + 1}.0.",
                          f"down_blocks.{i}.resnets.{j}."))
            if i < 3:
                pairs.append((f"input_blocks.{3 * i + j + 1}.1.",
                              f"down_blocks.{i}.attentions.{j}."))
        for j in range(3):
            pairs.append((f"output_blocks.{3 * i + j}.0.",
                          f"up_blocks.{i}.resnets.{j}."))
            if i > 0:
                pairs.append((f"output_blocks.{3 * i + j}.1.",
                              f"up_blocks.{i}.attentions.{j}."))
        if i < 3:
            pairs.append((f"input_blocks.{3 * (i + 1)}.0.op.",
                          f"down_blocks.{i}.downsamplers.0.conv."))
            pairs.append((f"output_blocks.{3 * i + 2}."
                          f"{1 if i == 0 else 2}.",
                          f"up_blocks.{i}.upsamplers.0."))
    pairs.append(("middle_block.1.", "mid_block.attentions.0."))
    for j in range(2):
        pairs.append((f"middle_block.{2 * j}.", f"mid_block.resnets.{j}."))
    return pairs


def convert_unet_state_dict(unet_state: Mapping[str, Any]) -> dict:
    """diffusers UNet keys → original SD keys (reference :93-110)."""
    mapping = {k: k for k in unet_state}
    for sd_name, hf_name in _UNET_TOP:
        for k in list(mapping):
            if k.startswith(hf_name):
                mapping[k] = sd_name + k[len(hf_name):]
    for k, v in mapping.items():
        if "resnets" in k:
            for sd_part, hf_part in _UNET_RESNET:
                v = v.replace(hf_part, sd_part)
            mapping[k] = v
    layer_map = _unet_layer_map()
    for k, v in mapping.items():
        for sd_part, hf_part in layer_map:
            v = v.replace(hf_part, sd_part)
        mapping[k] = v
    return {v: unet_state[k] for k, v in mapping.items()}


def _vae_map() -> list[tuple[str, str]]:
    pairs = [
        ("nin_shortcut", "conv_shortcut"),
        ("norm_out", "conv_norm_out"),
        ("mid.attn_1.", "mid_block.attentions.0."),
    ]
    for i in range(4):
        for j in range(2):
            pairs.append((f"encoder.down.{i}.block.{j}.",
                          f"encoder.down_blocks.{i}.resnets.{j}."))
        if i < 3:
            pairs.append((f"down.{i}.downsample.",
                          f"down_blocks.{i}.downsamplers.0."))
            pairs.append((f"up.{3 - i}.upsample.",
                          f"up_blocks.{i}.upsamplers.0."))
        for j in range(3):
            pairs.append((f"decoder.up.{3 - i}.block.{j}.",
                          f"decoder.up_blocks.{i}.resnets.{j}."))
    for i in range(2):
        pairs.append((f"mid.block_{i + 1}.", f"mid_block.resnets.{i}."))
    return pairs


_VAE_ATTN = [
    ("norm.", "group_norm."),
    ("q.", "query."),
    ("k.", "key."),
    ("v.", "value."),
    ("proj_out.", "proj_attn."),
]


def convert_vae_state_dict(vae_state: Mapping[str, Any]) -> dict:
    """diffusers VAE keys → original SD keys, reshaping the mid-attention
    linear weights to 1x1 convs (reference :167-186)."""
    import numpy as np
    mapping = {k: k for k in vae_state}
    vae_map = _vae_map()
    for k, v in mapping.items():
        for sd_part, hf_part in vae_map:
            v = v.replace(hf_part, sd_part)
        mapping[k] = v
    for k, v in mapping.items():
        if "attentions" in k:
            for sd_part, hf_part in _VAE_ATTN:
                v = v.replace(hf_part, sd_part)
            mapping[k] = v
    out = {v: vae_state[k] for k, v in mapping.items()}
    patterns = tuple(f"mid.attn_1.{n}.weight" for n in
                     ("q", "k", "v", "proj_out"))
    for key, w in list(out.items()):
        if any(p in key for p in patterns):
            arr = w.detach().cpu().numpy() if hasattr(w, "detach") else \
                np.asarray(w)
            out[key] = np.array(arr.reshape(*arr.shape, 1, 1), copy=True)
    return out


def diffusers_to_original(unet_state: Mapping[str, Any],
                          vae_state: Mapping[str, Any],
                          text_enc_state: Mapping[str, Any]) -> dict:
    """Assemble the single original-format checkpoint dict
    (reference :212-233; text encoder is a prefix-only no-op)."""
    out = {}
    out.update({"model.diffusion_model." + k: v for k, v in
                convert_unet_state_dict(unet_state).items()})
    out.update({"first_stage_model." + k: v for k, v in
                convert_vae_state_dict(vae_state).items()})
    out.update({"cond_stage_model.transformer." + k: v
                for k, v in text_enc_state.items()})
    return out


def text_encoder_to_params(state_dict: Mapping[str, Any],
                           text_config) -> dict:
    """Taiyi-SD Chinese text encoder (HF BertModel state dict) → the flax
    TaiyiStableDiffusion `text_encoder` params subtree."""
    from fengshen_tpu.models.bert.convert import model_to_params
    return model_to_params(state_dict, text_config)


def main(argv=None):
    """CLI parity with the reference script (reference :199-235)."""
    import argparse
    import os.path as osp

    import torch

    parser = argparse.ArgumentParser()
    parser.add_argument("--model_path", type=str, required=True)
    parser.add_argument("--checkpoint_path", type=str, required=True)
    parser.add_argument("--half", action="store_true")
    args = parser.parse_args(argv)

    load = lambda *p: torch.load(osp.join(*p), map_location="cpu")  # noqa
    state = diffusers_to_original(
        load(args.model_path, "unet", "diffusion_pytorch_model.bin"),
        load(args.model_path, "vae", "diffusion_pytorch_model.bin"),
        load(args.model_path, "text_encoder", "pytorch_model.bin"))
    if args.half:
        state = {k: v.half() for k, v in state.items()}
    torch.save({"state_dict": state}, args.checkpoint_path)


if __name__ == "__main__":
    main()
