"""Stable Diffusion checkpoint conversion.

Two surfaces:

1. `diffusers_to_original(...)` — behavioural port of the reference's
   format converter (reference:
   fengshen/utils/convert_diffusers_to_original_stable_diffusion.py:17-235):
   remap a HF-diffusers pipeline state dict (unet/vae/text_encoder) into the
   original CompVis single-checkpoint layout. Pure key arithmetic — works on
   any Mapping of arrays, no torch required.

2. `unet_to_params` / `vae_to_params` / `load_diffusers_pipeline` —
   DIRECT tower import of released diffusers weights into the faithful
   SD-1.x flax towers (`unet_sd.SDUNet2DConditionModel`,
   `vae_sd.SDAutoencoderKL`), whose parameter trees mirror the diffusers
   state-dict keys; `unet_params_to_diffusers`/`vae_params_to_diffusers`
   export back (derived exact inverses). Old (<0.17) VAE attention
   naming (query/key/value/proj_attn) is normalized on import.

3. `text_encoder_to_params(...)` — import the Taiyi-SD Chinese text encoder
   (a BertModel) into the flax TaiyiStableDiffusion text tower.
"""

from __future__ import annotations

from typing import Any, Mapping

# -- (stable-diffusion, diffusers) fixed renames (reference :17-29) ---------
_UNET_TOP = [
    ("time_embed.0.", "time_embedding.linear_1."),
    ("time_embed.2.", "time_embedding.linear_2."),
    ("input_blocks.0.0.", "conv_in."),
    ("out.0.", "conv_norm_out."),
    ("out.2.", "conv_out."),
]

_UNET_RESNET = [
    ("in_layers.0.", "norm1."),
    ("in_layers.2.", "conv1."),
    ("out_layers.0.", "norm2."),
    ("out_layers.3.", "conv2."),
    ("emb_layers.1.", "time_emb_proj."),
    ("skip_connection.", "conv_shortcut."),
]


def _unet_layer_map() -> list[tuple[str, str]]:
    """Block-index arithmetic between the two layouts (reference :41-90)."""
    pairs = []
    for i in range(4):
        for j in range(2):
            pairs.append((f"input_blocks.{3 * i + j + 1}.0.",
                          f"down_blocks.{i}.resnets.{j}."))
            if i < 3:
                pairs.append((f"input_blocks.{3 * i + j + 1}.1.",
                              f"down_blocks.{i}.attentions.{j}."))
        for j in range(3):
            pairs.append((f"output_blocks.{3 * i + j}.0.",
                          f"up_blocks.{i}.resnets.{j}."))
            if i > 0:
                pairs.append((f"output_blocks.{3 * i + j}.1.",
                              f"up_blocks.{i}.attentions.{j}."))
        if i < 3:
            pairs.append((f"input_blocks.{3 * (i + 1)}.0.op.",
                          f"down_blocks.{i}.downsamplers.0.conv."))
            pairs.append((f"output_blocks.{3 * i + 2}."
                          f"{1 if i == 0 else 2}.",
                          f"up_blocks.{i}.upsamplers.0."))
    pairs.append(("middle_block.1.", "mid_block.attentions.0."))
    for j in range(2):
        pairs.append((f"middle_block.{2 * j}.", f"mid_block.resnets.{j}."))
    return pairs


def convert_unet_state_dict(unet_state: Mapping[str, Any]) -> dict:
    """diffusers UNet keys → original SD keys (reference :93-110)."""
    mapping = {k: k for k in unet_state}
    for sd_name, hf_name in _UNET_TOP:
        for k in list(mapping):
            if k.startswith(hf_name):
                mapping[k] = sd_name + k[len(hf_name):]
    for k, v in mapping.items():
        if "resnets" in k:
            for sd_part, hf_part in _UNET_RESNET:
                v = v.replace(hf_part, sd_part)
            mapping[k] = v
    layer_map = _unet_layer_map()
    for k, v in mapping.items():
        for sd_part, hf_part in layer_map:
            v = v.replace(hf_part, sd_part)
        mapping[k] = v
    return {v: unet_state[k] for k, v in mapping.items()}


def _vae_map() -> list[tuple[str, str]]:
    pairs = [
        ("nin_shortcut", "conv_shortcut"),
        ("norm_out", "conv_norm_out"),
        ("mid.attn_1.", "mid_block.attentions.0."),
    ]
    for i in range(4):
        for j in range(2):
            pairs.append((f"encoder.down.{i}.block.{j}.",
                          f"encoder.down_blocks.{i}.resnets.{j}."))
        if i < 3:
            pairs.append((f"down.{i}.downsample.",
                          f"down_blocks.{i}.downsamplers.0."))
            pairs.append((f"up.{3 - i}.upsample.",
                          f"up_blocks.{i}.upsamplers.0."))
        for j in range(3):
            pairs.append((f"decoder.up.{3 - i}.block.{j}.",
                          f"decoder.up_blocks.{i}.resnets.{j}."))
    for i in range(2):
        pairs.append((f"mid.block_{i + 1}.", f"mid_block.resnets.{i}."))
    return pairs


_VAE_ATTN = [
    ("norm.", "group_norm."),
    ("q.", "query."),
    ("k.", "key."),
    ("v.", "value."),
    ("proj_out.", "proj_attn."),
]


def convert_vae_state_dict(vae_state: Mapping[str, Any]) -> dict:
    """diffusers VAE keys → original SD keys, reshaping the mid-attention
    linear weights to 1x1 convs (reference :167-186)."""
    import numpy as np
    mapping = {k: k for k in vae_state}
    vae_map = _vae_map()
    for k, v in mapping.items():
        for sd_part, hf_part in vae_map:
            v = v.replace(hf_part, sd_part)
        mapping[k] = v
    for k, v in mapping.items():
        if "attentions" in k:
            for sd_part, hf_part in _VAE_ATTN:
                v = v.replace(hf_part, sd_part)
            mapping[k] = v
    out = {v: vae_state[k] for k, v in mapping.items()}
    patterns = tuple(f"mid.attn_1.{n}.weight" for n in
                     ("q", "k", "v", "proj_out"))
    for key, w in list(out.items()):
        if any(p in key for p in patterns):
            arr = w.detach().cpu().numpy() if hasattr(w, "detach") else \
                np.asarray(w)
            out[key] = np.array(arr.reshape(*arr.shape, 1, 1), copy=True)
    return out


def diffusers_to_original(unet_state: Mapping[str, Any],
                          vae_state: Mapping[str, Any],
                          text_enc_state: Mapping[str, Any]) -> dict:
    """Assemble the single original-format checkpoint dict
    (reference :212-233; text encoder is a prefix-only no-op)."""
    out = {}
    out.update({"model.diffusion_model." + k: v for k, v in
                convert_unet_state_dict(unet_state).items()})
    out.update({"first_stage_model." + k: v for k, v in
                convert_vae_state_dict(vae_state).items()})
    out.update({"cond_stage_model.transformer." + k: v
                for k, v in text_enc_state.items()})
    return out


# -- direct tower import: diffusers state dict → flax params ---------------

#: old-diffusers (<0.17) VAE attention names → current names (the
#: released 2022-era Taiyi-SD weights use the old ones)
_OLD_ATTN_RENAMES = {"query": "to_q", "key": "to_k", "value": "to_v",
                     "proj_attn": "to_out_0"}


def diffusers_tower_to_params(state_dict: Mapping[str, Any]) -> dict:
    """Generic diffusers→flax weight mapping for the SD towers.

    The flax modules in `unet_sd.py` / `vae_sd.py` name their submodules
    exactly like the diffusers state-dict keys with numeric segments
    merged (``down_blocks.0.resnets.1`` → ``down_blocks_0/resnets_1``),
    so the import is a mechanical key mangle plus the standard layout
    transposes: torch Conv [O,I,kh,kw] → flax [kh,kw,I,O], Linear
    [O,I] → [I,O], norm weight → scale.
    """
    import numpy as np

    from fengshen_tpu.utils.convert_common import tensor as _t

    params: dict = {}
    for key in state_dict:
        arr = _t(state_dict, key)
        parts = key.split(".")
        leaf_name, parts = parts[-1], parts[:-1]
        path: list[str] = []
        for p in parts:
            if p.isdigit() and path:
                path[-1] = f"{path[-1]}_{p}"
            else:
                path.append(_OLD_ATTN_RENAMES.get(p, p))
        if arr.ndim == 4:
            leaf = ("kernel", np.transpose(arr, (2, 3, 1, 0)))
        elif arr.ndim == 2:
            leaf = ("kernel", arr.T)
        elif leaf_name == "weight":
            leaf = ("scale", arr)  # GroupNorm/LayerNorm
        else:
            leaf = ("bias", arr)
        node = params
        for p in path:
            node = node.setdefault(p, {})
        node[leaf[0]] = leaf[1]
    return params


def unet_to_params(state_dict: Mapping[str, Any], config=None) -> dict:
    """diffusers UNet2DConditionModel state dict → SDUNet2DConditionModel
    params (reference: the released Taiyi-SD pipeline's `unet/` weights,
    finetune_taiyi_stable_diffusion/finetune.py:81-89)."""
    return diffusers_tower_to_params(state_dict)


def vae_to_params(state_dict: Mapping[str, Any], config=None) -> dict:
    """diffusers AutoencoderKL state dict → SDAutoencoderKL params."""
    return diffusers_tower_to_params(state_dict)


def unet_params_to_diffusers(params: dict, template_state, config=None):
    """SDUNet params → diffusers state dict (exact inverse, derived —
    see utils/convert_common.invert_import)."""
    from fengshen_tpu.utils.convert_common import invert_import
    return invert_import(unet_to_params, template_state, config, params)


def vae_params_to_diffusers(params: dict, template_state, config=None):
    from fengshen_tpu.utils.convert_common import invert_import
    return invert_import(vae_to_params, template_state, config, params)


def sd_unet_config_from_diffusers(cfg: Mapping[str, Any]):
    """diffusers unet/config.json → SDUNetConfig."""
    from fengshen_tpu.models.stable_diffusion.unet_sd import SDUNetConfig
    keep = {f.name for f in __import__("dataclasses").fields(SDUNetConfig)}
    return SDUNetConfig(**{k: (tuple(v) if isinstance(v, list) else v)
                           for k, v in cfg.items()
                           if k in keep and k != "dtype"})


def sd_vae_config_from_diffusers(cfg: Mapping[str, Any]):
    """diffusers vae/config.json → SDVAEConfig."""
    from fengshen_tpu.models.stable_diffusion.vae_sd import SDVAEConfig
    keep = {f.name for f in __import__("dataclasses").fields(SDVAEConfig)}
    return SDVAEConfig(**{k: (tuple(v) if isinstance(v, list) else v)
                          for k, v in cfg.items()
                          if k in keep and k != "dtype"})


def load_diffusers_pipeline(model_path: str):
    """A released diffusers SD pipeline dir → (unet_config, unet_params,
    vae_config, vae_params). Weights: `unet/diffusion_pytorch_model.bin`
    (or .safetensors) + `vae/...` (reference: finetune.py:81-89
    StableDiffusionPipeline.from_pretrained)."""
    import json
    import os

    from fengshen_tpu.utils.convert_common import load_weight_files

    def load_tower(sub):
        with open(os.path.join(model_path, sub, "config.json")) as f:
            cfg = json.load(f)
        return cfg, load_weight_files(os.path.join(model_path, sub),
                                      "diffusion_pytorch_model")

    unet_cfg, unet_state = load_tower("unet")
    vae_cfg, vae_state = load_tower("vae")
    return (sd_unet_config_from_diffusers(unet_cfg),
            unet_to_params(unet_state),
            sd_vae_config_from_diffusers(vae_cfg),
            vae_to_params(vae_state))


def resolve_towers(sd_pipeline_path=None, faithful: bool = False,
                   small_test: bool = False):
    """Shared tower selection for the SD drivers (finetune_taiyi_sd,
    disco demo): returns (unet_config, vae_config, pipeline_params) —
    `pipeline_params` is a {'unet':…, 'vae':…} import dict when a
    released diffusers dir was given, else None."""
    if sd_pipeline_path:
        unet_cfg, unet_params, vae_cfg, vae_params = \
            load_diffusers_pipeline(sd_pipeline_path)
        return unet_cfg, vae_cfg, {"unet": unet_params,
                                   "vae": vae_params}
    if faithful:
        from fengshen_tpu.models.stable_diffusion.unet_sd import (
            SDUNetConfig)
        from fengshen_tpu.models.stable_diffusion.vae_sd import (
            SDVAEConfig)
        if small_test:
            return (SDUNetConfig.small_test_config(),
                    SDVAEConfig.small_test_config(), None)
        return SDUNetConfig(), SDVAEConfig(), None
    from fengshen_tpu.models.stable_diffusion.autoencoder_kl import (
        VAEConfig)
    from fengshen_tpu.models.stable_diffusion.unet import UNetConfig
    if small_test:
        return (UNetConfig.small_test_config(),
                VAEConfig.small_test_config(), None)
    return UNetConfig(), VAEConfig(), None


def text_encoder_to_params(state_dict: Mapping[str, Any],
                           text_config) -> dict:
    """Taiyi-SD Chinese text encoder (HF BertModel state dict) → the flax
    TaiyiStableDiffusion `text_encoder` params subtree."""
    from fengshen_tpu.models.bert.convert import model_to_params
    return model_to_params(state_dict, text_config)


def main(argv=None):
    """CLI parity with the reference script (reference :199-235)."""
    import argparse
    import os.path as osp

    import torch

    parser = argparse.ArgumentParser()
    parser.add_argument("--model_path", type=str, required=True)
    parser.add_argument("--checkpoint_path", type=str, required=True)
    parser.add_argument("--half", action="store_true")
    args = parser.parse_args(argv)

    load = lambda *p: torch.load(osp.join(*p), map_location="cpu")  # noqa
    state = diffusers_to_original(
        load(args.model_path, "unet", "diffusion_pytorch_model.bin"),
        load(args.model_path, "vae", "diffusion_pytorch_model.bin"),
        load(args.model_path, "text_encoder", "pytorch_model.bin"))
    if args.half:
        state = {k: v.half() for k, v in state.items()}
    torch.save({"state_dict": state}, args.checkpoint_path)


if __name__ == "__main__":
    main()
