"""Taiyi Stable Diffusion family (reference:
fengshen/examples/finetune_taiyi_stable_diffusion/finetune.py — latent
diffusion finetune over diffusers' tokenizer/text_encoder/vae/unet/scheduler
with the ε / v-prediction switch, SURVEY.md §3.4)."""

from fengshen_tpu.models.stable_diffusion.scheduler import DDPMScheduler
from fengshen_tpu.models.stable_diffusion.autoencoder_kl import AutoencoderKL
from fengshen_tpu.models.stable_diffusion.unet import UNet2DConditionModel
from fengshen_tpu.models.stable_diffusion.unet_sd import (
    SDUNetConfig, SDUNet2DConditionModel)
from fengshen_tpu.models.stable_diffusion.vae_sd import (SDVAEConfig,
                                                         SDAutoencoderKL)
from fengshen_tpu.models.stable_diffusion.modeling_taiyi_sd import (
    TaiyiStableDiffusion, diffusion_loss)

__all__ = ["DDPMScheduler", "AutoencoderKL", "UNet2DConditionModel",
           "SDUNetConfig", "SDUNet2DConditionModel", "SDVAEConfig",
           "SDAutoencoderKL", "TaiyiStableDiffusion", "diffusion_loss"]
