"""DDPM noise scheduler (diffusers-parity math).

Reference workload: fengshen/examples/finetune_taiyi_stable_diffusion/
finetune.py:112-144 — `scheduler.add_noise` during training and the
ε / v-prediction target switch (:130-136).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class DDPMScheduler:
    num_train_timesteps: int = 1000
    beta_start: float = 0.00085
    beta_end: float = 0.012
    beta_schedule: str = "scaled_linear"   # diffusers SD default
    prediction_type: str = "epsilon"       # "epsilon" | "v_prediction"

    def __post_init__(self):
        if self.beta_schedule == "scaled_linear":
            betas = np.linspace(self.beta_start ** 0.5,
                                self.beta_end ** 0.5,
                                self.num_train_timesteps) ** 2
        elif self.beta_schedule == "linear":
            betas = np.linspace(self.beta_start, self.beta_end,
                                self.num_train_timesteps)
        else:
            raise ValueError(f"unknown beta schedule {self.beta_schedule!r}")
        alphas = 1.0 - betas
        self.alphas_cumprod = jnp.asarray(np.cumprod(alphas),
                                          dtype=jnp.float32)

    def _gather(self, t, shape):
        a = self.alphas_cumprod[t]
        return a.reshape(a.shape + (1,) * (len(shape) - a.ndim))

    def add_noise(self, sample, noise, timesteps):
        a = self._gather(timesteps, sample.shape)
        return jnp.sqrt(a) * sample + jnp.sqrt(1 - a) * noise

    def get_velocity(self, sample, noise, timesteps):
        """v = sqrt(ᾱ)·ε − sqrt(1−ᾱ)·x (the v-prediction target)."""
        a = self._gather(timesteps, sample.shape)
        return jnp.sqrt(a) * noise - jnp.sqrt(1 - a) * sample

    def step(self, model_output, timestep, sample, prev_timestep=None):
        """One ancestral DDPM denoise step (inference).

        `prev_timestep` is the NEXT timestep of the (possibly subsampled)
        inference schedule — with num_inference_steps < T the stride is
        T//num_steps, not 1 (diffusers' prev_t convention); defaults to
        timestep-1 for a full-schedule walk."""
        if prev_timestep is None:
            prev_timestep = timestep - 1
        a_t = self.alphas_cumprod[timestep]
        a_prev = jnp.where(prev_timestep >= 0,
                           self.alphas_cumprod[
                               jnp.maximum(prev_timestep, 0)],
                           1.0)
        if self.prediction_type == "v_prediction":
            eps = jnp.sqrt(a_t) * model_output + \
                jnp.sqrt(1 - a_t) * sample
        else:
            eps = model_output
        x0 = (sample - jnp.sqrt(1 - a_t) * eps) / jnp.sqrt(a_t)
        dir_xt = jnp.sqrt(1 - a_prev) * eps
        return jnp.sqrt(a_prev) * x0 + dir_xt
