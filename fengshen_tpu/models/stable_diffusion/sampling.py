"""Text-to-image sampling for Taiyi Stable Diffusion.

The inference counterpart of the training pipeline (reference:
fengshen/examples/stable_diffusion_chinese/ — diffusers
StableDiffusionPipeline driven by the Chinese text encoder): DDIM-style
ancestral loop over the DDPM scheduler with classifier-free guidance.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from fengshen_tpu.models.stable_diffusion.autoencoder_kl import (
    SCALING_FACTOR)
from fengshen_tpu.models.stable_diffusion.scheduler import DDPMScheduler


def text_to_image(model, params, input_ids, uncond_ids=None,
                  image_size: int = 512, num_steps: int = 50,
                  guidance_scale: float = 7.5,
                  rng: Optional[jax.Array] = None,
                  scheduler: Optional[DDPMScheduler] = None,
                  latent_guidance_fn=None):
    """input_ids [B, S] (and optional unconditional ids for guidance) →
    images [B, H, W, 3] in [0, 1].

    `latent_guidance_fn(latents) -> latents` runs after every denoise step
    (the hook CLIP-guided/disco sampling plugs into)."""
    scheduler = scheduler or DDPMScheduler()
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    batch = input_ids.shape[0]
    latent_shape = (batch,) + model.vae_config.latent_shape(image_size)

    text = model.apply({"params": params}, input_ids,
                       method=type(model).encode_text)
    uncond = None
    if uncond_ids is not None and guidance_scale > 1.0:
        uncond = model.apply({"params": params}, uncond_ids,
                             method=type(model).encode_text)

    latents = jax.random.normal(rng, latent_shape)
    T = scheduler.num_train_timesteps
    timesteps = jnp.linspace(T - 1, 0, num_steps).astype(jnp.int32)
    # each step denoises to the NEXT timestep of the subsampled schedule
    prev_timesteps = jnp.concatenate(
        [timesteps[1:], jnp.asarray([-1], jnp.int32)])

    def body(latents, ts):
        t, t_prev = ts
        tb = jnp.full((batch,), t, jnp.int32)
        eps = model.apply({"params": params}, latents, tb, text,
                          method=type(model).denoise)
        if uncond is not None:
            eps_u = model.apply({"params": params}, latents, tb, uncond,
                                method=type(model).denoise)
            eps = eps_u + guidance_scale * (eps - eps_u)
        latents = scheduler.step(eps, t, latents, prev_timestep=t_prev)
        if latent_guidance_fn is not None:
            latents = latent_guidance_fn(latents)
        return latents, None

    latents, _ = jax.lax.scan(body, latents,
                              (timesteps, prev_timesteps))
    pixels = model.apply({"params": params}, latents / SCALING_FACTOR,
                         method=lambda m, z: m.vae.decode(z))
    return jnp.clip(pixels / 2.0 + 0.5, 0.0, 1.0)


def init_sampling_params(model, rng, image_size: int, seq_len: int = 8):
    """Init params covering BOTH the training path and the decoder (the
    training __call__ only encodes, so a plain init lacks vae.decode
    params needed for sampling)."""

    def full(m, ids, pixels, t, noise, z):
        pred, _ = m(ids, pixels, t, noise)
        return pred, m.vae.decode(z)

    ids = jnp.zeros((1, seq_len), jnp.int32)
    pixels = jnp.zeros((1, image_size, image_size, 3), jnp.float32)
    t = jnp.zeros((1,), jnp.int32)
    z = jnp.zeros((1,) + model.vae_config.latent_shape(image_size),
                  jnp.float32)
    return model.init(rng, ids, pixels, t, z, z, method=full)["params"]
