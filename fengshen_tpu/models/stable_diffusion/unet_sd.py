"""Diffusers-faithful UNet2DConditionModel (SD-1.x architecture).

The denoiser the released Taiyi-Stable-Diffusion-1B checkpoint ships
(reference workload: fengshen/examples/finetune_taiyi_stable_diffusion/
finetune.py:81-89 loads the diffusers pipeline; its UNet is the SD-1.x
`UNet2DConditionModel`). This flax module reproduces that architecture
exactly — 32-group GroupNorm, per-block transformer depth, GEGLU feed
forward, conv proj_in/proj_out, SD block layout — with a parameter tree
that mirrors the diffusers state-dict keys (``down_blocks.0.resnets.1``
→ path ``down_blocks_0/resnets_1``), so the importer in `convert.py` is
a mechanical key mangle and the released weights load directly. The
compact `unet.UNetConfig` tower remains as the small test config for
trainer plumbing.

Layout is NHWC (TPU-native; torch NCHW weights are transposed on
import). All matmuls/convs ride the MXU; attention over the flattened
spatial dim is plain dot-product attention, which XLA fuses — spatial
lengths (≤4096 at 512px) are far below the Pallas flash cutover.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn


from fengshen_tpu.sharding import (to_partition_rules,
                                   with_logical_constraint)

#: fsdp/tensor sharding for the SD towers (the reference trains SD under
#: DeepSpeed ZeRO; here the fsdp axis shards the big conv out-channels
#: and the transformer/ff matmuls ride the tensor axis). `_spec_fits`
#: drops any axis a tiny channel count cannot divide, so small test
#: configs degrade to replicated instead of failing. Dimension roles
#: are declared as logical axes (docs/sharding.md); the active rules
#: table resolves them to mesh axes.
SD_PARAM_LOGICAL_AXES: list[tuple[str, tuple]] = [
    (r"(to_q|to_k|to_v)/kernel", (None, "heads")),
    (r"to_out_0/kernel", ("heads", None)),
    (r"ff/net_0/proj/kernel", (None, "mlp")),
    (r"ff/net_2/kernel", ("mlp", None)),
    (r"time_emb_proj/kernel", (None, "conv_out")),
    (r"(linear_1|linear_2)/kernel", (None, "conv_out")),
    # `(^|/)conv` anchors the down/upsampler convs without catching
    # quant_conv/post_quant_conv (4- and 8-channel 1x1s that must stay
    # replicated)
    (r"(conv1|conv2|conv_shortcut|(^|/)conv)/kernel",
     ("conv_kernel", "conv_kernel", "conv_in", "conv_out")),
    (r"(proj_in|proj_out)/kernel",
     ("conv_kernel", "conv_kernel", "conv_in", "conv_out")),
    (".*", (None,)),
]

SD_PARTITION_RULES = to_partition_rules(SD_PARAM_LOGICAL_AXES)


@dataclasses.dataclass
class SDUNetConfig:
    """Field names follow diffusers' UNet2DConditionModel config."""

    sample_size: int = 64
    in_channels: int = 4
    out_channels: int = 4
    down_block_types: Sequence[str] = (
        "CrossAttnDownBlock2D", "CrossAttnDownBlock2D",
        "CrossAttnDownBlock2D", "DownBlock2D")
    up_block_types: Sequence[str] = (
        "UpBlock2D", "CrossAttnUpBlock2D", "CrossAttnUpBlock2D",
        "CrossAttnUpBlock2D")
    block_out_channels: Sequence[int] = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    cross_attention_dim: int = 768
    attention_head_dim: int = 8  # = number of heads (SD-1.x quirk)
    norm_num_groups: int = 32
    norm_eps: float = 1e-5
    flip_sin_to_cos: bool = True
    freq_shift: int = 0
    dtype: str = "float32"

    @classmethod
    def small_test_config(cls, **overrides: Any) -> "SDUNetConfig":
        base = dict(sample_size=8, block_out_channels=(32, 64),
                    down_block_types=("CrossAttnDownBlock2D",
                                      "DownBlock2D"),
                    up_block_types=("UpBlock2D", "CrossAttnUpBlock2D"),
                    layers_per_block=1, cross_attention_dim=32,
                    attention_head_dim=2, norm_num_groups=8)
        base.update(overrides)
        return cls(**base)


def sd_timestep_embedding(timesteps: jax.Array, dim: int,
                          flip_sin_to_cos: bool = True,
                          freq_shift: float = 0.0) -> jax.Array:
    """diffusers `Timesteps` module (get_timestep_embedding)."""
    half = dim // 2
    exponent = -math.log(10000.0) * jnp.arange(half, dtype=jnp.float32)
    exponent = exponent / (half - freq_shift)
    emb = timesteps.astype(jnp.float32)[:, None] * \
        jnp.exp(exponent)[None, :]
    emb = jnp.concatenate([jnp.sin(emb), jnp.cos(emb)], axis=-1)
    if flip_sin_to_cos:
        emb = jnp.concatenate([emb[:, half:], emb[:, :half]], axis=-1)
    # the sin|cos concat must stay replicated on its feature dim: GSPMD
    # back-propagates downstream weight shards onto it, and a
    # concatenate consumed through a sharded matmul contraction
    # mispartitions on the CPU XLA build (docs/sharding.md "Root
    # cause") — this constraint is the fix for NOTES.md item 3
    return with_logical_constraint(emb, ("batch", "relpos"))


class TimestepEmbedding(nn.Module):
    dim: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, temb):
        temb = nn.Dense(self.dim, dtype=self.dtype, name="linear_1")(temb)
        return nn.Dense(self.dim, dtype=self.dtype, name="linear_2")(
            jax.nn.silu(temb))


class ResnetBlock2D(nn.Module):
    """diffusers ResnetBlock2D: norm→silu→conv ×2 with time projection
    between, learned 1x1 shortcut on channel change."""

    out_channels: int
    groups: int = 32
    eps: float = 1e-5
    use_temb: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, temb=None):
        h = nn.GroupNorm(num_groups=self.groups, epsilon=self.eps,
                         name="norm1")(x)
        h = nn.Conv(self.out_channels, (3, 3), padding=((1, 1), (1, 1)),
                    dtype=self.dtype, name="conv1")(jax.nn.silu(h))
        if self.use_temb:
            h = h + nn.Dense(self.out_channels, dtype=self.dtype,
                             name="time_emb_proj")(
                jax.nn.silu(temb))[:, None, None, :]
        h = nn.GroupNorm(num_groups=self.groups, epsilon=self.eps,
                         name="norm2")(h)
        h = nn.Conv(self.out_channels, (3, 3), padding=((1, 1), (1, 1)),
                    dtype=self.dtype, name="conv2")(jax.nn.silu(h))
        if x.shape[-1] != self.out_channels:
            x = nn.Conv(self.out_channels, (1, 1), dtype=self.dtype,
                        name="conv_shortcut")(x)
        return x + h


class Attention(nn.Module):
    """diffusers Attention: to_q/to_k/to_v (no bias) + to_out.0."""

    channels: int
    num_heads: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, context=None):
        context = x if context is None else context
        head_dim = self.channels // self.num_heads
        b = x.shape[0]
        q = nn.Dense(self.channels, use_bias=False, dtype=self.dtype,
                     name="to_q")(x)
        k = nn.Dense(self.channels, use_bias=False, dtype=self.dtype,
                     name="to_k")(context)
        v = nn.Dense(self.channels, use_bias=False, dtype=self.dtype,
                     name="to_v")(context)
        q = q.reshape(b, -1, self.num_heads, head_dim)
        k = k.reshape(b, -1, self.num_heads, head_dim)
        v = v.reshape(b, -1, self.num_heads, head_dim)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                            preferred_element_type=jnp.float32)
        scores = scores / jnp.sqrt(jnp.asarray(head_dim, jnp.float32))
        probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        return nn.Dense(self.channels, dtype=self.dtype,
                        name="to_out_0")(
            out.reshape(b, -1, self.channels))


class FeedForward(nn.Module):
    """diffusers FeedForward with GEGLU: proj to 2×inner, a·gelu(gate).

    The GEGLU projection lives at ``ff.net.0.proj`` in diffusers (net is
    a ModuleList [GEGLU, Dropout, Linear]), hence the nested name."""

    dim: int
    dtype: Any = jnp.float32

    class _GEGLU(nn.Module):
        inner: int
        dtype: Any = jnp.float32

        @nn.compact
        def __call__(self, x):
            proj = nn.Dense(2 * self.inner, dtype=self.dtype,
                            name="proj")(x)
            a, gate = jnp.split(proj, 2, axis=-1)
            return a * jax.nn.gelu(gate, approximate=False)

    @nn.compact
    def __call__(self, x):
        h = self._GEGLU(4 * self.dim, self.dtype, name="net_0")(x)
        return nn.Dense(self.dim, dtype=self.dtype, name="net_2")(h)


class BasicTransformerBlock(nn.Module):
    channels: int
    num_heads: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, context):
        # torch LayerNorm eps is 1e-5 (flax defaults to 1e-6)
        x = x + Attention(self.channels, self.num_heads, self.dtype,
                          name="attn1")(
            nn.LayerNorm(epsilon=1e-5, name="norm1")(x))
        x = x + Attention(self.channels, self.num_heads, self.dtype,
                          name="attn2")(
            nn.LayerNorm(epsilon=1e-5, name="norm2")(x), context)
        return x + FeedForward(self.channels, self.dtype, name="ff")(
            nn.LayerNorm(epsilon=1e-5, name="norm3")(x))


class Transformer2DModel(nn.Module):
    """GroupNorm → 1x1-conv proj_in → transformer over HW → 1x1-conv
    proj_out, residual (SD-1.x: use_linear_projection=False)."""

    channels: int
    num_heads: int
    groups: int = 32
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, context):
        b, hh, ww, c = x.shape
        residual = x
        h = nn.GroupNorm(num_groups=self.groups, epsilon=1e-6,
                         name="norm")(x)
        h = nn.Conv(self.channels, (1, 1), dtype=self.dtype,
                    name="proj_in")(h)
        h = h.reshape(b, hh * ww, self.channels)
        h = BasicTransformerBlock(self.channels, self.num_heads,
                                  self.dtype,
                                  name="transformer_blocks_0")(h, context)
        h = h.reshape(b, hh, ww, self.channels)
        h = nn.Conv(self.channels, (1, 1), dtype=self.dtype,
                    name="proj_out")(h)
        return h + residual


class Downsample2D(nn.Module):
    channels: int
    # torch Conv2d(k3, s2, p1) for the UNet; the VAE pads (0,1) only
    pad: tuple = ((1, 1), (1, 1))
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        return nn.Conv(self.channels, (3, 3), strides=(2, 2),
                       padding=self.pad, dtype=self.dtype,
                       name="conv")(x)


class Upsample2D(nn.Module):
    channels: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, hh, ww, c = x.shape
        x = jax.image.resize(x, (b, hh * 2, ww * 2, c), "nearest")
        return nn.Conv(self.channels, (3, 3), padding=((1, 1), (1, 1)),
                       dtype=self.dtype, name="conv")(x)


class _DownBlock(nn.Module):
    cfg: SDUNetConfig
    channels: int
    cross_attn: bool
    is_last: bool

    @nn.compact
    def __call__(self, h, temb, context):
        cfg, dt = self.cfg, jnp.dtype(self.cfg.dtype)
        skips = []
        for j in range(cfg.layers_per_block):
            h = ResnetBlock2D(self.channels, cfg.norm_num_groups,
                              cfg.norm_eps, dtype=dt,
                              name=f"resnets_{j}")(h, temb)
            if self.cross_attn:
                h = Transformer2DModel(self.channels,
                                       cfg.attention_head_dim,
                                       cfg.norm_num_groups, dt,
                                       name=f"attentions_{j}")(h, context)
            skips.append(h)
        if not self.is_last:
            h = Downsample2D(self.channels, dtype=dt,
                             name="downsamplers_0")(h)
            skips.append(h)
        return h, skips


class _MidBlock(nn.Module):
    cfg: SDUNetConfig
    channels: int

    @nn.compact
    def __call__(self, h, temb, context):
        cfg, dt = self.cfg, jnp.dtype(self.cfg.dtype)
        h = ResnetBlock2D(self.channels, cfg.norm_num_groups,
                          cfg.norm_eps, dtype=dt,
                          name="resnets_0")(h, temb)
        h = Transformer2DModel(self.channels, cfg.attention_head_dim,
                               cfg.norm_num_groups, dt,
                               name="attentions_0")(h, context)
        return ResnetBlock2D(self.channels, cfg.norm_num_groups,
                             cfg.norm_eps, dtype=dt,
                             name="resnets_1")(h, temb)


class _UpBlock(nn.Module):
    cfg: SDUNetConfig
    channels: int
    cross_attn: bool
    is_last: bool

    @nn.compact
    def __call__(self, h, skips, temb, context):
        cfg, dt = self.cfg, jnp.dtype(self.cfg.dtype)
        for j in range(cfg.layers_per_block + 1):
            h = jnp.concatenate([h, skips.pop()], axis=-1)
            # the skip concat's channel dim is the very next conv's
            # contraction: keep it replicated (docs/sharding.md "Root
            # cause" — same concat-contraction hazard as the timestep
            # embedding; the conv weights stay sharded on conv_out)
            h = with_logical_constraint(
                h, ("batch", None, None, "conv_in"))
            h = ResnetBlock2D(self.channels, cfg.norm_num_groups,
                              cfg.norm_eps, dtype=dt,
                              name=f"resnets_{j}")(h, temb)
            if self.cross_attn:
                h = Transformer2DModel(self.channels,
                                       cfg.attention_head_dim,
                                       cfg.norm_num_groups, dt,
                                       name=f"attentions_{j}")(h, context)
        if not self.is_last:
            h = Upsample2D(self.channels, dtype=dt,
                           name="upsamplers_0")(h)
        return h


class SDUNet2DConditionModel(nn.Module):
    """The SD-1.x denoiser; forward contract identical to the compact
    tower: (latents NHWC, timesteps [B], text states [B,T,D]) → noise."""

    config: SDUNetConfig

    @nn.compact
    def __call__(self, latents, timesteps, encoder_hidden_states):
        cfg = self.config
        dt = jnp.dtype(cfg.dtype)
        context = encoder_hidden_states

        temb = sd_timestep_embedding(timesteps, cfg.block_out_channels[0],
                                     cfg.flip_sin_to_cos, cfg.freq_shift)
        temb = TimestepEmbedding(cfg.block_out_channels[0] * 4, dt,
                                 name="time_embedding")(temb)

        h = nn.Conv(cfg.block_out_channels[0], (3, 3),
                    padding=((1, 1), (1, 1)), dtype=dt,
                    name="conv_in")(latents)
        skips = [h]
        n = len(cfg.block_out_channels)
        for i, (btype, ch) in enumerate(zip(cfg.down_block_types,
                                            cfg.block_out_channels)):
            h, block_skips = _DownBlock(
                cfg, ch, btype == "CrossAttnDownBlock2D",
                is_last=(i == n - 1), name=f"down_blocks_{i}")(
                h, temb, context)
            skips.extend(block_skips)

        h = _MidBlock(cfg, cfg.block_out_channels[-1],
                      name="mid_block")(h, temb, context)

        rev_channels = list(reversed(cfg.block_out_channels))
        for i, (btype, ch) in enumerate(zip(cfg.up_block_types,
                                            rev_channels)):
            h = _UpBlock(cfg, ch, btype == "CrossAttnUpBlock2D",
                         is_last=(i == n - 1), name=f"up_blocks_{i}")(
                h, skips, temb, context)

        h = nn.GroupNorm(num_groups=cfg.norm_num_groups,
                         epsilon=cfg.norm_eps, name="conv_norm_out")(h)
        return nn.Conv(cfg.out_channels, (3, 3), padding=((1, 1), (1, 1)),
                       dtype=dt, name="conv_out")(jax.nn.silu(h))

    def partition_rules(self):
        # resolved at call time so a `use_rules` scope takes effect
        return to_partition_rules(SD_PARAM_LOGICAL_AXES)
