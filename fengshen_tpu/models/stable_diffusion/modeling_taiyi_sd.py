"""Taiyi Stable Diffusion: text encoder + VAE + UNet + scheduler.

Port of the reference training step (reference:
fengshen/examples/finetune_taiyi_stable_diffusion/finetune.py:112-144):
vae.encode → ×0.18215 → sample noise+timesteps → scheduler.add_noise →
text_encoder(input_ids) → unet(noisy, t, text) → MSE against ε or v
(:130-136), with frozen-tower options (:91-100).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from fengshen_tpu.models.bert import BertConfig, BertModel
from fengshen_tpu.models.stable_diffusion.autoencoder_kl import (
    SCALING_FACTOR, AutoencoderKL, VAEConfig)
from fengshen_tpu.models.stable_diffusion.scheduler import DDPMScheduler
from fengshen_tpu.models.stable_diffusion.unet import (UNetConfig,
                                                       UNet2DConditionModel)
from fengshen_tpu.models.stable_diffusion.unet_sd import (
    SDUNetConfig, SDUNet2DConditionModel)
from fengshen_tpu.models.stable_diffusion.vae_sd import (SDVAEConfig,
                                                         SDAutoencoderKL)


class TaiyiStableDiffusion(nn.Module):
    """The three-model latent-diffusion pipeline with a Chinese text
    tower. The UNet/VAE configs pick the tower: `SDUNetConfig` /
    `SDVAEConfig` build the diffusers-faithful SD-1.x architecture that
    loads the released Taiyi-SD weights (convert.load_diffusers_pipeline);
    the compact `UNetConfig` / `VAEConfig` towers remain for fast test
    plumbing."""

    text_config: BertConfig
    vae_config: Any
    unet_config: Any

    def setup(self):
        self.text_encoder = BertModel(self.text_config,
                                      add_pooling_layer=False,
                                      name="text_encoder")
        if isinstance(self.vae_config, SDVAEConfig):
            self.vae = SDAutoencoderKL(self.vae_config, name="vae")
        else:
            self.vae = AutoencoderKL(self.vae_config, name="vae")
        if isinstance(self.unet_config, SDUNetConfig):
            self.unet = SDUNet2DConditionModel(self.unet_config,
                                               name="unet")
        else:
            self.unet = UNet2DConditionModel(self.unet_config,
                                             name="unet")

    def encode_text(self, input_ids, attention_mask=None,
                    deterministic=True):
        hidden, _ = self.text_encoder(input_ids, attention_mask,
                                      deterministic=deterministic)
        return hidden

    def encode_image(self, pixels, rng=None):
        mean, logvar = self.vae.encode(pixels)
        if rng is not None:
            latent = mean + jnp.exp(0.5 * logvar) * \
                jax.random.normal(rng, mean.shape)
        else:
            latent = mean
        return latent * SCALING_FACTOR

    def denoise(self, noisy_latents, timesteps, text_states):
        return self.unet(noisy_latents, timesteps, text_states)

    def decode_image(self, latents):
        """Scaled latents → pixels (the inference tail the serving
        pipeline jits after its denoise loop)."""
        return self.vae.decode(latents / SCALING_FACTOR)

    def __call__(self, input_ids, pixels, timesteps, noise,
                 attention_mask=None, rng=None, deterministic=True):
        latents = self.encode_image(pixels, rng)
        scheduler = DDPMScheduler()
        noisy = scheduler.add_noise(latents, noise, timesteps)
        text = self.encode_text(input_ids, attention_mask, deterministic)
        pred = self.denoise(noisy, timesteps, text)
        return pred, latents

    def partition_rules(self):
        """Combined rules for the three towers: the bert text rules plus
        the SD conv/transformer rules when the faithful towers are in
        use (compact test towers replicate)."""
        from fengshen_tpu.models.bert.modeling_bert import (
            PARTITION_RULES as BERT_RULES)
        rules = [r for r in BERT_RULES if r[0] != ".*"]
        if isinstance(self.unet_config, SDUNetConfig) or \
                isinstance(self.vae_config, SDVAEConfig):
            from fengshen_tpu.models.stable_diffusion.unet_sd import (
                SD_PARTITION_RULES)
            rules += [r for r in SD_PARTITION_RULES if r[0] != ".*"]
        return rules + [(".*", P(None))]


def diffusion_loss(pred, latents, noise, timesteps,
                   scheduler: Optional[DDPMScheduler] = None,
                   prediction_type: str = "epsilon"):
    """MSE against the ε or v target (reference: finetune.py:130-136)."""
    scheduler = scheduler or DDPMScheduler(prediction_type=prediction_type)
    if prediction_type == "v_prediction":
        target = scheduler.get_velocity(latents, noise, timesteps)
    else:
        target = noise
    return jnp.mean(jnp.square(pred.astype(jnp.float32) -
                               target.astype(jnp.float32)))
