"""LLaMA in flax, TPU-first.

Functional parity with the reference's TP LLaMA
(reference: fengshen/models/llama/modeling_llama.py:97-405, built from
megatron ``Embedding`` + ``ParallelTransformerLayer`` + ``ParallelLinear``):
RMSNorm pre-norm, rotary, SwiGLU with `multiple_of` rounding, causal LM head,
KV-cache generation. The Megatron TP layer classes collapse into
PARTITION_RULES below — GSPMD inserts the collectives the reference coded as
autograd Functions (SURVEY.md §2.1), and `parallel_output` (reference:
modeling_llama.py:246-264) disappears: the loss consumes sharded logits via
vocab-parallel CE.

Parameter naming matches HF's LlamaForCausalLM so torch checkpoints import
by path mapping (see convert.py), replacing the reference's offline TP
resharding scripts (reference: fengshen/utils/llama_convert/*, SURVEY.md §5.4).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from fengshen_tpu.models.llama.configuration_llama import LlamaConfig
from fengshen_tpu.ops.attention import dot_product_attention
from fengshen_tpu.ops.pallas.decode_attention import decode_attention
from fengshen_tpu.ops.embedding import VocabParallelEmbed
from fengshen_tpu.ops.masks import causal_mask
from fengshen_tpu.ops.norms import RMSNorm
from fengshen_tpu.ops.rotary import apply_rotary_pos_emb
from fengshen_tpu.sharding import to_partition_rules, with_logical_constraint

#: Megatron-equivalent sharding layout (reference: mpu/layers.py:55-470 —
#: vocab-parallel embedding, column-parallel QKV/gate/up, row-parallel
#: o_proj/down) expressed as LOGICAL axes; the active rules table
#: (fengshen_tpu/sharding/rules.py) maps them onto the mesh. flax Dense
#: kernels are [in, out]: column-parallel shards out, row-parallel
#: shards in; 'embed' picks up ZeRO-3-style param sharding.
LLAMA_PARAM_LOGICAL_AXES: list[tuple[str, tuple]] = [
    ("embed_tokens/embedding", ("vocab", "embed")),
    (r"(q_proj|k_proj|v_proj)/kernel", ("embed", "heads")),
    (r"(gate_proj|up_proj)/kernel", ("embed", "mlp")),
    (r"o_proj/kernel", ("heads", "embed")),
    (r"down_proj/kernel", ("mlp", "embed")),
    (r"experts_(gate|up)", ("expert", None, "mlp")),
    (r"experts_down", ("expert", "mlp", None)),
    ("lm_head/kernel", ("embed", "vocab")),
    ("norm", ("norm",)),
    (".*", (None,)),
]

#: rules for scan_layers=True — stacked layer params carry a leading [L]
#: dim ('layers', never mesh-sharded), so layer-internal dims shift right
SCAN_PARAM_LOGICAL_AXES: list[tuple[str, tuple]] = [
    ("embed_tokens/embedding", ("vocab", "embed")),
    (r"layers/.*(q_proj|k_proj|v_proj)/kernel", ("layers", "embed", "heads")),
    (r"layers/.*(gate_proj|up_proj)/kernel", ("layers", "embed", "mlp")),
    (r"layers/.*o_proj/kernel", ("layers", "heads", "embed")),
    (r"layers/.*down_proj/kernel", ("layers", "mlp", "embed")),
    (r"layers/.*experts_(gate|up)", ("layers", "expert", None, "mlp")),
    (r"layers/.*experts_down", ("layers", "expert", "mlp", None)),
    ("lm_head/kernel", ("embed", "vocab")),
    ("norm", ("norm",)),
    (".*", (None,)),
]

#: resolved against the default rules table at import time for callers
#: that want concrete PartitionSpecs; `partition_rules()` re-resolves so
#: a `use_rules(...)` scope takes effect
PARTITION_RULES = to_partition_rules(LLAMA_PARAM_LOGICAL_AXES)
SCAN_PARTITION_RULES = to_partition_rules(SCAN_PARAM_LOGICAL_AXES)


def _dt(config: LlamaConfig):
    return jnp.dtype(config.dtype)


class CacheView(NamedTuple):
    """What `_update_cache` hands the decode_attention dispatch seam
    (fengshen_tpu/ops/pallas/decode_attention.py): the cache in its
    NATIVE layout — the paged pool stays `[num_blocks, block_size, kv,
    hd]` behind its `block_table` (the Mosaic kernel reads it through
    the table; the xla lowering gathers), and int8 pools stay int8
    with their per-(token, head) scales (dequant happens inside the
    attention read on either path)."""

    k: jax.Array
    v: jax.Array
    k_scale: Optional[jax.Array]
    v_scale: Optional[jax.Array]
    block_table: Optional[jax.Array]
    #: [B, Sq, L] bool over the (virtual) lane
    valid: jax.Array


class LlamaMLP(nn.Module):
    """SwiGLU (reference: LLaMAParallelMLP,
    fengshen/models/megatron/layers/transformer.py:571-623)."""

    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        inter = cfg.intermediate_size
        if inter is None:
            # 2/3·4h rounded up to multiple_of (reference: :589-590)
            inter = int(2 * 4 * cfg.hidden_size / 3)
            inter = cfg.multiple_of * (
                (inter + cfg.multiple_of - 1) // cfg.multiple_of)
        dense = lambda feats, name: nn.Dense(  # noqa: E731
            feats, use_bias=False, dtype=_dt(cfg),
            param_dtype=jnp.dtype(cfg.param_dtype),
            kernel_init=nn.initializers.normal(cfg.initializer_range),
            name=name)
        gate = dense(inter, "gate_proj")(x)
        up = dense(inter, "up_proj")(x)
        h = nn.silu(gate) * up
        h = with_logical_constraint(h, ("batch", "seq", "mlp"))
        return dense(cfg.hidden_size, "down_proj")(h)


class LlamaAttention(nn.Module):
    """Rotary MHA/GQA with KV cache (reference: ParallelSelfAttention,
    fengshen/models/megatron/layers/transformer.py:175-568; KV-cache concat
    for generation at :529-537)."""

    config: LlamaConfig

    @nn.compact
    def __call__(self, hidden, attention_mask=None, position_ids=None,
                 init_cache: bool = False, deterministic: bool = True):
        cfg = self.config
        n_heads, n_kv = cfg.num_attention_heads, cfg.num_key_value_heads
        head_dim = cfg.head_dim
        batch, seq, _ = hidden.shape

        dense = lambda feats, name: nn.Dense(  # noqa: E731
            feats, use_bias=False, dtype=_dt(cfg),
            param_dtype=jnp.dtype(cfg.param_dtype),
            kernel_init=nn.initializers.normal(cfg.initializer_range),
            name=name)
        q = dense(n_heads * head_dim, "q_proj")(hidden)
        k = dense(n_kv * head_dim, "k_proj")(hidden)
        v = dense(n_kv * head_dim, "v_proj")(hidden)
        q = q.reshape(batch, seq, n_heads, head_dim)
        k = k.reshape(batch, seq, n_kv, head_dim)
        v = v.reshape(batch, seq, n_kv, head_dim)

        if position_ids is None:
            position_ids = jnp.arange(seq)[None, :]
        q, k = apply_rotary_pos_emb(q, k, position_ids, base=cfg.rope_theta)

        is_decode = self.has_variable("cache", "cached_key") or init_cache
        impl = cfg.attention_impl
        if is_decode:
            # every (layout, dtype, spec_mode) decode combo routes
            # through ONE dispatch seam (docs/kernels.md): the Mosaic
            # kernel reads paged pools through the block table with no
            # gather copy and dequantizes int8 in registers; the xla
            # lowering replays the stock gather → dequant → GQA repeat
            # → dense chain op-for-op, so CPU tier-1 pins decode
            # token-identical through the seam
            view = self._update_cache(k, v, attention_mask)
            out = decode_attention(
                q, view.k, view.v, view.valid,
                k_scale=view.k_scale, v_scale=view.v_scale,
                block_table=view.block_table, dequant_dtype=_dt(cfg))
        else:
            mask = causal_mask(seq, k.shape[1])[None, None]
            if attention_mask is not None:
                if getattr(cfg, "packed_sequences", False):
                    # packed rows: attention_mask carries per-example
                    # segment ids (0 = pad) — block-diagonal causal mask
                    seg_m = attention_mask.astype(jnp.int32)
                    mask = mask & (seg_m[:, None, :, None] ==
                                   seg_m[:, None, None, :])
                else:
                    mask = mask & \
                        attention_mask[:, None, None, :].astype(bool)

            if n_kv != n_heads and impl != "flash":
                # GQA: repeat kv heads for the dense/ring paths; the
                # flash dispatch handles grouped KV natively (the Pallas
                # kernel reads each KV head once per group from HBM)
                rep = n_heads // n_kv
                k = jnp.repeat(k, rep, axis=2)
                v = jnp.repeat(v, rep, axis=2)

            if impl in ("flash", "ring", "ulysses", "sequence"):
                # a padding mask maps to segment ids (pads = segment 0),
                # so padded SFT batches stay on the fused/ring paths
                seg = None if attention_mask is None else \
                    attention_mask.astype(jnp.int32)
                if impl == "flash":
                    from fengshen_tpu.ops.flash_attention import (
                        flash_attention)
                    out = flash_attention(q, k, v, causal=True,
                                          segment_ids=seg)
                else:
                    out = dot_product_attention(q, k, v, impl=impl,
                                                segment_ids=seg)
            else:
                out = dot_product_attention(q, k, v, mask=mask)

        out = with_logical_constraint(
            out, ("batch", "seq", "heads", None))
        out = out.reshape(batch, seq, n_heads * head_dim)
        return dense(cfg.hidden_size, "o_proj")(out)

    def _update_cache(self, k, v, attention_mask):
        """flax mutable-cache decode (same role as the reference's KV concat,
        reference: transformer.py:529-537, but with static shapes for XLA:
        the cache is preallocated at max length and updated in place).

        Three physical layouts share this entry point, detected from the
        cache variables themselves (shapes are static under jit):

        - scalar `cache_index`: lockstep batch decode (`utils.generate`);
        - `[B]` vector index: the serving slot pool — every lane at its
          own position, optionally int8 (a `cached_key_scale` variable
          marks the quantized pool);
        - `block_table` present: the paged pool
          (`fengshen_tpu/serving/paged_cache.py`) — lanes indirect
          through per-slot block lists into a shared block pool.

        Returns a :class:`CacheView` in the cache's NATIVE layout; the
        decode_attention dispatch seam owns the read (gather/dequant on
        the xla lowering, table-indirect + in-register dequant in the
        Mosaic kernel).
        """
        cfg = self.config
        batch, seq, n_kv, head_dim = k.shape
        max_len = cfg.max_position_embeddings
        if self.has_variable("cache", "block_table"):
            return self._update_paged_cache(k, v, attention_mask)
        # when the variables are being created (the init_cache=True init
        # pass), skip the update so the returned cache starts at index 0
        is_initialized = self.has_variable("cache", "cached_key")
        cached_k = self.variable("cache", "cached_key", jnp.zeros,
                                 (batch, max_len, n_kv, head_dim), k.dtype)
        cached_v = self.variable("cache", "cached_value", jnp.zeros,
                                 (batch, max_len, n_kv, head_dim), v.dtype)
        cache_index = self.variable("cache", "cache_index",
                                    lambda: jnp.zeros((), jnp.int32))
        if not is_initialized:
            valid = jnp.broadcast_to(
                (jnp.arange(max_len) < seq)[None, None],
                (batch, seq, max_len))
            return CacheView(k, v, None, None, None, valid[:, :, :seq])
        idx = cache_index.value
        ks_all = vs_all = None
        if idx.ndim == 1:
            # slot-pool decode (fengshen_tpu/serving): a [B] cache_index
            # gives every lane its own write position, so concurrently
            # served requests at different progress share ONE jitted step
            quantized = self.has_variable("cache", "cached_key_scale")
            if quantized:
                from fengshen_tpu.ops.int8_matmul import quantize_kv
                k_scale = self.variable(
                    "cache", "cached_key_scale", jnp.zeros,
                    (batch, max_len, n_kv), jnp.float32)
                v_scale = self.variable(
                    "cache", "cached_value_scale", jnp.zeros,
                    (batch, max_len, n_kv), jnp.float32)
                k, ks = quantize_kv(k)
                v, vs = quantize_kv(v)
                ks_all = jax.vmap(
                    lambda c, u, i: jax.lax.dynamic_update_slice(
                        c, u, (i, 0)))(k_scale.value, ks, idx)
                vs_all = jax.vmap(
                    lambda c, u, i: jax.lax.dynamic_update_slice(
                        c, u, (i, 0)))(v_scale.value, vs, idx)
                k_scale.value, v_scale.value = ks_all, vs_all
            k_all = jax.vmap(lambda c, u, i: jax.lax.dynamic_update_slice(
                c, u, (i, 0, 0)))(cached_k.value, k, idx)
            v_all = jax.vmap(lambda c, u, i: jax.lax.dynamic_update_slice(
                c, u, (i, 0, 0)))(cached_v.value, v, idx)
            cached_k.value, cached_v.value = k_all, v_all
            # int8 pools stay int8: the CacheView carries the raw pool
            # + scales and the attention read dequantizes (in registers
            # on the Mosaic kernel, via dequantize_kv on the lowering)
            cache_index.value = idx + seq
            # per-lane causal validity: lane b's query t (position
            # idx[b]+t) sees cache positions ≤ idx[b]+t
            q_pos = idx[:, None] + jnp.arange(seq)[None, :]
            valid = jnp.arange(max_len)[None, None, :] <= q_pos[:, :, None]
        else:
            k_all = jax.lax.dynamic_update_slice(cached_k.value, k,
                                                 (0, idx, 0, 0))
            v_all = jax.lax.dynamic_update_slice(cached_v.value, v,
                                                 (0, idx, 0, 0))
            cached_k.value, cached_v.value = k_all, v_all
            cache_index.value = idx + seq
            # per-query causal validity: query t (global position idx+t)
            # sees cache positions ≤ idx+t  → [B, Sq, max_len]
            q_pos = idx + jnp.arange(seq)
            valid = jnp.arange(max_len)[None, :] <= q_pos[:, None]
            valid = jnp.broadcast_to(valid[None], (batch, seq, max_len))
        if attention_mask is not None:
            # left-padded batches mask out pad positions of the prompt
            pad = jnp.ones((attention_mask.shape[0],
                            max_len - attention_mask.shape[1]),
                           attention_mask.dtype)
            full = jnp.concatenate([attention_mask, pad], axis=1)
            valid = valid & full[:, None, :].astype(bool)
        return CacheView(k_all, v_all, ks_all, vs_all, None, valid)

    def _update_paged_cache(self, k, v, attention_mask):
        """Paged decode (fengshen_tpu/serving/paged_cache.py): K/V live
        in a shared `[num_blocks, block_size, kv, hd]` pool; each lane's
        logical positions map through its `block_table` row to physical
        blocks. The host scheduler owns the free list; this method only
        scatters the step's K/V at `table[lane, p // bs] * bs + p % bs`
        for each of the step's `seq` positions `p = idx + 0..seq-1`
        (seq == 1 for the plain decode tick; seq == gamma+1 for the
        speculative verify window, whose positions may CROSS a block
        boundary — hence the per-position block lookup). The READ moved
        into the decode_attention dispatch seam: the Mosaic kernel
        walks the block table directly (no gather copy), while the xla
        lowering reconstructs the stock contiguous-virtual-lane
        `jnp.take` gather, so the XLA-CPU tier-1 lane sees the same
        math it always ran. Inactive
        lanes are parked on block 0 (the null block, never allocated),
        which absorbs their stray writes; the engine's admission
        charges blocks for the speculative tail too
        (`serving/paged_cache.py blocks_for_tokens` over
        bucket + max_new + gamma), so an active lane's over-scattered
        window never reaches a block it does not own. Prefill still
        runs on a contiguous batch-1 cache and is scattered in by
        `assign_paged` — a whole prompt through this path would
        overrun the lane, hence the seq bound below.

        An int8 pool (marked by `cached_key_scale`) stores per-(token,
        head) absmax scales alongside and dequantizes inside the read.
        """
        cfg = self.config
        batch, seq, n_kv, head_dim = k.shape
        cached_k = self.variable("cache", "cached_key", jnp.zeros,
                                 (1, 1, n_kv, head_dim), k.dtype)
        cached_v = self.variable("cache", "cached_value", jnp.zeros,
                                 (1, 1, n_kv, head_dim), v.dtype)
        cache_index = self.variable("cache", "cache_index",
                                    lambda: jnp.zeros((batch,), jnp.int32))
        table = self.variable("cache", "block_table",
                              lambda: jnp.zeros((batch, 1), jnp.int32))
        num_blocks, block_size = cached_k.value.shape[:2]
        max_blocks = table.value.shape[-1]
        virt_len = max_blocks * block_size   # the lane's logical extent
        if seq > virt_len:
            # a window that cannot fit any lane (e.g. prefilling a
            # long prompt through the paged path) must fail loudly —
            # the block lookup below would clamp its overflow
            # positions onto one block and silently corrupt it
            raise ValueError(
                f"paged cache updates take at most the virtual lane "
                f"length {virt_len} tokens per step (decode tick or "
                f"speculative verify window); got seq={seq}. Prefill "
                "runs on a contiguous batch-1 cache.")
        idx = cache_index.value              # [B] physical cursors
        quantized = self.has_variable("cache", "cached_key_scale")

        # scatter this step's K/V at each lane's physical positions
        # (lanes parked on the null block collide there by design —
        # whichever garbage write wins is never read unmasked)
        p = idx[:, None] + jnp.arange(seq)[None, :]        # [B, seq]
        blk = jnp.take_along_axis(table.value, p // block_size, axis=-1)
        pos = (blk * block_size + p % block_size).reshape(-1)
        flat_k = cached_k.value.reshape(num_blocks * block_size,
                                        n_kv, head_dim)
        flat_v = cached_v.value.reshape(num_blocks * block_size,
                                        n_kv, head_dim)
        if quantized:
            from fengshen_tpu.ops.int8_matmul import quantize_kv
            k_scale = self.variable(
                "cache", "cached_key_scale", jnp.zeros,
                (num_blocks, block_size, n_kv), jnp.float32)
            v_scale = self.variable(
                "cache", "cached_value_scale", jnp.zeros,
                (num_blocks, block_size, n_kv), jnp.float32)
            kq, ks = quantize_kv(k)
            vq, vs = quantize_kv(v)
            flat_k = flat_k.at[pos].set(
                kq.reshape(batch * seq, n_kv, head_dim))
            flat_v = flat_v.at[pos].set(
                vq.reshape(batch * seq, n_kv, head_dim))
            flat_ks = k_scale.value.reshape(-1, n_kv).at[pos].set(
                ks.reshape(batch * seq, n_kv))
            flat_vs = v_scale.value.reshape(-1, n_kv).at[pos].set(
                vs.reshape(batch * seq, n_kv))
            k_scale.value = flat_ks.reshape(num_blocks, block_size, n_kv)
            v_scale.value = flat_vs.reshape(num_blocks, block_size, n_kv)
        else:
            flat_k = flat_k.at[pos].set(
                k.reshape(batch * seq, n_kv, head_dim).astype(
                    flat_k.dtype))
            flat_v = flat_v.at[pos].set(
                v.reshape(batch * seq, n_kv, head_dim).astype(
                    flat_v.dtype))
        cached_k.value = flat_k.reshape(num_blocks, block_size,
                                        n_kv, head_dim)
        cached_v.value = flat_v.reshape(num_blocks, block_size,
                                        n_kv, head_dim)
        cache_index.value = idx + seq

        # NO gather: the pool stays put and the CacheView carries the
        # block table — the attention read resolves the indirection
        # (the Mosaic kernel's index maps walk the table per block; the
        # xla lowering reconstructs the stock jnp.take virtual lane)
        # per-lane causal validity over the virtual lane (same law as
        # the slot path: query at idx[b] sees positions <= idx[b])
        q_pos = idx[:, None] + jnp.arange(seq)[None, :]
        valid = jnp.arange(virt_len)[None, None, :] <= q_pos[:, :, None]
        if attention_mask is not None:
            m = attention_mask[:, :virt_len]
            if m.shape[1] < virt_len:
                pad = jnp.ones((batch, virt_len - m.shape[1]), m.dtype)
                m = jnp.concatenate([m, pad], axis=1)
            valid = valid & m[:, None, :].astype(bool)
        return CacheView(cached_k.value, cached_v.value,
                         k_scale.value if quantized else None,
                         v_scale.value if quantized else None,
                         table.value, valid)


class LlamaDecoderLayer(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, hidden, attention_mask=None, position_ids=None,
                 init_cache=False, deterministic=True):
        cfg = self.config
        h = RMSNorm(epsilon=cfg.rms_norm_eps, name="input_layernorm")(hidden)
        h = LlamaAttention(cfg, name="self_attn")(
            h, attention_mask, position_ids, init_cache, deterministic)
        hidden = hidden + h
        h = RMSNorm(epsilon=cfg.rms_norm_eps,
                    name="post_attention_layernorm")(hidden)
        if cfg.moe_experts > 0:
            # routed expert MLP instead of the dense one (beyond-reference
            # capability; aux loss sowed under ("losses","moe_aux_loss"))
            from fengshen_tpu.ops.moe import SwitchMoE
            # cached decode feeds a 1-token hidden with the full-prompt
            # mask; the live decode token is always real, so no mask
            tok_mask = attention_mask
            if tok_mask is not None and tok_mask.shape[1] != h.shape[1]:
                tok_mask = None
            elif tok_mask is not None:
                # packed rows carry segment ids; MoE only needs real/pad
                tok_mask = (tok_mask > 0).astype(jnp.int32)
            h, _ = SwitchMoE(
                hidden_size=cfg.hidden_size,
                intermediate_size=cfg.intermediate_size,
                num_experts=cfg.moe_experts,
                capacity_factor=cfg.moe_capacity_factor,
                dtype=_dt(cfg),
                param_dtype=jnp.dtype(cfg.param_dtype),
                name="moe_mlp")(h, token_mask=tok_mask,
                                deterministic=deterministic)
        else:
            h = LlamaMLP(cfg, name="mlp")(h)
        return hidden + h


class _ScanDecoderLayer(nn.Module):
    """nn.scan body: (carry, _) → (carry, None)."""

    config: LlamaConfig

    @nn.compact
    def __call__(self, hidden, attention_mask, position_ids, init_cache,
                 deterministic):
        out = LlamaDecoderLayer(self.config, name="layer")(
            hidden, attention_mask, position_ids, init_cache, deterministic)
        return out, None


class LlamaModel(nn.Module):
    """Decoder stack (reference: fengshen/models/llama/modeling_llama.py:97-236)."""

    config: LlamaConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, position_ids=None,
                 init_cache=False, deterministic=True):
        cfg = self.config
        embed = VocabParallelEmbed(cfg.vocab_size, cfg.hidden_size,
                                   dtype=_dt(cfg),
                                   param_dtype=jnp.dtype(cfg.param_dtype),
                                   embedding_init=nn.initializers.normal(
                                       cfg.initializer_range),
                                   name="embed_tokens")
        hidden = embed(input_ids)
        hidden = with_logical_constraint(
            hidden, ("batch", "seq", None))

        remat_policy = {
            "nothing": jax.checkpoint_policies.nothing_saveable,
            "dots_no_batch":
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            "checkpoint_dots": jax.checkpoint_policies.checkpoint_dots,
        }[getattr(cfg, "remat_policy", "nothing")]
        if cfg.scan_layers:
            body = _ScanDecoderLayer
            if cfg.gradient_checkpointing:
                body = nn.remat(
                    body, static_argnums=(4, 5),
                    policy=remat_policy,
                    prevent_cse=False)
            scan = nn.scan(
                body,
                variable_axes={"params": 0, "cache": 0, "losses": 0},
                split_rngs={"params": True, "dropout": True},
                in_axes=(nn.broadcast,) * 4,
                length=cfg.num_hidden_layers)
            hidden, _ = scan(cfg, name="layers")(
                hidden, attention_mask, position_ids, init_cache,
                deterministic)
        else:
            layer_cls = LlamaDecoderLayer
            if cfg.gradient_checkpointing:
                layer_cls = nn.remat(
                    layer_cls, static_argnums=(4, 5),
                    policy=remat_policy)
            for i in range(cfg.num_hidden_layers):
                hidden = layer_cls(cfg, name=f"layers_{i}")(
                    hidden, attention_mask, position_ids, init_cache,
                    deterministic)
        return RMSNorm(epsilon=cfg.rms_norm_eps, name="norm")(hidden)


class _Int8LMHead(nn.Module):
    """Dense-compatible LM head routed through the dynamic int8 matmul
    (ops/int8_matmul.py): same `kernel` param shape/path as nn.Dense so
    partition rules and checkpoint converters are unaffected."""

    config: LlamaConfig

    @nn.compact
    def __call__(self, hidden):
        from fengshen_tpu.ops.int8_matmul import int8_matmul
        cfg = self.config
        kernel = self.param("kernel",
                            nn.initializers.normal(cfg.initializer_range),
                            (cfg.hidden_size, cfg.vocab_size),
                            jnp.dtype(cfg.param_dtype))
        return int8_matmul(hidden, kernel.astype(_dt(cfg)))


class LlamaForCausalLM(nn.Module):
    """LM head on the stack (reference: modeling_llama.py:239-405)."""

    config: LlamaConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, position_ids=None,
                 init_cache=False, deterministic=True,
                 return_hidden=False):
        cfg = self.config
        hidden = LlamaModel(cfg, name="model")(
            input_ids, attention_mask, position_ids, init_cache,
            deterministic)
        if return_hidden:
            # the fused chunked LM-head+CE path (ops/fused_ce.py)
            # applies the head itself from the param tree (init always
            # runs the normal path, so lm_head params exist either way)
            return hidden
        if cfg.tie_word_embeddings:
            embedding = self.variables["params"]["model"]["embed_tokens"][
                "embedding"]
            if cfg.int8_lm_head:
                from fengshen_tpu.ops.int8_matmul import int8_matmul
                logits = int8_matmul(hidden,
                                     embedding.T.astype(hidden.dtype))
            else:
                logits = hidden @ embedding.T.astype(hidden.dtype)
        elif cfg.int8_lm_head:
            # same lm_head/kernel param path as the Dense branch, so
            # partition rules and converters apply unchanged
            logits = _Int8LMHead(cfg, name="lm_head")(hidden)
        else:
            logits = nn.Dense(cfg.vocab_size, use_bias=False,
                              dtype=_dt(cfg),
                              param_dtype=jnp.dtype(cfg.param_dtype),
                              kernel_init=nn.initializers.normal(
                                  cfg.initializer_range),
                              name="lm_head")(hidden)
        return logits

    # -- convenience -----------------------------------------------------
    def init_params(self, rng, seq_len: int = 8):
        ids = jnp.zeros((1, seq_len), jnp.int32)
        return self.init(rng, ids)["params"]

    def partition_rules(self):
        return to_partition_rules(
            SCAN_PARAM_LOGICAL_AXES if self.config.scan_layers
            else LLAMA_PARAM_LOGICAL_AXES)


def resize_token_embeddings(params: dict, config, new_num_tokens: int,
                            rng=None):
    """Grow/shrink the vocab dim of embed_tokens + lm_head, preserving the
    existing rows (reference: models/llama/modeling_llama.py:386-405 —
    there it rebuilds Embedding/ParallelLinear modules and copies the old
    weight rows; here params are a pytree, so this is a pure function
    returning (new_params, new_config)).

    New rows draw from N(0, config.initializer_range) like the
    reference's init_method. Works for both tied (no lm_head entry) and
    untied heads.
    """
    import dataclasses

    old = config.vocab_size
    if rng is None:
        rng = jax.random.PRNGKey(0)

    def _resize_rows(table, key):
        n, h = table.shape
        if new_num_tokens <= n:
            return table[:new_num_tokens]
        extra = (jax.random.normal(key, (new_num_tokens - n, h),
                                   jnp.float32)
                 * config.initializer_range).astype(table.dtype)
        return jnp.concatenate([table, extra], axis=0)

    k_embed, k_head = jax.random.split(rng)
    embed = params["model"]["embed_tokens"]["embedding"]
    assert embed.shape[0] == old, (embed.shape, old)
    new_params = {**params,
                  "model": {**params["model"],
                            "embed_tokens": {
                                "embedding": _resize_rows(embed, k_embed)}}}
    if "lm_head" in params:
        kernel = params["lm_head"]["kernel"]  # [H, V]
        new_params["lm_head"] = {
            "kernel": _resize_rows(kernel.T, k_head).T}
    return new_params, dataclasses.replace(config,
                                           vocab_size=new_num_tokens)


def make_self_draft(config: LlamaConfig, params: dict, n_layers: int):
    """Early-exit draft for SELF-speculative decoding: the target's own
    first `n_layers` decoder layers plus its shared embeddings, final
    norm, and LM head form the draft model — no second checkpoint
    needed (`utils/generate.py speculative_generate` stays exact
    regardless of draft quality, so the truncated tower only affects
    the acceptance rate, never the output law).

    Returns `(draft_config, draft_params)`. Shared leaves alias the
    target's arrays (no copy); under `scan_layers` the stacked layer
    leaves are sliced to the first `n_layers`.
    """
    import dataclasses

    if not 0 < n_layers < config.num_hidden_layers:
        raise ValueError(
            f"make_self_draft: n_layers={n_layers} must be in "
            f"(0, {config.num_hidden_layers})")
    model_p = dict(params["model"])
    if config.scan_layers:
        model_p["layers"] = jax.tree_util.tree_map(
            lambda x: x[:n_layers], params["model"]["layers"])
    else:
        kept = {f"layers_{i}" for i in range(n_layers)}
        model_p = {k: v for k, v in model_p.items()
                   if not k.startswith("layers_") or k in kept}
    draft_params = {**params, "model": model_p}
    return dataclasses.replace(config, num_hidden_layers=n_layers), \
        draft_params
