"""LLaMA config (reference: fengshen/models/llama/configuration_llama.py:24-100).

Field names follow the HF convention so checkpoints/configs interoperate;
TPU-specific knobs (dtype policy, remat, attention impl) are additive.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Optional


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: Optional[int] = None  # GQA; None = MHA
    max_position_embeddings: int = 2048
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    initializer_range: float = 0.02
    use_cache: bool = True
    tie_word_embeddings: bool = False
    bos_token_id: int = 1
    eos_token_id: int = 2
    pad_token_id: int = 0
    # TPU-native knobs
    dtype: str = "bfloat16"            # activation/compute dtype
    param_dtype: str = "float32"
    gradient_checkpointing: bool = False
    # remat policy for gradient checkpointing (the MFU lever VERDICT r1
    # item 2 calls out): "nothing" recomputes the full layer;
    # "dots_no_batch" saves matmul outputs (jax
    # dots_with_no_batch_dims_saveable); "checkpoint_dots" saves all dots
    remat_policy: str = "nothing"      # nothing | dots_no_batch | checkpoint_dots
    attention_impl: str = "dense"      # dense | flash | ring | ulysses | sequence
    # dynamic int8x int8 LM-head matmul (2x MXU rate on v5e; see
    # ops/int8_matmul.py). Training-time perf lever, off by default.
    int8_lm_head: bool = False
    # >0: chunked fused LM-head+CE (ops/fused_ce.py) — logits are
    # computed per sequence chunk and recomputed in backward, cutting
    # peak HBM by ~the chunk factor on the [B,S,V] tensor. Replicated
    # head only (TP uses vocab-parallel CE instead).
    fused_ce_chunks: int = 0
    # lax.scan over layers: one compiled layer body regardless of depth —
    # keeps compile time/program size O(1) in num_hidden_layers and is the
    # standard TPU pattern for deep stacks. Params gain a leading [L] dim.
    scan_layers: bool = False
    # `multiple_of` rounding of the SwiGLU hidden dim
    # (reference: fengshen/models/megatron/layers/transformer.py:589-590)
    multiple_of: int = 256
    # MoE: >0 replaces the dense MLP with a SwitchMoE of that many
    # experts, sharded over the 'expert' mesh axis (beyond-reference)
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01  # Switch aux-loss coefficient (α)
    # sequence packing: attention_mask carries per-example segment ids
    # (0 = pad) and position ids restart per example — the flash kernel's
    # segment support makes packing free; dense builds the block-diagonal
    # mask from segment equality
    packed_sequences: bool = False

    def __post_init__(self):
        if self.num_key_value_heads is None:
            self.num_key_value_heads = self.num_attention_heads

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def from_pretrained(cls, path: str) -> "LlamaConfig":
        cfg_file = os.path.join(path, "config.json") if os.path.isdir(path) \
            else path
        with open(cfg_file) as f:
            raw = json.load(f)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in raw.items() if k in known})

    def save_pretrained(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "config.json"), "w") as f:
            json.dump(dataclasses.asdict(self) |
                      {"model_type": "llama"}, f, indent=2)

    @classmethod
    def small_test_config(cls, **overrides: Any) -> "LlamaConfig":
        base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                    num_hidden_layers=2, num_attention_heads=4,
                    max_position_embeddings=128, multiple_of=16)
        base.update(overrides)
        return cls(**base)
