"""LLaMA / Ziya family (reference: fengshen/models/llama/ — the reference's
only tensor-parallel model, SURVEY.md §2.5)."""

from fengshen_tpu.models.llama.configuration_llama import LlamaConfig
from fengshen_tpu.models.llama.modeling_llama import (LlamaModel,
                                                      LlamaForCausalLM,
                                                      make_self_draft,
                                                      resize_token_embeddings)

__all__ = ["LlamaConfig", "LlamaModel", "LlamaForCausalLM",
           "make_self_draft", "resize_token_embeddings"]
