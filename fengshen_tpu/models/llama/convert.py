"""torch(HF) ↔ jax weight interop for LLaMA.

Replaces the reference's offline converter+resharder suite
(reference: fengshen/utils/llama_convert/hf_to_fs.py, fs_to_hf.py,
convert_fs_llama_tp.py — the per-rank ``part_{i}`` shard dirs,
convert_fs_llama_tp.py:15-31). TPU-native: ONE logical checkpoint; sharding
happens at `device_put` time from the partition rules, so offline TP
resharding is obsolete (SURVEY.md §5.4).
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from fengshen_tpu.utils.convert_common import tensor as _tensor

from fengshen_tpu.models.llama.configuration_llama import LlamaConfig


def torch_to_params(state_dict: Mapping[str, Any],
                    config: LlamaConfig) -> dict:
    """HF `LlamaForCausalLM.state_dict()` → flax params pytree.

    torch Linear stores [out, in]; flax Dense kernel is [in, out] → transpose.
    Norm `weight` → `scale`. No QKV head-major reshuffle is needed because we
    keep separate q/k/v projections (the reference's fused-QKV head-major
    reshape, convert_fs_llama_tp.py:152-157, was an artifact of its fused
    ColumnParallel layout).
    """

    def t(name):
        return _tensor(state_dict, name)

    params: dict = {"model": {"embed_tokens": {
        "embedding": t("model.embed_tokens.weight")}}}

    def layer_tree(i: int) -> dict:
        pre = f"model.layers.{i}"
        return {
            "self_attn": {
                proj: {"kernel": t(f"{pre}.self_attn.{proj}.weight").T}
                for proj in ("q_proj", "k_proj", "v_proj", "o_proj")},
            "mlp": {
                proj: {"kernel": t(f"{pre}.mlp.{proj}.weight").T}
                for proj in ("gate_proj", "up_proj", "down_proj")},
            "input_layernorm": {"scale": t(f"{pre}.input_layernorm.weight")},
            "post_attention_layernorm": {
                "scale": t(f"{pre}.post_attention_layernorm.weight")},
        }

    if config.scan_layers:
        # stack per-layer trees along a leading [L] dim (nn.scan layout)
        import jax
        trees = [layer_tree(i) for i in range(config.num_hidden_layers)]
        params["model"]["layers"] = {"layer": jax.tree_util.tree_map(
            lambda *xs: np.stack(xs), *trees)}
    else:
        for i in range(config.num_hidden_layers):
            params["model"][f"layers_{i}"] = layer_tree(i)
    params["model"]["norm"] = {"scale": t("model.norm.weight")}
    if not config.tie_word_embeddings:
        params["lm_head"] = {"kernel": t("lm_head.weight").T}
    return params


def params_to_torch_state(params: dict, config: LlamaConfig) -> dict:
    """flax params → HF state_dict-shaped numpy mapping (merge-back path,
    reference: fengshen/utils/llama_convert/merge_lt_mp_to_hf.py)."""
    out: dict = {}

    def n(x):
        return np.asarray(x, dtype=np.float32)

    out["model.embed_tokens.weight"] = n(
        params["model"]["embed_tokens"]["embedding"])
    import jax

    def layer_view(i: int):
        if config.scan_layers:
            # unstack the nn.scan layout's leading [L] dim
            return jax.tree_util.tree_map(
                lambda x: x[i], params["model"]["layers"]["layer"])
        return params["model"][f"layers_{i}"]

    for i in range(config.num_hidden_layers):
        layer = layer_view(i)
        pre = f"model.layers.{i}"
        for proj in ("q_proj", "k_proj", "v_proj", "o_proj"):
            out[f"{pre}.self_attn.{proj}.weight"] = n(
                layer["self_attn"][proj]["kernel"]).T
        for proj in ("gate_proj", "up_proj", "down_proj"):
            out[f"{pre}.mlp.{proj}.weight"] = n(
                layer["mlp"][proj]["kernel"]).T
        out[f"{pre}.input_layernorm.weight"] = n(
            layer["input_layernorm"]["scale"])
        out[f"{pre}.post_attention_layernorm.weight"] = n(
            layer["post_attention_layernorm"]["scale"])
    out["model.norm.weight"] = n(params["model"]["norm"]["scale"])
    if "lm_head" in params:
        out["lm_head.weight"] = n(params["lm_head"]["kernel"]).T
    return out


def load_hf_pretrained(path: str, config: LlamaConfig | None = None):
    """Load an HF llama checkpoint directory into (config, params)."""
    import torch

    config = config or LlamaConfig.from_pretrained(path)
    import glob
    import os
    state: dict = {}
    safetensor_files = sorted(glob.glob(os.path.join(path, "*.safetensors")))
    if safetensor_files:
        from safetensors import safe_open
        for f in safetensor_files:
            with safe_open(f, framework="pt") as sf:
                for key in sf.keys():
                    state[key] = sf.get_tensor(key)
    else:
        for f in sorted(glob.glob(os.path.join(path, "pytorch_model*.bin"))):
            state.update(torch.load(f, map_location="cpu",
                                    weights_only=True))
    return config, torch_to_params(state, config)


def save_converted(output_path: str, config: LlamaConfig,
                   params: dict, model_parallel_size: int = 1) -> None:
    """Write the ONE logical fengshen-tpu checkpoint: config.json +
    orbax params. `model_parallel_size` is validated against the config
    and recorded as intent — actual TP sharding happens at load time
    from the partition rules, so there are no per-rank `part_{i}` dirs
    (the reference's convert_fs_llama_tp.py:15-31 layout is obsolete
    by design here)."""
    import json
    import os

    import orbax.checkpoint as ocp

    if model_parallel_size > 1:
        for dim, name in ((config.num_attention_heads,
                           "num_attention_heads"),
                          (getattr(config, "num_key_value_heads",
                                   config.num_attention_heads),
                           "num_key_value_heads"),
                          (config.intermediate_size,
                           "intermediate_size")):
            if dim % model_parallel_size:
                raise ValueError(
                    f"{name}={dim} not divisible by "
                    f"model_parallel_size={model_parallel_size}")
    os.makedirs(output_path, exist_ok=True)
    config.save_pretrained(output_path)
    with open(os.path.join(output_path, "parallel_meta.json"), "w") as f:
        json.dump({"intended_model_parallel_size": model_parallel_size,
                   "layout": "logical (shard at load via partition "
                             "rules)"}, f)
    ckpt = ocp.StandardCheckpointer()
    ckpt.save(os.path.abspath(os.path.join(output_path, "params")),
              params, force=True)
    ckpt.wait_until_finished()


def main(argv=None) -> None:
    """CLI for the ziya convert shells (reference:
    ziya_llama/convert_llama13b_to_fs.sh, convert_llama13b_tp{4,8}.sh)."""
    import argparse

    parser = argparse.ArgumentParser("llama HF -> fengshen-tpu convert")
    parser.add_argument("--input_path", required=True, type=str,
                        help="HF llama checkpoint dir")
    parser.add_argument("--output_path", required=True, type=str)
    parser.add_argument("--input_dir", default=None, type=str,
                        help="alias of --input_path (tp-reshard shells)")
    parser.add_argument("--output_dir", default=None, type=str,
                        help="alias of --output_path")
    parser.add_argument("--model_parallel_size", default=1, type=int)
    args = parser.parse_args(argv)
    config, params = load_hf_pretrained(args.input_dir or args.input_path)
    save_converted(args.output_dir or args.output_path, config, params,
                   model_parallel_size=args.model_parallel_size)
    print(f"converted -> {args.output_dir or args.output_path} "
          f"(model_parallel_size={args.model_parallel_size})")


if __name__ == "__main__":
    main()
