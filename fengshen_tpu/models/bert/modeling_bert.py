"""Vanilla post-LN BERT in flax, HF-weight-compatible."""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from fengshen_tpu.ops.activations import get_activation
from fengshen_tpu.ops.embedding import VocabParallelEmbed
from fengshen_tpu.ops.attention import dot_product_attention
from fengshen_tpu.ops.norms import LayerNorm
from fengshen_tpu.sharding import (to_partition_rules,
                                    with_logical_constraint)

PARAM_LOGICAL_AXES: list[tuple[str, tuple]] = [
    ("word_embeddings/embedding", ("vocab", None)),
    (r"(query|key|value)/kernel", ("embed", "heads")),
    (r"intermediate_dense/kernel", ("embed", "mlp")),
    (r"attention_output_dense/kernel", ("heads", "embed")),
    (r"output_dense/kernel", ("mlp", "embed")),
    (".*", (None,)),
]
PARTITION_RULES = to_partition_rules(PARAM_LOGICAL_AXES)


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 21128
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    pad_token_id: int = 0
    num_labels: int = 2
    dtype: str = "bfloat16"
    param_dtype: str = "float32"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def from_pretrained(cls, path: str) -> "BertConfig":
        cfg_file = os.path.join(path, "config.json") if os.path.isdir(path) \
            else path
        with open(cfg_file) as f:
            raw = json.load(f)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in raw.items() if k in known})

    def save_pretrained(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "config.json"), "w") as f:
            json.dump(dataclasses.asdict(self) |
                      {"model_type": "bert"}, f, indent=2)

    @classmethod
    def small_test_config(cls, **overrides: Any) -> "BertConfig":
        base = dict(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=64,
                    max_position_embeddings=64)
        base.update(overrides)
        return cls(**base)


def _dt(cfg):
    return jnp.dtype(cfg.dtype)


def _dense(cfg, feats, name):
    return nn.Dense(feats, dtype=_dt(cfg),
                    param_dtype=jnp.dtype(cfg.param_dtype),
                    kernel_init=nn.initializers.normal(
                        cfg.initializer_range), name=name)


class BertLayer(nn.Module):
    """Post-LN transformer layer; `pre_ln=True` flips it to the pre-LN
    order (norm → attn → residual, norm → ff → residual — e.g. HF's
    HubertEncoderLayerStableLayerNorm) with IDENTICAL parameter names,
    so importers and partition rules serve both variants."""

    config: BertConfig
    pre_ln: bool = False

    @nn.compact
    def __call__(self, hidden, attention_mask=None, deterministic=True):
        cfg = self.config
        batch, seq, _ = hidden.shape
        n_head, head_dim = cfg.num_attention_heads, cfg.head_dim
        attn_ln = LayerNorm(epsilon=cfg.layer_norm_eps,
                            name="attention_ln")
        out_ln = LayerNorm(epsilon=cfg.layer_norm_eps, name="output_ln")
        x = attn_ln(hidden) if self.pre_ln else hidden
        q = _dense(cfg, cfg.hidden_size, "query")(x)
        k = _dense(cfg, cfg.hidden_size, "key")(x)
        v = _dense(cfg, cfg.hidden_size, "value")(x)
        q = q.reshape(batch, seq, n_head, head_dim)
        k = k.reshape(batch, seq, n_head, head_dim)
        v = v.reshape(batch, seq, n_head, head_dim)
        mask = None
        if attention_mask is not None:
            if attention_mask.ndim == 3:
                # per-sample [B, S, S] mask (UniMC block-diagonal options)
                mask = attention_mask[:, None].astype(bool)
            else:
                mask = attention_mask[:, None, None, :].astype(bool)
        drop_rng = None
        if not deterministic and cfg.attention_probs_dropout_prob > 0:
            drop_rng = self.make_rng("dropout")
        out = dot_product_attention(
            q, k, v, mask=mask, dropout_rng=drop_rng,
            dropout_rate=cfg.attention_probs_dropout_prob,
            deterministic=deterministic)
        out = with_logical_constraint(
            out, ("batch", "seq", "heads", None))
        out = out.reshape(batch, seq, cfg.hidden_size)
        out = _dense(cfg, cfg.hidden_size, "attention_output_dense")(out)
        out = nn.Dropout(cfg.hidden_dropout_prob)(
            out, deterministic=deterministic)
        hidden = hidden + out if self.pre_ln else attn_ln(hidden + out)
        h = out_ln(hidden) if self.pre_ln else hidden
        h = _dense(cfg, cfg.intermediate_size, "intermediate_dense")(h)
        h = get_activation(cfg.hidden_act)(h)
        h = with_logical_constraint(h, ("batch", "seq", "mlp"))
        h = _dense(cfg, cfg.hidden_size, "output_dense")(h)
        h = nn.Dropout(cfg.hidden_dropout_prob)(h,
                                                deterministic=deterministic)
        return hidden + h if self.pre_ln else out_ln(hidden + h)


class BertModel(nn.Module):
    config: BertConfig
    add_pooling_layer: bool = True

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 position_ids=None, deterministic=True):
        cfg = self.config
        batch, seq = input_ids.shape
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        if position_ids is None:
            position_ids = jnp.arange(seq)[None, :]
        embed = lambda n, name, cls=nn.Embed: cls(  # noqa: E731
            n, cfg.hidden_size, dtype=_dt(cfg),
            param_dtype=jnp.dtype(cfg.param_dtype),
            embedding_init=nn.initializers.normal(cfg.initializer_range),
            name=name)
        hidden = embed(cfg.vocab_size, "word_embeddings",
                       VocabParallelEmbed)(input_ids) + \
            embed(cfg.max_position_embeddings,
                  "position_embeddings")(position_ids) + \
            embed(cfg.type_vocab_size,
                  "token_type_embeddings")(token_type_ids)
        hidden = LayerNorm(epsilon=cfg.layer_norm_eps,
                           name="embeddings_ln")(hidden)
        hidden = nn.Dropout(cfg.hidden_dropout_prob)(
            hidden, deterministic=deterministic)
        for i in range(cfg.num_hidden_layers):
            hidden = BertLayer(cfg, name=f"layer_{i}")(
                hidden, attention_mask, deterministic)
        pooled = None
        if self.add_pooling_layer:
            pooled = jnp.tanh(_dense(cfg, cfg.hidden_size,
                                     "pooler")(hidden[:, 0]))
        return hidden, pooled

    def partition_rules(self):
        return to_partition_rules(PARAM_LOGICAL_AXES)


class BertForMaskedLM(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 position_ids=None, deterministic=True,
                 return_hidden=False):
        cfg = self.config
        hidden, _ = BertModel(cfg, add_pooling_layer=False, name="bert")(
            input_ids, attention_mask, token_type_ids, position_ids,
            deterministic=deterministic)
        h = _dense(cfg, cfg.hidden_size, "transform_dense")(hidden)
        h = get_activation(cfg.hidden_act)(h)
        h = LayerNorm(epsilon=cfg.layer_norm_eps, name="transform_ln")(h)
        wte = self.variables["params"]["bert"]["word_embeddings"][
            "embedding"]
        logits = h @ wte.T.astype(h.dtype)
        bias = self.param("bias", nn.initializers.zeros,
                          (cfg.vocab_size,), jnp.dtype(cfg.param_dtype))
        logits = logits + bias
        return (logits, hidden) if return_hidden else logits

    def partition_rules(self):
        return to_partition_rules(PARAM_LOGICAL_AXES)
