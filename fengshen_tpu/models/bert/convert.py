"""torch(HF) ↔ jax weights for BERT.

The bert analog of the reference's checkpoint-loading path (the reference
uses HF BertForMaskedLM/BertForPreTraining directly, e.g.
fengshen/examples/pretrain_bert/pretrain_bert.py:1-8); this importer lets
released HF bert checkpoints load into the flax family.
"""

from __future__ import annotations

from typing import Any, Mapping

from fengshen_tpu.models.bert.modeling_bert import BertConfig
from fengshen_tpu.utils.convert_common import bert_layer, make_helpers


def torch_to_params(state_dict: Mapping[str, Any],
                    config: BertConfig) -> dict:
    t, lin, ln = make_helpers(state_dict)
    bert = {
        "word_embeddings": {
            "embedding": t("bert.embeddings.word_embeddings.weight")},
        "position_embeddings": {
            "embedding": t("bert.embeddings.position_embeddings.weight")},
        "token_type_embeddings": {
            "embedding": t("bert.embeddings.token_type_embeddings.weight")},
        "embeddings_ln": ln("bert.embeddings.LayerNorm"),
    }
    for i in range(config.num_hidden_layers):
        bert[f"layer_{i}"] = bert_layer(state_dict,
                                        f"bert.encoder.layer.{i}")
    if "bert.pooler.dense.weight" in state_dict:
        bert["pooler"] = lin("bert.pooler.dense")
    params: dict = {"bert": bert}
    if "cls.predictions.transform.dense.weight" in state_dict:
        params["transform_dense"] = lin("cls.predictions.transform.dense")
        params["transform_ln"] = ln("cls.predictions.transform.LayerNorm")
        params["bias"] = t("cls.predictions.bias")
    return params


def model_to_params(state_dict: Mapping[str, Any],
                    config: BertConfig) -> dict:
    """For a bare BertModel state dict (no `bert.` prefix / no MLM head)."""
    prefixed = {f"bert.{k}": v for k, v in state_dict.items()}
    return torch_to_params(prefixed, config)["bert"]


#: fs→torch export: derived exact inverse of `torch_to_params`
#: (template_state = the source checkpoint: dict, Lightning ckpt, or dir)
from fengshen_tpu.utils.convert_common import (  # noqa: E402
    make_derived_export)

params_to_torch_state = make_derived_export(torch_to_params)
