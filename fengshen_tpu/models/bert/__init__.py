"""Standard BERT (post-LN) — used directly by pretrain_bert-style workloads
and as the Taiyi-CLIP text tower (reference:
fengshen/models/clip/modeling_taiyi_clip.py:27-29 uses HF BertModel)."""

from fengshen_tpu.models.bert.modeling_bert import (BertConfig, BertModel,
                                                    BertForMaskedLM)

__all__ = ["BertConfig", "BertModel", "BertForMaskedLM"]

from fengshen_tpu.models.bert.task_heads import (BertForSequenceClassification, BertForTokenClassification, BertForQuestionAnswering, BertForMultipleChoice)
__all__ += ['BertForSequenceClassification', 'BertForTokenClassification', 'BertForQuestionAnswering', 'BertForMultipleChoice']
