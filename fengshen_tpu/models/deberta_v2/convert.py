"""torch(HF) → jax weights for DeBERTa-v2."""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from fengshen_tpu.utils.convert_common import tensor as _tensor

from fengshen_tpu.models.deberta_v2.modeling_deberta_v2 import (
    DebertaV2Config)


def torch_to_params(state_dict: Mapping[str, Any],
                    config: DebertaV2Config) -> dict:
    def t(name):
        return _tensor(state_dict, name)

    def lin(prefix):
        return {"kernel": t(f"{prefix}.weight").T,
                "bias": t(f"{prefix}.bias")}

    def ln(prefix):
        return {"scale": t(f"{prefix}.weight"), "bias": t(f"{prefix}.bias")}

    d: dict = {
        "word_embeddings": {
            "embedding": t("deberta.embeddings.word_embeddings.weight")},
        "embeddings_ln": ln("deberta.embeddings.LayerNorm"),
    }
    if config.position_biased_input:
        d["position_embeddings"] = {"embedding": t(
            "deberta.embeddings.position_embeddings.weight")}
    if config.relative_attention:
        d["rel_embeddings"] = t("deberta.encoder.rel_embeddings.weight")
        if "layer_norm" in config.norm_rel_ebd:
            d["rel_embeddings_ln"] = ln("deberta.encoder.LayerNorm")
    if config.conv_kernel_size > 0:
        # torch Conv1d weight [out, in, k] → flax Conv kernel [k, in, out]
        d["conv"] = {"kernel": t("deberta.encoder.conv.conv.weight"
                                 ).transpose(2, 1, 0),
                     "bias": t("deberta.encoder.conv.conv.bias")}
        d["conv_ln"] = ln("deberta.encoder.conv.LayerNorm")
    for i in range(config.num_hidden_layers):
        pre = f"deberta.encoder.layer.{i}"
        layer = {
            "self": {
                "query_proj": lin(f"{pre}.attention.self.query_proj"),
                "key_proj": lin(f"{pre}.attention.self.key_proj"),
                "value_proj": lin(f"{pre}.attention.self.value_proj"),
            },
            "attention_output_dense": lin(f"{pre}.attention.output.dense"),
            "attention_ln": ln(f"{pre}.attention.output.LayerNorm"),
            "intermediate_dense": lin(f"{pre}.intermediate.dense"),
            "output_dense": lin(f"{pre}.output.dense"),
            "output_ln": ln(f"{pre}.output.LayerNorm"),
        }
        if not config.share_att_key:
            layer["self"]["pos_query_proj"] = lin(
                f"{pre}.attention.self.pos_query_proj")
            layer["self"]["pos_key_proj"] = lin(
                f"{pre}.attention.self.pos_key_proj")
        d[f"layer_{i}"] = layer
    return {"deberta": d}


#: fs→torch export: derived exact inverse of `torch_to_params`
#: (template_state = the source checkpoint: dict, Lightning ckpt, or dir)
from fengshen_tpu.utils.convert_common import (  # noqa: E402
    make_derived_export)

params_to_torch_state = make_derived_export(torch_to_params)
