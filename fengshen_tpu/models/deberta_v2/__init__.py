"""DeBERTa-v2 family (reference: fengshen/models/deberta_v2/ — the
Erlangshen-DeBERTa-v2 Chinese NLU fork, 1,617 LoC)."""

from fengshen_tpu.models.deberta_v2.modeling_deberta_v2 import (
    DebertaV2Config, DebertaV2Model, DebertaV2ForMaskedLM,
    DebertaV2ForSequenceClassification)

__all__ = ["DebertaV2Config", "DebertaV2Model", "DebertaV2ForMaskedLM",
           "DebertaV2ForSequenceClassification"]

from fengshen_tpu.models.deberta_v2.task_heads import (DebertaV2ForTokenClassification, DebertaV2ForQuestionAnswering, DebertaV2ForMultipleChoice)
__all__ += ['DebertaV2ForTokenClassification', 'DebertaV2ForQuestionAnswering', 'DebertaV2ForMultipleChoice']
