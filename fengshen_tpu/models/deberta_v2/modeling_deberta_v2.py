"""DeBERTa-v2 in flax, HF-weight-compatible.

Reference: fengshen/models/deberta_v2/ (HF fork for Erlangshen-DeBERTa).
Disentangled attention: content↔content plus content→position (c2p) and
position→content (p2c) terms over log-bucketed relative positions, with the
relative-position embedding table shared across layers and projected by the
(shared) key/query projections. Optional depthwise conv branch on layer 0.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from fengshen_tpu.ops.activations import get_activation
from fengshen_tpu.ops.embedding import VocabParallelEmbed
from fengshen_tpu.ops.norms import LayerNorm
from fengshen_tpu.sharding import (to_partition_rules,
                                    with_logical_constraint)

PARAM_LOGICAL_AXES: list[tuple[str, tuple]] = [
    ("word_embeddings/embedding", ("vocab", None)),
    (r"(query_proj|key_proj|value_proj)/kernel", ("embed", "heads")),
    (r"intermediate_dense/kernel", ("embed", "mlp")),
    (r"attention_output_dense/kernel", ("heads", "embed")),
    (r"output_dense/kernel", ("mlp", "embed")),
    (".*", (None,)),
]
PARTITION_RULES = to_partition_rules(PARAM_LOGICAL_AXES)


@dataclasses.dataclass
class DebertaV2Config:
    vocab_size: int = 128100
    hidden_size: int = 1536
    num_hidden_layers: int = 24
    num_attention_heads: int = 24
    intermediate_size: int = 6144
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 0
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-7
    relative_attention: bool = True
    max_relative_positions: int = -1
    position_buckets: int = 256
    norm_rel_ebd: str = "layer_norm"
    share_att_key: bool = True
    pos_att_type: tuple = ("p2c", "c2p")
    position_biased_input: bool = False
    conv_kernel_size: int = 0
    conv_act: str = "tanh"  # HF DebertaV2 default
    pad_token_id: int = 0
    num_labels: int = 2
    pooler_hidden_size: Optional[int] = None
    dtype: str = "bfloat16"
    param_dtype: str = "float32"

    def __post_init__(self):
        if self.max_relative_positions < 1:
            self.max_relative_positions = self.max_position_embeddings
        if self.pooler_hidden_size is None:
            self.pooler_hidden_size = self.hidden_size
        if isinstance(self.pos_att_type, str):
            self.pos_att_type = tuple(
                x.strip() for x in self.pos_att_type.split("|") if x)

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def pos_ebd_size(self) -> int:
        return self.position_buckets if self.position_buckets > 0 \
            else self.max_relative_positions

    @classmethod
    def from_pretrained(cls, path: str) -> "DebertaV2Config":
        cfg_file = os.path.join(path, "config.json") if os.path.isdir(path) \
            else path
        with open(cfg_file) as f:
            raw = json.load(f)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in raw.items() if k in known})

    def save_pretrained(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "config.json"), "w") as f:
            json.dump(dataclasses.asdict(self) |
                      {"model_type": "deberta-v2"}, f, indent=2)

    @classmethod
    def small_test_config(cls, **overrides: Any) -> "DebertaV2Config":
        base = dict(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=64,
                    max_position_embeddings=64, position_buckets=8)
        base.update(overrides)
        return cls(**base)


def _dt(cfg):
    return jnp.dtype(cfg.dtype)


def _dense(cfg, feats, name):
    return nn.Dense(feats, dtype=_dt(cfg),
                    param_dtype=jnp.dtype(cfg.param_dtype),
                    kernel_init=nn.initializers.normal(
                        cfg.initializer_range), name=name)


def make_log_bucket_position(relative_pos, bucket_size: int,
                             max_position: int):
    """Exact port of HF's torchscript make_log_bucket_position."""
    sign = jnp.sign(relative_pos)
    mid = bucket_size // 2
    inside = (relative_pos < mid) & (relative_pos > -mid)
    abs_pos = jnp.where(inside, mid - 1, jnp.abs(relative_pos)
                        ).astype(jnp.float32)
    log_pos = jnp.ceil(
        jnp.log(abs_pos / mid) /
        np.log((max_position - 1) / mid) * (mid - 1)) + mid
    bucket_pos = jnp.where(abs_pos <= mid,
                           relative_pos.astype(jnp.float32),
                           log_pos * sign)
    return bucket_pos.astype(jnp.int32)


def build_relative_position(q_len: int, k_len: int, bucket_size: int,
                            max_position: int):
    rel = jnp.arange(q_len)[:, None] - jnp.arange(k_len)[None, :]
    if bucket_size > 0 and max_position > 0:
        rel = make_log_bucket_position(rel, bucket_size, max_position)
    return rel.astype(jnp.int32)  # [q, k]


class DisentangledSelfAttention(nn.Module):
    config: DebertaV2Config

    @nn.compact
    def __call__(self, hidden, attention_mask, rel_embeddings,
                 relative_pos, deterministic=True):
        cfg = self.config
        batch, seq, _ = hidden.shape
        n_head, head_dim = cfg.num_attention_heads, cfg.head_dim

        q_proj = _dense(cfg, cfg.hidden_size, "query_proj")
        k_proj = _dense(cfg, cfg.hidden_size, "key_proj")
        v_proj = _dense(cfg, cfg.hidden_size, "value_proj")
        q = q_proj(hidden).reshape(batch, seq, n_head, head_dim)
        k = k_proj(hidden).reshape(batch, seq, n_head, head_dim)
        v = v_proj(hidden).reshape(batch, seq, n_head, head_dim)

        scale_factor = 1 + len(cfg.pos_att_type)
        scale = jnp.sqrt(jnp.asarray(head_dim * scale_factor, jnp.float32))
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                            preferred_element_type=jnp.float32) / scale

        if cfg.relative_attention:
            att_span = cfg.pos_ebd_size
            rel_emb = rel_embeddings[: att_span * 2]  # [2*span, H]
            if cfg.share_att_key:
                pos_q = q_proj(rel_emb).reshape(-1, n_head, head_dim)
                pos_k = k_proj(rel_emb).reshape(-1, n_head, head_dim)
            else:
                pos_q = _dense(cfg, cfg.hidden_size, "pos_query_proj")(
                    rel_emb).reshape(-1, n_head, head_dim)
                pos_k = _dense(cfg, cfg.hidden_size, "pos_key_proj")(
                    rel_emb).reshape(-1, n_head, head_dim)

            if "c2p" in cfg.pos_att_type:
                c2p = jnp.einsum("bqhd,phd->bhqp", q, pos_k,
                                 preferred_element_type=jnp.float32)
                c2p_pos = jnp.clip(relative_pos + att_span, 0,
                                   att_span * 2 - 1)  # [q, k]
                gathered = jnp.take_along_axis(
                    c2p, jnp.broadcast_to(
                        c2p_pos[None, None], (batch, n_head) +
                        c2p_pos.shape), axis=-1)
                scores = scores + gathered / scale
            if "p2c" in cfg.pos_att_type:
                p2c = jnp.einsum("bkhd,phd->bhkp", k, pos_q,
                                 preferred_element_type=jnp.float32)
                p2c_pos = jnp.clip(-relative_pos + att_span, 0,
                                   att_span * 2 - 1)  # [q, k] (k as rows
                # after transpose below)
                gathered = jnp.take_along_axis(
                    p2c, jnp.broadcast_to(
                        p2c_pos[None, None], (batch, n_head) +
                        p2c_pos.shape), axis=-1)
                scores = scores + gathered.transpose(0, 1, 3, 2) / scale

        if attention_mask is not None:
            scores = jnp.where(
                attention_mask[:, None, None, :].astype(bool), scores,
                jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(scores, axis=-1)
        if not deterministic and cfg.attention_probs_dropout_prob > 0:
            keep = jax.random.bernoulli(
                self.make_rng("dropout"),
                1.0 - cfg.attention_probs_dropout_prob, probs.shape)
            probs = jnp.where(
                keep, probs / (1.0 - cfg.attention_probs_dropout_prob), 0.0)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
        return out.reshape(batch, seq, cfg.hidden_size)


class DebertaV2Layer(nn.Module):
    config: DebertaV2Config

    @nn.compact
    def __call__(self, hidden, attention_mask, rel_embeddings, relative_pos,
                 deterministic=True):
        cfg = self.config
        h = DisentangledSelfAttention(cfg, name="self")(
            hidden, attention_mask, rel_embeddings, relative_pos,
            deterministic)
        h = _dense(cfg, cfg.hidden_size, "attention_output_dense")(h)
        h = nn.Dropout(cfg.hidden_dropout_prob)(h,
                                                deterministic=deterministic)
        hidden = LayerNorm(epsilon=cfg.layer_norm_eps,
                           name="attention_ln")(hidden + h)
        h = _dense(cfg, cfg.intermediate_size, "intermediate_dense")(hidden)
        h = get_activation(cfg.hidden_act)(h)
        h = with_logical_constraint(h, ("batch", "seq", "mlp"))
        h = _dense(cfg, cfg.hidden_size, "output_dense")(h)
        h = nn.Dropout(cfg.hidden_dropout_prob)(h,
                                                deterministic=deterministic)
        return LayerNorm(epsilon=cfg.layer_norm_eps,
                         name="output_ln")(hidden + h)


class DebertaV2Model(nn.Module):
    config: DebertaV2Config

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic=True):
        cfg = self.config
        batch, seq = input_ids.shape
        if attention_mask is None:
            attention_mask = jnp.ones((batch, seq), jnp.int32)
        hidden = VocabParallelEmbed(
            cfg.vocab_size, cfg.hidden_size, dtype=_dt(cfg),
            param_dtype=jnp.dtype(cfg.param_dtype),
            embedding_init=nn.initializers.normal(cfg.initializer_range),
            name="word_embeddings")(input_ids)
        if cfg.position_biased_input:
            pos = jnp.arange(seq)[None]
            hidden = hidden + nn.Embed(
                cfg.max_position_embeddings, cfg.hidden_size,
                dtype=_dt(cfg), param_dtype=jnp.dtype(cfg.param_dtype),
                embedding_init=nn.initializers.normal(
                    cfg.initializer_range),
                name="position_embeddings")(pos)
        hidden = LayerNorm(epsilon=cfg.layer_norm_eps,
                           name="embeddings_ln")(hidden)
        # HF masks embeddings by the input mask
        hidden = hidden * attention_mask[..., None].astype(hidden.dtype)
        hidden = nn.Dropout(cfg.hidden_dropout_prob)(
            hidden, deterministic=deterministic)

        rel_embeddings = None
        relative_pos = None
        if cfg.relative_attention:
            rel_embeddings = self.param(
                "rel_embeddings", nn.initializers.normal(
                    cfg.initializer_range),
                (cfg.pos_ebd_size * 2, cfg.hidden_size),
                jnp.dtype(cfg.param_dtype))
            if "layer_norm" in cfg.norm_rel_ebd:
                rel_embeddings = LayerNorm(
                    epsilon=cfg.layer_norm_eps, name="rel_embeddings_ln")(
                    rel_embeddings)
            relative_pos = build_relative_position(
                seq, seq, cfg.position_buckets, cfg.max_relative_positions)

        conv_out = None
        for i in range(cfg.num_hidden_layers):
            prev = hidden
            hidden = DebertaV2Layer(cfg, name=f"layer_{i}")(
                hidden, attention_mask, rel_embeddings, relative_pos,
                deterministic)
            if i == 0 and cfg.conv_kernel_size > 0:
                conv = nn.Conv(
                    cfg.hidden_size, (cfg.conv_kernel_size,),
                    padding="SAME", feature_group_count=1,
                    dtype=_dt(cfg), param_dtype=jnp.dtype(cfg.param_dtype),
                    name="conv")(prev)
                conv = conv * attention_mask[..., None].astype(conv.dtype)
                conv = get_activation(cfg.conv_act)(
                    nn.Dropout(cfg.hidden_dropout_prob)(
                        conv, deterministic=deterministic))
                hidden = LayerNorm(epsilon=cfg.layer_norm_eps,
                                   name="conv_ln")(hidden + conv)
                hidden = hidden * attention_mask[..., None].astype(
                    hidden.dtype)
        return hidden

    def partition_rules(self):
        return to_partition_rules(PARAM_LOGICAL_AXES)


class DebertaV2ForMaskedLM(nn.Module):
    config: DebertaV2Config

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic=True):
        cfg = self.config
        hidden = DebertaV2Model(cfg, name="deberta")(
            input_ids, attention_mask, token_type_ids, deterministic)
        h = _dense(cfg, cfg.hidden_size, "transform_dense")(hidden)
        h = get_activation(cfg.hidden_act)(h)
        h = LayerNorm(epsilon=cfg.layer_norm_eps, name="transform_ln")(h)
        wte = self.variables["params"]["deberta"]["word_embeddings"][
            "embedding"]
        logits = h @ wte.T.astype(h.dtype)
        bias = self.param("bias", nn.initializers.zeros,
                          (cfg.vocab_size,), jnp.dtype(cfg.param_dtype))
        return logits + bias

    def partition_rules(self):
        return to_partition_rules(PARAM_LOGICAL_AXES)


class DebertaV2ForSequenceClassification(nn.Module):
    config: DebertaV2Config

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic=True):
        cfg = self.config
        hidden = DebertaV2Model(cfg, name="deberta")(
            input_ids, attention_mask, token_type_ids, deterministic)
        # ContextPooler: dense+tanh over [CLS] with dropout
        pooled = nn.Dropout(cfg.hidden_dropout_prob)(
            hidden[:, 0], deterministic=deterministic)
        pooled = jnp.tanh(_dense(cfg, cfg.pooler_hidden_size,
                                 "pooler_dense")(pooled))
        pooled = nn.Dropout(cfg.hidden_dropout_prob)(
            pooled, deterministic=deterministic)
        return _dense(cfg, cfg.num_labels, "classifier")(pooled)

    def partition_rules(self):
        return to_partition_rules(PARAM_LOGICAL_AXES)
