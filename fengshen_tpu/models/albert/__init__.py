"""ALBERT family (reference: fengshen/models/albert/, 1,363 LoC)."""

from fengshen_tpu.models.albert.modeling_albert import (
    AlbertConfig, AlbertModel, AlbertForMaskedLM,
    AlbertForSequenceClassification)

__all__ = ["AlbertConfig", "AlbertModel", "AlbertForMaskedLM",
           "AlbertForSequenceClassification"]

from fengshen_tpu.models.albert.task_heads import (AlbertForTokenClassification, AlbertForQuestionAnswering, AlbertForMultipleChoice)
__all__ += ['AlbertForTokenClassification', 'AlbertForQuestionAnswering', 'AlbertForMultipleChoice']
