"""ALBERT in flax, HF-weight-compatible.

Reference: fengshen/models/albert/. ALBERT = BERT with (1) factorized
embeddings (embedding_size < hidden_size, projected up), (2) ONE shared
transformer layer applied num_hidden_layers times — which on TPU means the
natural implementation is `lax.scan` over a zero-parameter-growth body:
cross-layer sharing is just a scan whose params are broadcast.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from fengshen_tpu.ops.activations import get_activation
from fengshen_tpu.ops.embedding import VocabParallelEmbed
from fengshen_tpu.ops.attention import dot_product_attention
from fengshen_tpu.ops.norms import LayerNorm
from fengshen_tpu.sharding import (to_partition_rules,
                                    with_logical_constraint)

PARAM_LOGICAL_AXES: list[tuple[str, tuple]] = [
    ("word_embeddings/embedding", ("vocab", None)),
    (r"(query|key|value)/kernel", ("embed", "heads")),
    (r"ffn/kernel", ("embed", "mlp")),
    (r"attention_dense/kernel", ("heads", "embed")),
    (r"ffn_output/kernel", ("mlp", "embed")),
    (".*", (None,)),
]
PARTITION_RULES = to_partition_rules(PARAM_LOGICAL_AXES)


@dataclasses.dataclass
class AlbertConfig:
    vocab_size: int = 30000
    embedding_size: int = 128
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_hidden_groups: int = 1
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    inner_group_num: int = 1
    hidden_act: str = "gelu_new"
    hidden_dropout_prob: float = 0.0
    attention_probs_dropout_prob: float = 0.0
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    pad_token_id: int = 0
    num_labels: int = 2
    dtype: str = "bfloat16"
    param_dtype: str = "float32"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def from_pretrained(cls, path: str) -> "AlbertConfig":
        cfg_file = os.path.join(path, "config.json") if os.path.isdir(path) \
            else path
        with open(cfg_file) as f:
            raw = json.load(f)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in raw.items() if k in known})

    @classmethod
    def small_test_config(cls, **overrides: Any) -> "AlbertConfig":
        base = dict(vocab_size=128, embedding_size=16, hidden_size=32,
                    num_hidden_layers=3, num_attention_heads=4,
                    intermediate_size=64, max_position_embeddings=64)
        base.update(overrides)
        return cls(**base)


def _dt(cfg):
    return jnp.dtype(cfg.dtype)


def _dense(cfg, feats, name):
    return nn.Dense(feats, dtype=_dt(cfg),
                    param_dtype=jnp.dtype(cfg.param_dtype),
                    kernel_init=nn.initializers.normal(
                        cfg.initializer_range), name=name)


class AlbertLayer(nn.Module):
    config: AlbertConfig

    @nn.compact
    def __call__(self, hidden, attention_mask=None, deterministic=True):
        cfg = self.config
        batch, seq, _ = hidden.shape
        n_head, head_dim = cfg.num_attention_heads, cfg.head_dim
        q = _dense(cfg, cfg.hidden_size, "query")(hidden)
        k = _dense(cfg, cfg.hidden_size, "key")(hidden)
        v = _dense(cfg, cfg.hidden_size, "value")(hidden)
        q = q.reshape(batch, seq, n_head, head_dim)
        k = k.reshape(batch, seq, n_head, head_dim)
        v = v.reshape(batch, seq, n_head, head_dim)
        mask = None
        if attention_mask is not None:
            mask = attention_mask[:, None, None, :].astype(bool)
        drop_rng = None
        if not deterministic and cfg.attention_probs_dropout_prob > 0:
            drop_rng = self.make_rng("dropout")
        out = dot_product_attention(
            q, k, v, mask=mask, dropout_rng=drop_rng,
            dropout_rate=cfg.attention_probs_dropout_prob,
            deterministic=deterministic)
        out = with_logical_constraint(
            out, ("batch", "seq", "heads", None))
        out = out.reshape(batch, seq, cfg.hidden_size)
        out = _dense(cfg, cfg.hidden_size, "attention_dense")(out)
        out = nn.Dropout(cfg.hidden_dropout_prob)(
            out, deterministic=deterministic)
        hidden = LayerNorm(epsilon=cfg.layer_norm_eps,
                           name="attention_ln")(hidden + out)

        h = _dense(cfg, cfg.intermediate_size, "ffn")(hidden)
        h = get_activation(cfg.hidden_act)(h)
        h = with_logical_constraint(h, ("batch", "seq", "mlp"))
        h = _dense(cfg, cfg.hidden_size, "ffn_output")(h)
        h = nn.Dropout(cfg.hidden_dropout_prob)(h,
                                                deterministic=deterministic)
        return LayerNorm(epsilon=cfg.layer_norm_eps,
                         name="full_layer_ln")(hidden + h)


class AlbertModel(nn.Module):
    config: AlbertConfig
    add_pooling_layer: bool = True

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 position_ids=None, deterministic=True):
        cfg = self.config
        batch, seq = input_ids.shape
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        if position_ids is None:
            position_ids = jnp.arange(seq)[None, :]
        embed = lambda n, name, cls=nn.Embed: cls(  # noqa: E731
            n, cfg.embedding_size, dtype=_dt(cfg),
            param_dtype=jnp.dtype(cfg.param_dtype),
            embedding_init=nn.initializers.normal(cfg.initializer_range),
            name=name)
        hidden = embed(cfg.vocab_size, "word_embeddings",
                       VocabParallelEmbed)(input_ids) + \
            embed(cfg.max_position_embeddings,
                  "position_embeddings")(position_ids) + \
            embed(cfg.type_vocab_size,
                  "token_type_embeddings")(token_type_ids)
        hidden = LayerNorm(epsilon=cfg.layer_norm_eps,
                           name="embeddings_ln")(hidden)
        hidden = nn.Dropout(cfg.hidden_dropout_prob)(
            hidden, deterministic=deterministic)
        hidden = _dense(cfg, cfg.hidden_size,
                        "embedding_hidden_mapping_in")(hidden)

        # ONE layer's params, applied num_hidden_layers times (cross-layer
        # sharing); groups>1 would add more layer instances
        layer = AlbertLayer(cfg, name="albert_layer")
        for _ in range(cfg.num_hidden_layers):
            hidden = layer(hidden, attention_mask, deterministic)

        pooled = None
        if self.add_pooling_layer:
            pooled = jnp.tanh(_dense(cfg, cfg.hidden_size,
                                     "pooler")(hidden[:, 0]))
        return hidden, pooled

    def partition_rules(self):
        return to_partition_rules(PARAM_LOGICAL_AXES)


class AlbertForMaskedLM(nn.Module):
    config: AlbertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic=True):
        cfg = self.config
        hidden, _ = AlbertModel(cfg, add_pooling_layer=False,
                                name="albert")(
            input_ids, attention_mask, token_type_ids,
            deterministic=deterministic)
        h = _dense(cfg, cfg.embedding_size, "predictions_dense")(hidden)
        h = get_activation(cfg.hidden_act)(h)
        h = LayerNorm(epsilon=cfg.layer_norm_eps, name="predictions_ln")(h)
        wte = self.variables["params"]["albert"]["word_embeddings"][
            "embedding"]
        logits = h @ wte.T.astype(h.dtype)
        bias = self.param("bias", nn.initializers.zeros,
                          (cfg.vocab_size,), jnp.dtype(cfg.param_dtype))
        return logits + bias

    def partition_rules(self):
        return to_partition_rules(PARAM_LOGICAL_AXES)


class AlbertForSequenceClassification(nn.Module):
    config: AlbertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic=True):
        cfg = self.config
        _, pooled = AlbertModel(cfg, name="albert")(
            input_ids, attention_mask, token_type_ids,
            deterministic=deterministic)
        pooled = nn.Dropout(cfg.hidden_dropout_prob)(
            pooled, deterministic=deterministic)
        return _dense(cfg, cfg.num_labels, "classifier")(pooled)

    def partition_rules(self):
        return to_partition_rules(PARAM_LOGICAL_AXES)
