"""torch(HF) → jax weights for ALBERT."""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from fengshen_tpu.utils.convert_common import tensor as _tensor

from fengshen_tpu.models.albert.modeling_albert import AlbertConfig


def torch_to_params(state_dict: Mapping[str, Any],
                    config: AlbertConfig) -> dict:
    def t(name):
        return _tensor(state_dict, name)

    def lin(prefix):
        return {"kernel": t(f"{prefix}.weight").T,
                "bias": t(f"{prefix}.bias")}

    def ln(prefix):
        return {"scale": t(f"{prefix}.weight"), "bias": t(f"{prefix}.bias")}

    g = "albert.encoder.albert_layer_groups.0.albert_layers.0"
    params: dict = {
        "word_embeddings": {
            "embedding": t("albert.embeddings.word_embeddings.weight")},
        "position_embeddings": {
            "embedding": t("albert.embeddings.position_embeddings.weight")},
        "token_type_embeddings": {
            "embedding":
                t("albert.embeddings.token_type_embeddings.weight")},
        "embeddings_ln": ln("albert.embeddings.LayerNorm"),
        "embedding_hidden_mapping_in": lin(
            "albert.encoder.embedding_hidden_mapping_in"),
        "albert_layer": {
            "query": lin(f"{g}.attention.query"),
            "key": lin(f"{g}.attention.key"),
            "value": lin(f"{g}.attention.value"),
            "attention_dense": lin(f"{g}.attention.dense"),
            "attention_ln": ln(f"{g}.attention.LayerNorm"),
            "ffn": lin(f"{g}.ffn"),
            "ffn_output": lin(f"{g}.ffn_output"),
            "full_layer_ln": ln(f"{g}.full_layer_layer_norm"),
        },
    }
    if "albert.pooler.weight" in state_dict:
        params["pooler"] = {"kernel": t("albert.pooler.weight").T,
                            "bias": t("albert.pooler.bias")}
    return params


#: fs→torch export: derived exact inverse of `torch_to_params`
#: (template_state = the source checkpoint: dict, Lightning ckpt, or dir)
from fengshen_tpu.utils.convert_common import (  # noqa: E402
    make_derived_export)

params_to_torch_state = make_derived_export(torch_to_params)
