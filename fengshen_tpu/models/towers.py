"""Encoder-tower dispatch for the task-head families.

The reference task heads are built over either a plain HF BertModel
(ubert, uniex, tagging: e.g. fengshen/models/ubert/modeling_ubert.py
`self.bert = BertModel(config)`) or MegatronBert (unimc/tcbert 1.3B:
fengshen/models/unimc/modeling_unimc.py:297-308). The flax heads take a
`backbone_type` field so published checkpoints of either architecture
import faithfully.
"""

from __future__ import annotations


def encoder_tower(config, backbone_type: str, name: str = "bert",
                  add_pooling_layer: bool = False):
    """Instantiate the encoder module (returns (hidden, pooled))."""
    if backbone_type == "bert":
        from fengshen_tpu.models.bert.modeling_bert import BertModel
        return BertModel(config, add_pooling_layer=add_pooling_layer,
                         name=name)
    from fengshen_tpu.models.megatron_bert import MegatronBertModel
    return MegatronBertModel(config, add_pooling_layer=add_pooling_layer,
                             name=name)


def mlm_tower(config, backbone_type: str, name: str = "backbone"):
    """Instantiate the MaskedLM module (returns vocab logits)."""
    if backbone_type == "bert":
        from fengshen_tpu.models.bert.modeling_bert import BertForMaskedLM
        return BertForMaskedLM(config, name=name)
    from fengshen_tpu.models.megatron_bert import MegatronBertForMaskedLM
    return MegatronBertForMaskedLM(config, name=name)


def gelu_exact(x):
    """erf-form GELU — the reference heads use torch.nn.GELU(), not the
    tanh approximation jax.nn.gelu defaults to."""
    import jax
    return jax.nn.gelu(x, approximate=False)
