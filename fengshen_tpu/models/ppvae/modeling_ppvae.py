"""PPVAE — plug-in conditional VAE over a frozen text-VAE latent space.

Behavioural port of reference: fengshen/models/PPVAE/pluginVAE.py (232
LoC): a small bottleneck VAE (Encoder fc1→fc2→mean/log_var, Decoder
fc1→fc2→fc3, leaky-relu, :13-58) trained ONLY on latents of
condition-positive texts (optionally pushed away from negative-sample
latents with weight gamma, :119-149); generation decodes bottleneck noise
back to the big latent space and then to text through the frozen DAVAE.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn

from fengshen_tpu.models.davae.modeling_davae import (
    DAVAEConfig, DAVAEModel, text_from_latent_code_batch)


@dataclasses.dataclass
class PPVAEConfig:
    latent_dim: int = 128
    bottle_dim: int = 20
    kl_weight: float = 1.0
    beta: float = 0.0          # free-bits style |kl - beta| target
    gamma: float = 1.0         # negative-sample repulsion weight
    neg_loss_threshold: float = 10.0
    ppvae_lr: float = 1e-3
    vae: DAVAEConfig = None

    @classmethod
    def small_test_config(cls, **overrides: Any) -> "PPVAEConfig":
        vae = DAVAEConfig.small_test_config()
        base = dict(latent_dim=vae.latent_size, bottle_dim=4, vae=vae)
        base.update(overrides)
        return cls(**base)


class PluginVAE(nn.Module):
    """The bottleneck VAE over latents (reference: pluginVAE.py:13-78)."""

    latent_dim: int = 128
    bottle_dim: int = 20

    def setup(self):
        half, quarter = self.latent_dim // 2, self.latent_dim // 4
        self.enc_fc1 = nn.Dense(half, name="enc_fc1")
        self.enc_fc2 = nn.Dense(quarter, name="enc_fc2")
        self.mean = nn.Dense(self.bottle_dim, name="mean")
        self.log_var = nn.Dense(self.bottle_dim, name="log_var")
        self.dec_fc1 = nn.Dense(quarter, name="dec_fc1")
        self.dec_fc2 = nn.Dense(half, name="dec_fc2")
        self.dec_fc3 = nn.Dense(self.latent_dim, name="dec_fc3")

    def encode(self, z):
        h = jax.nn.leaky_relu(self.enc_fc1(z))
        h = jax.nn.leaky_relu(self.enc_fc2(h))
        return self.mean(h), self.log_var(h)

    def decode(self, enc_z):
        h = jax.nn.leaky_relu(self.dec_fc1(enc_z))
        h = jax.nn.leaky_relu(self.dec_fc2(h))
        return self.dec_fc3(h)

    def __call__(self, z, rng=None):
        mean, log_var = self.encode(z)
        kl = (-0.5 * (1 + log_var - mean ** 2 -
                      jnp.exp(log_var)).sum(-1)).mean()
        enc_z = mean if rng is None else \
            mean + jnp.exp(0.5 * log_var) * jax.random.normal(rng,
                                                              mean.shape)
        return self.decode(enc_z), kl


def plugin_loss(model: PluginVAE, params, z, rng, kl_weight: float,
                beta: float):
    """z-space reconstruction + |KL − beta| (reference:
    pluginVAE.py:75-78)."""
    z_out, kl = model.apply({"params": params}, z, rng=rng)
    z_loss = ((z_out - z) ** 2).mean()
    return z_loss + kl_weight * jnp.abs(kl - beta), kl


class PPVAEModel:
    """train_plugin / generate surface (reference: pluginVAE.py:86-180)."""

    def __init__(self, config: PPVAEConfig,
                 vae_model: Optional[DAVAEModel] = None, vae_params=None):
        self.config = config
        self.vae_model = vae_model or DAVAEModel(config.vae)
        self.vae_params = vae_params
        self.plugin = PluginVAE(config.latent_dim, config.bottle_dim)
        self.params = None

    def train_plugin(self, pos_latents, neg_latents=None,
                     steps: int = 200, seed: int = 0):
        """Train on condition-positive latents, repelled from negatives
        (reference: pluginVAE.py:119-149 `loss = pos - gamma*neg` with the
        runaway-negative detach)."""
        cfg = self.config
        rng = jax.random.PRNGKey(seed)
        rng, init_key = jax.random.split(rng)
        self.params = self.plugin.init(
            init_key, jnp.zeros((1, cfg.latent_dim)))["params"]
        tx = optax.adam(cfg.ppvae_lr)
        opt = tx.init(self.params)

        @jax.jit
        def one_step(params, opt, rng):
            rng, k_pos, k_neg = jax.random.split(rng, 3)

            def loss_fn(p):
                pos_loss, pos_kl = plugin_loss(self.plugin, p, pos_latents,
                                               k_pos, cfg.kl_weight,
                                               cfg.beta)
                if neg_latents is None:
                    return pos_loss, (pos_loss, pos_kl, 0.0)
                neg_loss, _ = plugin_loss(self.plugin, p, neg_latents,
                                          k_neg, cfg.kl_weight, cfg.beta)
                # a runaway negative term is detached (reference :138-141)
                neg_loss = jnp.where(
                    neg_loss > cfg.neg_loss_threshold * pos_loss,
                    jax.lax.stop_gradient(neg_loss), neg_loss)
                return pos_loss - cfg.gamma * neg_loss, \
                    (pos_loss, pos_kl, neg_loss)

            (loss, aux), grads = jax.value_and_grad(loss_fn,
                                                    has_aux=True)(params)
            upd, opt = tx.update(grads, opt, params)
            return optax.apply_updates(params, upd), opt, rng, loss, aux

        loss = aux = None
        for _ in range(steps):
            self.params, opt, rng, loss, aux = one_step(self.params, opt,
                                                        rng)
        return float(loss), {"pos_loss": float(aux[0]),
                             "pos_kl": float(aux[1]),
                             "neg_loss": float(aux[2])}

    def gen_latent(self, n: int, seed: int = 0):
        """bottleneck noise → big latent (reference: pluginVAE.py:168-172)."""
        rng = jax.random.PRNGKey(seed)
        z = jax.random.normal(rng, (n, self.config.bottle_dim))
        return self.plugin.apply({"params": self.params}, z,
                                 method=PluginVAE.decode)

    def generate(self, n: int, seed: int = 0, max_length: int = 32,
                 bos_id: int = 0):
        assert self.vae_params is not None, "needs trained DAVAE params"
        latents = self.gen_latent(n, seed)
        return text_from_latent_code_batch(self.vae_model, self.vae_params,
                                           latents, max_length=max_length,
                                           bos_id=bos_id)
