"""PPVAE family (reference: fengshen/models/PPVAE/, 232 LoC)."""

from fengshen_tpu.models.ppvae.modeling_ppvae import (
    PPVAEConfig, PPVAEModel, PluginVAE, plugin_loss)

__all__ = ["PPVAEConfig", "PPVAEModel", "PluginVAE", "plugin_loss"]
