"""torch → jax weights for PPVAE (plug-in bottleneck VAE).

Reference state-dict naming (fengshen/models/PPVAE/pluginVAE.py:86-92):
`pluginvae.encoder.{fc1,fc2,mean,log_var}` +
`pluginvae.decoder.{fc1,fc2,fc3}` over the frozen DAVAE
(`vae_model.*`, imported separately via davae.convert).
"""

from __future__ import annotations

from typing import Any, Mapping

from fengshen_tpu.utils.convert_common import (make_helpers, strip_prefix,
                                               unwrap_lightning)


def torch_to_params(state_dict: Mapping[str, Any]) -> dict:
    """Returns the PluginVAE param tree (enc_fc1/enc_fc2/mean/log_var/
    dec_fc1..3)."""
    sd = unwrap_lightning(state_dict)
    if any(k.startswith("pluginvae.") for k in sd):
        sd = strip_prefix(sd, "pluginvae.")
    _, lin, _ = make_helpers(sd)
    return {
        "enc_fc1": lin("encoder.fc1"),
        "enc_fc2": lin("encoder.fc2"),
        "mean": lin("encoder.mean"),
        "log_var": lin("encoder.log_var"),
        "dec_fc1": lin("decoder.fc1"),
        "dec_fc2": lin("decoder.fc2"),
        "dec_fc3": lin("decoder.fc3"),
    }


#: fs→torch export: derived exact inverse of `torch_to_params`
#: (template_state = the source checkpoint: dict, Lightning ckpt, or dir)
from fengshen_tpu.utils.convert_common import (  # noqa: E402
    make_derived_export)

params_to_torch_state = make_derived_export(torch_to_params)
