"""T5 / Randeng family (reference: fengshen/models/megatron_t5/ — Randeng
encoder-decoder with Megatron-style LN placement, plus the HF-T5-based
examples pretrain_t5/qa_t5/mt5_summary)."""

from fengshen_tpu.models.t5.configuration_t5 import T5Config
from fengshen_tpu.models.t5.modeling_t5 import (T5Model,
                                                T5ForConditionalGeneration,
                                                T5EncoderModel)
from fengshen_tpu.models.t5.tokenization_megatron_t5 import T5Tokenizer

__all__ = ["T5Config", "T5Model", "T5ForConditionalGeneration",
           "T5EncoderModel", "T5Tokenizer"]
