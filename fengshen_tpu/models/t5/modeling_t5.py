"""T5 in flax, HF-weight-compatible.

Covers the reference's encoder-decoder tier: Randeng Megatron-T5
(reference: fengshen/models/megatron_t5/modeling_megatron_t5.py —
`T5Model/T5ForConditionalGeneration/T5EncoderModel/T5Stack`) and the
HF-T5-based pretrain/QA/summary examples. Semantics follow HF T5 exactly
(relative-position-bucket bias on the first layer, unscaled attention,
RMS-style T5LayerNorm, tied-embedding logit rescale) so torch checkpoints
import losslessly via convert.py.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from fengshen_tpu.models.t5.configuration_t5 import T5Config
from fengshen_tpu.ops.activations import get_activation
from fengshen_tpu.ops.embedding import VocabParallelEmbed
from fengshen_tpu.ops.masks import causal_mask
from fengshen_tpu.sharding import (to_partition_rules,
                                    with_logical_constraint)

PARAM_LOGICAL_AXES: list[tuple[str, tuple]] = [
    ("shared/embedding", ("vocab", "embed")),
    ("relative_attention_bias/embedding", ("relpos", None)),
    (r"(q|k|v)/kernel", ("embed", "heads")),
    (r"(wi|wi_0|wi_1)/kernel", ("embed", "mlp")),
    (r"wo/kernel", ("mlp", "embed")),
    (r"o/kernel", ("heads", "embed")),
    ("lm_head/kernel", ("embed", "vocab")),
    ("layer_norm", ("norm",)),
    (".*", (None,)),
]
PARTITION_RULES = to_partition_rules(PARAM_LOGICAL_AXES)


def _dt(config):
    return jnp.dtype(config.dtype)


class T5LayerNorm(nn.Module):
    """RMS norm without mean subtraction or bias (HF T5LayerNorm)."""

    epsilon: float = 1e-6

    @nn.compact
    def __call__(self, x):
        orig = x.dtype
        x32 = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        y = x32 * jax.lax.rsqrt(var + self.epsilon)
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],),
                           jnp.float32)
        return (y * scale).astype(orig)


def relative_position_bucket(relative_position, bidirectional: bool,
                             num_buckets: int, max_distance: int):
    """HF T5 bucket function (log-spaced beyond max_exact)."""
    ret = 0
    n = -relative_position
    if bidirectional:
        num_buckets //= 2
        ret += (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_if_large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-6) /
        np.log(max_distance / max_exact) * (num_buckets - max_exact)
    ).astype(jnp.int32)
    val_if_large = jnp.minimum(val_if_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_if_large)


class T5Attention(nn.Module):
    config: T5Config
    has_relative_bias: bool = False
    causal: bool = False

    @nn.compact
    def __call__(self, hidden, kv=None, mask=None, position_bias=None,
                 init_cache=False, cross_from_cache=False,
                 deterministic=True):
        cfg = self.config
        batch, q_len, _ = hidden.shape
        inner = cfg.num_heads * cfg.d_kv
        dense = lambda feats, name: nn.Dense(  # noqa: E731
            feats, use_bias=False, dtype=_dt(cfg),
            param_dtype=jnp.dtype(cfg.param_dtype),
            kernel_init=nn.initializers.normal(
                cfg.initializer_factor * (cfg.d_model ** -0.5)), name=name)
        q = dense(inner, "q")(hidden).reshape(batch, q_len, cfg.num_heads,
                                              cfg.d_kv)
        if kv is not None and (cross_from_cache or init_cache or
                               self.has_variable("cache", "cross_key")):
            # cross-attention K/V cache: the encoder projections are the
            # dominant per-step cost of cached decode (2·S_src·d² per
            # layer) — project ONCE on the priming call, then read.
            # `cross_from_cache` is STATIC so the projection matmuls are
            # absent from the scan-body trace entirely.
            shape = (batch, kv.shape[1], cfg.num_heads, cfg.d_kv)
            ck = self.variable("cache", "cross_key", jnp.zeros, shape,
                               _dt(cfg))
            cv = self.variable("cache", "cross_value", jnp.zeros, shape,
                               _dt(cfg))
            if cross_from_cache:
                k, v = ck.value, cv.value
            else:
                k = dense(inner, "k")(kv).reshape(shape)
                v = dense(inner, "v")(kv).reshape(shape)
                ck.value, cv.value = k, v
        else:
            kv_in = hidden if kv is None else kv
            k = dense(inner, "k")(kv_in).reshape(batch, kv_in.shape[1],
                                                 cfg.num_heads, cfg.d_kv)
            v = dense(inner, "v")(kv_in).reshape(batch, kv_in.shape[1],
                                                 cfg.num_heads, cfg.d_kv)

        use_cache = self.causal and kv is None and (
            self.has_variable("cache", "cached_key") or init_cache)
        cache_offset = 0
        if use_cache:
            k, v, cache_offset, decode_mask = self._update_cache(k, v)

        k_len = k.shape[1]
        if position_bias is None and self.has_relative_bias:
            rel_emb = nn.Embed(
                cfg.relative_attention_num_buckets, cfg.num_heads,
                dtype=jnp.float32,
                param_dtype=jnp.dtype(cfg.param_dtype),
                embedding_init=nn.initializers.normal(
                    cfg.initializer_factor * (cfg.d_model ** -0.5)),
                name="relative_attention_bias")
            ctx = jnp.arange(k_len)[None, :] if not use_cache else \
                jnp.arange(k_len)[None, :]
            qpos = (cache_offset + jnp.arange(q_len))[:, None]
            rel = jnp.arange(k_len)[None, :] - qpos
            buckets = relative_position_bucket(
                rel, bidirectional=not self.causal,
                num_buckets=cfg.relative_attention_num_buckets,
                max_distance=cfg.relative_attention_max_distance)
            position_bias = rel_emb(buckets).transpose(2, 0, 1)[None]
        elif position_bias is None:
            position_bias = jnp.zeros((1, cfg.num_heads, q_len, k_len),
                                      jnp.float32)

        bias = position_bias.astype(jnp.float32)
        if use_cache:
            bias = bias + jnp.where(decode_mask[:, None], 0.0, -1e9)
        elif self.causal:
            bias = bias + jnp.where(causal_mask(q_len, k_len)[None, None],
                                    0.0, -1e9)
        if mask is not None:
            bias = bias + jnp.where(mask[:, None, None, :].astype(bool),
                                    0.0, -1e9)

        # T5 attention is UNSCALED (the 1/sqrt(d) is folded into init)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                            preferred_element_type=jnp.float32) + bias
        probs = jax.nn.softmax(scores, axis=-1)
        if not deterministic and cfg.dropout_rate > 0.0:
            keep = jax.random.bernoulli(self.make_rng("dropout"),
                                        1.0 - cfg.dropout_rate, probs.shape)
            probs = jnp.where(keep, probs / (1.0 - cfg.dropout_rate), 0.0)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
        out = out.reshape(batch, q_len, inner)
        return dense(cfg.d_model, "o")(out), position_bias

    def _update_cache(self, k, v):
        cfg = self.config
        batch, seq, n_heads, d_kv = k.shape
        max_len = getattr(cfg, "decode_cache_length", 512)
        is_initialized = self.has_variable("cache", "cached_key")
        cached_k = self.variable("cache", "cached_key", jnp.zeros,
                                 (batch, max_len, n_heads, d_kv), k.dtype)
        cached_v = self.variable("cache", "cached_value", jnp.zeros,
                                 (batch, max_len, n_heads, d_kv), v.dtype)
        cache_index = self.variable("cache", "cache_index",
                                    lambda: jnp.zeros((), jnp.int32))
        if not is_initialized:
            valid = jnp.broadcast_to(
                (jnp.arange(seq)[None, :] <= jnp.arange(seq)[:, None])[None],
                (batch, seq, seq))
            return k, v, 0, valid
        idx = cache_index.value
        k_all = jax.lax.dynamic_update_slice(cached_k.value, k,
                                             (0, idx, 0, 0))
        v_all = jax.lax.dynamic_update_slice(cached_v.value, v,
                                             (0, idx, 0, 0))
        cached_k.value, cached_v.value = k_all, v_all
        cache_index.value = idx + seq
        q_pos = idx + jnp.arange(seq)
        valid = jnp.arange(max_len)[None, :] <= q_pos[:, None]
        valid = jnp.broadcast_to(valid[None], (batch, seq, max_len))
        return k_all, v_all, idx, valid


class T5FF(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, hidden, deterministic=True):
        cfg = self.config
        dense = lambda feats, name: nn.Dense(  # noqa: E731
            feats, use_bias=False, dtype=_dt(cfg),
            param_dtype=jnp.dtype(cfg.param_dtype),
            kernel_init=nn.initializers.normal(
                cfg.initializer_factor * (cfg.d_model ** -0.5)), name=name)
        act = get_activation(cfg.dense_act_fn if cfg.dense_act_fn != "gelu"
                             else "gelu_new")
        if cfg.is_gated_act:
            h = act(dense(cfg.d_ff, "wi_0")(hidden)) * \
                dense(cfg.d_ff, "wi_1")(hidden)
        else:
            h = act(dense(cfg.d_ff, "wi")(hidden))
        h = with_logical_constraint(h, ("batch", "seq", "mlp"))
        h = nn.Dropout(cfg.dropout_rate)(h, deterministic=deterministic)
        return dense(cfg.d_model, "wo")(h)


class T5Block(nn.Module):
    config: T5Config
    causal: bool = False
    has_relative_bias: bool = False
    has_cross_attention: bool = False

    @nn.compact
    def __call__(self, hidden, mask=None, encoder_hidden=None,
                 encoder_mask=None, position_bias=None,
                 encdec_bias=None, init_cache=False,
                 cross_from_cache=False, deterministic=True):
        cfg = self.config
        drop = lambda x: nn.Dropout(cfg.dropout_rate)(  # noqa: E731
            x, deterministic=deterministic)
        h = T5LayerNorm(cfg.layer_norm_epsilon, name="ln_self")(hidden)
        h, position_bias = T5Attention(
            cfg, has_relative_bias=self.has_relative_bias,
            causal=self.causal, name="self_attention")(
            h, mask=mask, position_bias=position_bias,
            init_cache=init_cache, deterministic=deterministic)
        hidden = hidden + drop(h)
        if self.has_cross_attention:
            h = T5LayerNorm(cfg.layer_norm_epsilon, name="ln_cross")(hidden)
            h, encdec_bias = T5Attention(cfg, name="cross_attention")(
                h, kv=encoder_hidden, mask=encoder_mask,
                position_bias=encdec_bias, init_cache=init_cache,
                cross_from_cache=cross_from_cache,
                deterministic=deterministic)
            hidden = hidden + drop(h)
        h = T5LayerNorm(cfg.layer_norm_epsilon, name="ln_ff")(hidden)
        h = T5FF(cfg, name="ff")(h, deterministic)
        return hidden + drop(h), position_bias, encdec_bias


class T5Stack(nn.Module):
    """Encoder or decoder stack (reference: megatron_t5 `T5Stack`)."""

    config: T5Config
    causal: bool = False

    @nn.compact
    def __call__(self, hidden, mask=None, encoder_hidden=None,
                 encoder_mask=None, init_cache=False,
                 cross_from_cache=False, deterministic=True):
        cfg = self.config
        n_layers = cfg.num_decoder_layers if self.causal else cfg.num_layers
        hidden = nn.Dropout(cfg.dropout_rate)(hidden,
                                              deterministic=deterministic)
        position_bias = None
        encdec_bias = None
        for i in range(n_layers):
            block = T5Block(cfg, causal=self.causal,
                            has_relative_bias=(i == 0),
                            has_cross_attention=self.causal,
                            name=f"block_{i}")
            hidden, position_bias, encdec_bias = block(
                hidden, mask, encoder_hidden, encoder_mask, position_bias,
                encdec_bias, init_cache, cross_from_cache, deterministic)
        hidden = T5LayerNorm(cfg.layer_norm_epsilon,
                             name="final_layer_norm")(hidden)
        return nn.Dropout(cfg.dropout_rate)(hidden,
                                            deterministic=deterministic)


class T5Model(nn.Module):
    config: T5Config

    def setup(self):
        cfg = self.config
        self.shared = VocabParallelEmbed(
            cfg.vocab_size, cfg.d_model, dtype=_dt(cfg),
            param_dtype=jnp.dtype(cfg.param_dtype),
            embedding_init=nn.initializers.normal(cfg.initializer_factor),
            name="shared")
        self.encoder = T5Stack(cfg, causal=False, name="encoder")
        self.decoder = T5Stack(cfg, causal=True, name="decoder")

    def encode(self, input_ids, attention_mask=None, deterministic=True):
        return self.encoder(self.shared(input_ids), mask=attention_mask,
                            deterministic=deterministic)

    def decode(self, decoder_input_ids, encoder_hidden, attention_mask=None,
               decoder_attention_mask=None, init_cache=False,
               cross_from_cache=False, deterministic=True):
        return self.decoder(self.shared(decoder_input_ids),
                            mask=decoder_attention_mask,
                            encoder_hidden=encoder_hidden,
                            encoder_mask=attention_mask,
                            init_cache=init_cache,
                            cross_from_cache=cross_from_cache,
                            deterministic=deterministic)

    def __call__(self, input_ids, decoder_input_ids, attention_mask=None,
                 decoder_attention_mask=None, init_cache=False,
                 deterministic=True):
        enc = self.encode(input_ids, attention_mask, deterministic)
        dec = self.decode(decoder_input_ids, enc, attention_mask,
                          decoder_attention_mask, init_cache=init_cache,
                          deterministic=deterministic)
        return enc, dec


class T5ForConditionalGeneration(nn.Module):
    config: T5Config

    def setup(self):
        cfg = self.config
        self.model = T5Model(cfg, name="model")
        if not cfg.tie_word_embeddings:
            self.lm_head = nn.Dense(
                cfg.vocab_size, use_bias=False, dtype=_dt(cfg),
                param_dtype=jnp.dtype(cfg.param_dtype),
                kernel_init=nn.initializers.normal(cfg.initializer_factor),
                name="lm_head")

    def _logits(self, dec):
        cfg = self.config
        if cfg.tie_word_embeddings:
            # HF rescales by d_model^-0.5 when tied
            dec = dec * (cfg.d_model ** -0.5)
            emb = self.model.shared.embedding
            return dec @ emb.T.astype(dec.dtype)
        return self.lm_head(dec)

    def __call__(self, input_ids, decoder_input_ids, attention_mask=None,
                 decoder_attention_mask=None, init_cache=False,
                 deterministic=True):
        _, dec = self.model(input_ids, decoder_input_ids, attention_mask,
                            decoder_attention_mask, init_cache,
                            deterministic)
        return self._logits(dec)

    def encode(self, input_ids, attention_mask=None, deterministic=True):
        return self.model.encode(input_ids, attention_mask, deterministic)

    def decode_logits(self, decoder_input_ids, encoder_hidden,
                      attention_mask=None, init_cache=False,
                      cross_from_cache=False, deterministic=True):
        dec = self.model.decode(decoder_input_ids, encoder_hidden,
                                attention_mask, None, init_cache,
                                cross_from_cache, deterministic)
        return self._logits(dec)

    def partition_rules(self):
        return to_partition_rules(PARAM_LOGICAL_AXES)


class T5EncoderModel(nn.Module):
    config: T5Config

    def setup(self):
        self.model = T5Model(self.config, name="model")

    def __call__(self, input_ids, attention_mask=None, deterministic=True):
        return self.model.encode(input_ids, attention_mask, deterministic)

    def partition_rules(self):
        return to_partition_rules(PARAM_LOGICAL_AXES)
