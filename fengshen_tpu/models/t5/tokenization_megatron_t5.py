"""Randeng-T5-Char tokenizer: BertTokenizer-backed T5Tokenizer.

Port of reference: fengshen/models/megatron_t5/tokenization_megatron_t5.py
:20-32 — the char-level Randeng checkpoints (57M → 10B) ship a BERT
vocab.txt rather than a sentencepiece model; ``T5Tokenizer.from_pretrained``
returns a BertTokenizer carrying the T5 special surface: ``[BOS]``/``[EOS]``
plus 118 ``<extra_id_i>`` span-corruption sentinels as additional special
tokens.

Beyond the reference: bos/eos_token attributes are bound when the markers
exist in the vocab (the reference leaves them unset, which breaks
`tokenizer.eos_token_id`-driven collators), and `sentinel_token_ids`
exposes the extra-id range the span-corruption collator needs.
"""

from __future__ import annotations

from transformers import BertTokenizer

DEFAULT_EXTRA_ID_NUM = 118


class T5Tokenizer:
    """Factory matching the reference class shape: use
    ``T5Tokenizer.from_pretrained(vocab_path)``."""

    def __init__(self, extra_id_num: int = DEFAULT_EXTRA_ID_NUM):
        self.extra_id_num = extra_id_num

    @classmethod
    def from_pretrained(cls, vocab_path: str,
                        extra_id_num: int = DEFAULT_EXTRA_ID_NUM
                        ) -> BertTokenizer:
        special_tokens = ["[BOS]", "[EOS]"] + \
            [f"<extra_id_{i}>" for i in range(extra_id_num)]
        tokenizer = BertTokenizer.from_pretrained(
            vocab_path, additional_special_tokens=special_tokens)
        # bind the T5 special surface when the markers resolve (added
        # specials always resolve; [BOS]/[EOS] may also live in vocab.txt)
        unk = tokenizer.unk_token_id
        if tokenizer.convert_tokens_to_ids("[EOS]") != unk:
            tokenizer.eos_token = "[EOS]"
        if tokenizer.convert_tokens_to_ids("[BOS]") != unk:
            tokenizer.bos_token = "[BOS]"
        tokenizer.extra_id_num = extra_id_num
        tokenizer.sentinel_token_ids = [
            tokenizer.convert_tokens_to_ids(f"<extra_id_{i}>")
            for i in range(extra_id_num)]
        return tokenizer
