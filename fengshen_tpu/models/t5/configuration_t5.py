"""T5 config (HF-compatible field names)."""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Optional


@dataclasses.dataclass
class T5Config:
    vocab_size: int = 32128
    d_model: int = 512
    d_kv: int = 64
    d_ff: int = 2048
    num_layers: int = 6
    num_decoder_layers: Optional[int] = None
    num_heads: int = 8
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    dropout_rate: float = 0.1
    layer_norm_epsilon: float = 1e-6
    initializer_factor: float = 1.0
    feed_forward_proj: str = "relu"     # "relu" | "gated-gelu"
    tie_word_embeddings: bool = True
    pad_token_id: int = 0
    eos_token_id: int = 1
    decoder_start_token_id: int = 0
    # TPU-native knobs
    dtype: str = "bfloat16"
    param_dtype: str = "float32"
    gradient_checkpointing: bool = False
    decode_cache_length: int = 512  # KV-cache capacity for generation

    def __post_init__(self):
        if self.num_decoder_layers is None:
            self.num_decoder_layers = self.num_layers

    @property
    def is_gated_act(self) -> bool:
        return self.feed_forward_proj.startswith("gated-")

    @property
    def dense_act_fn(self) -> str:
        return self.feed_forward_proj.split("-")[-1]

    # aliases for shared utilities
    @property
    def hidden_size(self) -> int:
        return self.d_model

    @property
    def num_hidden_layers(self) -> int:
        return self.num_layers + (self.num_decoder_layers or 0)

    @property
    def intermediate_size(self) -> int:
        return self.d_ff

    @classmethod
    def from_pretrained(cls, path: str) -> "T5Config":
        cfg_file = os.path.join(path, "config.json") if os.path.isdir(path) \
            else path
        with open(cfg_file) as f:
            raw = json.load(f)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in raw.items() if k in known})

    def save_pretrained(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "config.json"), "w") as f:
            json.dump(dataclasses.asdict(self) | {"model_type": "t5"},
                      f, indent=2)

    @classmethod
    def small_test_config(cls, **overrides: Any) -> "T5Config":
        base = dict(vocab_size=128, d_model=32, d_kv=8, d_ff=64,
                    num_layers=2, num_heads=4)
        base.update(overrides)
        return cls(**base)
