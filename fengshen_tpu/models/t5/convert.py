"""torch(HF) → jax weights for T5."""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from fengshen_tpu.utils.convert_common import tensor as _tensor

from fengshen_tpu.models.t5.configuration_t5 import T5Config


def torch_to_params(state_dict: Mapping[str, Any], config: T5Config) -> dict:
    def t(name):
        return _tensor(state_dict, name)

    def lin(prefix):
        return {"kernel": t(f"{prefix}.weight").T}

    def stack_tree(side: str, n_layers: int, causal: bool) -> dict:
        out: dict = {"final_layer_norm": {
            "scale": t(f"{side}.final_layer_norm.weight")}}
        for i in range(n_layers):
            pre = f"{side}.block.{i}.layer"
            blk: dict = {
                "ln_self": {"scale": t(f"{pre}.0.layer_norm.weight")},
                "self_attention": {
                    proj: lin(f"{pre}.0.SelfAttention.{proj}")
                    for proj in ("q", "k", "v", "o")},
            }
            if i == 0:
                blk["self_attention"]["relative_attention_bias"] = {
                    "embedding":
                        t(f"{pre}.0.SelfAttention."
                          f"relative_attention_bias.weight")}
            ff_idx = 2 if causal else 1
            if causal:
                blk["ln_cross"] = {
                    "scale": t(f"{pre}.1.layer_norm.weight")}
                blk["cross_attention"] = {
                    proj: lin(f"{pre}.1.EncDecAttention.{proj}")
                    for proj in ("q", "k", "v", "o")}
            blk["ln_ff"] = {"scale": t(f"{pre}.{ff_idx}.layer_norm.weight")}
            ff = {}
            if config.is_gated_act:
                ff["wi_0"] = lin(f"{pre}.{ff_idx}.DenseReluDense.wi_0")
                ff["wi_1"] = lin(f"{pre}.{ff_idx}.DenseReluDense.wi_1")
            else:
                ff["wi"] = lin(f"{pre}.{ff_idx}.DenseReluDense.wi")
            ff["wo"] = lin(f"{pre}.{ff_idx}.DenseReluDense.wo")
            blk["ff"] = ff
            out[f"block_{i}"] = blk
        return out

    params: dict = {"model": {
        "shared": {"embedding": t("shared.weight")},
        "encoder": stack_tree("encoder", config.num_layers, causal=False),
        "decoder": stack_tree("decoder", config.num_decoder_layers,
                              causal=True),
    }}
    if not config.tie_word_embeddings and "lm_head.weight" in state_dict:
        params["lm_head"] = {"kernel": t("lm_head.weight").T}
    return params


def params_to_torch_state(params: Mapping[str, Any],
                          config: T5Config) -> dict:
    """Inverse of `torch_to_params`: flax params → HF
    T5ForConditionalGeneration state_dict (numpy values) — Randeng
    checkpoints trained here load straight into the torch ecosystem."""
    import numpy as np

    def arr(x):
        return np.asarray(x)

    def lin(prefix, tree, state):
        state[f"{prefix}.weight"] = arr(tree["kernel"]).T

    state: dict = {"shared.weight": arr(params["model"]["shared"]
                                        ["embedding"])}
    state["encoder.embed_tokens.weight"] = state["shared.weight"]
    state["decoder.embed_tokens.weight"] = state["shared.weight"]

    def emit_side(side: str, tree: dict, n_layers: int,
                  causal: bool) -> None:
        state[f"{side}.final_layer_norm.weight"] = arr(
            tree["final_layer_norm"]["scale"])
        for i in range(n_layers):
            blk = tree[f"block_{i}"]
            pre = f"{side}.block.{i}.layer"
            state[f"{pre}.0.layer_norm.weight"] = arr(
                blk["ln_self"]["scale"])
            for proj in ("q", "k", "v", "o"):
                lin(f"{pre}.0.SelfAttention.{proj}",
                    blk["self_attention"][proj], state)
            if i == 0:
                state[f"{pre}.0.SelfAttention.relative_attention_bias"
                      ".weight"] = arr(
                    blk["self_attention"]["relative_attention_bias"]
                    ["embedding"])
            ff_idx = 2 if causal else 1
            if causal:
                state[f"{pre}.1.layer_norm.weight"] = arr(
                    blk["ln_cross"]["scale"])
                for proj in ("q", "k", "v", "o"):
                    lin(f"{pre}.1.EncDecAttention.{proj}",
                        blk["cross_attention"][proj], state)
            state[f"{pre}.{ff_idx}.layer_norm.weight"] = arr(
                blk["ln_ff"]["scale"])
            ff = blk["ff"]
            if config.is_gated_act:
                lin(f"{pre}.{ff_idx}.DenseReluDense.wi_0", ff["wi_0"],
                    state)
                lin(f"{pre}.{ff_idx}.DenseReluDense.wi_1", ff["wi_1"],
                    state)
            else:
                lin(f"{pre}.{ff_idx}.DenseReluDense.wi", ff["wi"], state)
            lin(f"{pre}.{ff_idx}.DenseReluDense.wo", ff["wo"], state)

    emit_side("encoder", params["model"]["encoder"], config.num_layers,
              causal=False)
    emit_side("decoder", params["model"]["decoder"],
              config.num_decoder_layers, causal=True)
    if "lm_head" in params:
        state["lm_head.weight"] = arr(params["lm_head"]["kernel"]).T
    elif config.tie_word_embeddings:
        state["lm_head.weight"] = state["shared.weight"]
    return state
