"""torch(HF) → jax weights for T5."""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from fengshen_tpu.utils.convert_common import tensor as _tensor

from fengshen_tpu.models.t5.configuration_t5 import T5Config


def torch_to_params(state_dict: Mapping[str, Any], config: T5Config) -> dict:
    def t(name):
        return _tensor(state_dict, name)

    def lin(prefix):
        return {"kernel": t(f"{prefix}.weight").T}

    def stack_tree(side: str, n_layers: int, causal: bool) -> dict:
        out: dict = {"final_layer_norm": {
            "scale": t(f"{side}.final_layer_norm.weight")}}
        for i in range(n_layers):
            pre = f"{side}.block.{i}.layer"
            blk: dict = {
                "ln_self": {"scale": t(f"{pre}.0.layer_norm.weight")},
                "self_attention": {
                    proj: lin(f"{pre}.0.SelfAttention.{proj}")
                    for proj in ("q", "k", "v", "o")},
            }
            if i == 0:
                blk["self_attention"]["relative_attention_bias"] = {
                    "embedding":
                        t(f"{pre}.0.SelfAttention."
                          f"relative_attention_bias.weight")}
            ff_idx = 2 if causal else 1
            if causal:
                blk["ln_cross"] = {
                    "scale": t(f"{pre}.1.layer_norm.weight")}
                blk["cross_attention"] = {
                    proj: lin(f"{pre}.1.EncDecAttention.{proj}")
                    for proj in ("q", "k", "v", "o")}
            blk["ln_ff"] = {"scale": t(f"{pre}.{ff_idx}.layer_norm.weight")}
            ff = {}
            if config.is_gated_act:
                ff["wi_0"] = lin(f"{pre}.{ff_idx}.DenseReluDense.wi_0")
                ff["wi_1"] = lin(f"{pre}.{ff_idx}.DenseReluDense.wi_1")
            else:
                ff["wi"] = lin(f"{pre}.{ff_idx}.DenseReluDense.wi")
            ff["wo"] = lin(f"{pre}.{ff_idx}.DenseReluDense.wo")
            blk["ff"] = ff
            out[f"block_{i}"] = blk
        return out

    params: dict = {"model": {
        "shared": {"embedding": t("shared.weight")},
        "encoder": stack_tree("encoder", config.num_layers, causal=False),
        "decoder": stack_tree("decoder", config.num_decoder_layers,
                              causal=True),
    }}
    if not config.tie_word_embeddings and "lm_head.weight" in state_dict:
        params["lm_head"] = {"kernel": t("lm_head.weight").T}
    return params
