"""TCBert: topic classification as prompt MLM.

Behavioural port of reference: fengshen/models/tcbert/ — the template
"这是一则[MASK][MASK]新闻：{text}"; the MLM head scores each label's words at
the mask positions and the label with the highest joint score wins.
"""

from __future__ import annotations

import argparse
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from fengshen_tpu.models.megatron_bert import MegatronBertConfig


class TCBertModel(nn.Module):
    """MLM backbone scoring label words at mask positions.

    `backbone_type` mirrors the reference's tower dispatch (reference:
    fengshen/models/tcbert/modeling_tcbert.py:203-212 — MegatronBert for
    the 1.3B checkpoints, plain Bert otherwise)."""

    config: MegatronBertConfig
    backbone_type: str = "megatron_bert"
    num_labels: int = 0  # >0 adds the reference's [CLS] linear classifier

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic=True):
        from fengshen_tpu.models.towers import mlm_tower
        backbone = mlm_tower(self.config, self.backbone_type)
        if self.num_labels <= 0:
            return backbone(input_ids, attention_mask, token_type_ids,
                            deterministic=deterministic)
        # reference: modeling_tcbert.py:214-231 — a linear classifier over
        # the dropped-out [CLS] hidden state, returned next to the MLM
        # label-word logits
        mlm_logits, hidden = backbone(
            input_ids, attention_mask, token_type_ids,
            deterministic=deterministic, return_hidden=True)
        cls_h = nn.Dropout(0.1)(hidden[:, 0], deterministic=deterministic)
        cls_logits = nn.Dense(
            self.num_labels,
            kernel_init=nn.initializers.normal(
                self.config.initializer_range),
            name="classifier")(cls_h)
        return mlm_logits, cls_logits

    def partition_rules(self):
        from fengshen_tpu.models.megatron_bert.modeling_megatron_bert \
            import PARTITION_RULES
        return PARTITION_RULES


class TCBertPipelines:
    @staticmethod
    def pipelines_args(parent_parser: argparse.ArgumentParser):
        parser = parent_parser.add_argument_group("tcbert")
        parser.add_argument("--max_length", default=512, type=int)
        parser.add_argument("--prompt", default="这是一则{}新闻：", type=str)
        from fengshen_tpu.data import UniversalDataModule
        from fengshen_tpu.models.model_utils import add_module_args
        from fengshen_tpu.trainer import add_trainer_args
        from fengshen_tpu.utils import UniversalCheckpoint
        parent_parser = add_module_args(parent_parser)
        parent_parser = add_trainer_args(parent_parser)
        parent_parser = UniversalDataModule.add_data_specific_args(
            parent_parser)
        parent_parser = UniversalCheckpoint.add_argparse_args(parent_parser)
        return parent_parser

    def __init__(self, args=None, model: Optional[str] = None,
                 tokenizer=None, config=None, params=None,
                 label_words: Optional[list[str]] = None,
                 backbone_type: str = "megatron_bert"):
        self.args = args
        if config is None and model is not None:
            config = MegatronBertConfig.from_pretrained(model)
        if config is None:
            config = MegatronBertConfig.small_test_config()
        self.config = config
        if tokenizer is None and model is not None:
            from transformers import AutoTokenizer
            tokenizer = AutoTokenizer.from_pretrained(model)
        self.tokenizer = tokenizer
        self.model = TCBertModel(config, backbone_type=backbone_type)
        self.params = params
        self.label_words = label_words or []

    def _encode(self, text: str, mask_len: int) -> tuple[list[int], int]:
        tok = self.tokenizer
        prompt_prefix = [tok.cls_token_id] + \
            [tok.mask_token_id] * mask_len
        body = tok.encode(text, add_special_tokens=False)
        max_len = getattr(self.args, "max_length", 512) if self.args else 512
        ids = (prompt_prefix + body + [tok.sep_token_id])[:max_len]
        return ids, 1  # mask positions start after [CLS]

    def predict(self, texts: list[str],
                label_words: Optional[list[str]] = None) -> list[int]:
        label_words = label_words or self.label_words
        assert label_words, "label_words required"
        if self.params is None:
            self.params = self.model.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
            )["params"]
        tok = self.tokenizer
        label_ids = [tok.encode(w, add_special_tokens=False)
                     for w in label_words]
        mask_len = max(len(l) for l in label_ids)
        results = []
        for text in texts:
            ids, mask_start = self._encode(text, mask_len)
            arr = jnp.asarray([ids], jnp.int32)
            logits = self.model.apply({"params": self.params}, arr,
                                      attention_mask=jnp.ones_like(arr))
            logp = jax.nn.log_softmax(
                np.asarray(logits)[0, mask_start:mask_start + mask_len],
                axis=-1)
            scores = []
            for lab in label_ids:
                s = sum(float(logp[i, t]) for i, t in enumerate(lab))
                scores.append(s / len(lab))
            results.append(int(np.argmax(scores)))
        return results
