"""Reference TCBert checkpoint → flax params.

Reference state-dict naming (fengshen/models/tcbert/modeling_tcbert.py:
203-233): `bert.*` wraps a full *ForMaskedLM (so the inner keys are
`bert.bert.*` + `bert.cls.*`), plus `linear_classifier` over the [CLS]
hidden state. Tower dispatch mirrors the reference's "1.3B → MegatronBert
else Bert" rule via key detection.
"""

from __future__ import annotations

from typing import Any, Mapping

from fengshen_tpu.utils.convert_common import (detect_bert_arch,
                                               make_helpers, strip_prefix,
                                               unwrap_lightning)


def torch_to_params(state_dict: Mapping[str, Any], config,
                    backbone_type: str | None = None) -> dict:
    sd = unwrap_lightning(state_dict)
    _, lin, _ = make_helpers(sd)
    params: dict = {}
    if "linear_classifier.weight" in sd:
        params["classifier"] = lin("linear_classifier")
    inner = strip_prefix(sd, "bert.")
    if backbone_type is None:
        backbone_type = detect_bert_arch(inner)
    if backbone_type == "bert":
        from fengshen_tpu.models.bert.convert import torch_to_params as conv
        params["backbone"] = conv(inner, config)
    else:
        from fengshen_tpu.models.megatron_bert.convert import \
            torch_to_params as conv
        params["backbone"] = conv(inner, config, head="masked_lm")
    return params


#: fs→torch export: derived exact inverse of `torch_to_params`
#: (template_state = the source checkpoint: dict, Lightning ckpt, or dir)
from fengshen_tpu.utils.convert_common import (  # noqa: E402
    make_derived_export)

params_to_torch_state = make_derived_export(torch_to_params)
