"""TCBert — prompt-based topic classification (reference:
fengshen/models/tcbert/, 366 LoC)."""

from fengshen_tpu.models.tcbert.modeling_tcbert import (TCBertModel,
                                                        TCBertPipelines)

__all__ = ["TCBertModel", "TCBertPipelines"]
