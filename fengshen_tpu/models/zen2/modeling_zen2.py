"""ZEN2 — n-gram enhanced BERT with relative-position attention.

Behavioural port of reference: fengshen/models/zen2/modeling.py (2,129
LoC). Architectural deltas from ZEN1:

- no absolute position embeddings; every attention layer uses
  Transformer-XL-style relative attention (sinusoidal relative embeddings +
  learned r_w/r_r biases, reference: modeling.py:343-509);
- the n-gram side stack depth is `num_hidden_word_layers` and shares the
  relative attention mechanism (ZenEncoder, :609-645);
- full HF-style head set (ForMaskedLM/SequenceClassification/
  TokenClassification/QuestionAnswering, :985-1391).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from fengshen_tpu.models.bert.modeling_bert import (PARTITION_RULES,
                                                    BertConfig, _dense)
from fengshen_tpu.ops.activations import get_activation
from fengshen_tpu.ops.embedding import VocabParallelEmbed
from fengshen_tpu.ops.norms import LayerNorm


@dataclasses.dataclass
class Zen2Config(BertConfig):
    ngram_vocab_size: int = 104089
    num_hidden_word_layers: int = 6

    @classmethod
    def small_test_config(cls, **overrides: Any) -> "Zen2Config":
        base = dict(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=64,
                    max_position_embeddings=64, ngram_vocab_size=64,
                    num_hidden_word_layers=2)
        base.update(overrides)
        return cls(**base)


def _dt(cfg):
    return jnp.dtype(cfg.dtype)


def relative_sinusoidal_embedding(n_pos: int, dim: int) -> np.ndarray:
    """Sinusoidal embeddings over relative offsets -n_pos..n_pos-1 in the
    tensor2tensor layout the reference uses — [sin | cos] concatenated
    halves with freq_i = 10000^(-i/(dim/2-1)) — so imported r-bias
    vectors act on the same basis (reference: modeling.py:367-384,
    RelativeSinusoidalPositionalEmbedding.get_embedding)."""
    half = dim // 2
    scale = np.log(10000.0) / max(half - 1, 1)
    inv_freq = np.exp(np.arange(half, dtype=np.float32) * -scale)
    offsets = np.arange(-n_pos, n_pos, dtype=np.float32)
    angles = offsets[:, None] * inv_freq[None, :]
    emb = np.concatenate([np.sin(angles), np.cos(angles)], axis=1)
    if dim % 2 == 1:
        emb = np.concatenate([emb, np.zeros((len(offsets), 1),
                                            np.float32)], axis=1)
    return emb  # [2*n_pos, dim]


class Zen2SelfAttention(nn.Module):
    """Relative-position attention (reference: modeling.py:407-509):
    scores = (q + r_w_bias)·k + (q + r_r_bias)·R_{j-i}."""

    config: Zen2Config

    @nn.compact
    def __call__(self, hidden, attention_mask=None, deterministic=True):
        cfg = self.config
        batch, seq, _ = hidden.shape
        n_head = cfg.num_attention_heads
        head_dim = cfg.hidden_size // n_head

        def proj(name):
            x = _dense(cfg, cfg.hidden_size, name)(hidden)
            return x.reshape(batch, seq, n_head, head_dim)

        q, k, v = proj("query"), proj("key"), proj("value")

        r_w_bias = self.param("r_w_bias", nn.initializers.normal(0.02),
                              (n_head, head_dim), jnp.float32)
        r_r_bias = self.param("r_r_bias", nn.initializers.normal(0.02),
                              (n_head, head_dim), jnp.float32)

        # content term: (q + r_w) · k
        qw = q + r_w_bias[None, None].astype(q.dtype)
        ac = jnp.einsum("bqnd,bknd->bnqk", qw, k,
                        preferred_element_type=jnp.float32)

        # position term: (q + r_r) · R_{j-i}
        rel = jnp.asarray(relative_sinusoidal_embedding(seq, head_dim),
                          q.dtype)  # [2S, d], row r ↔ offset r - S
        idx = (jnp.arange(seq)[None, :] - jnp.arange(seq)[:, None]
               + seq)  # [S, S] in 1..2S-1
        r_mat = rel[idx]  # [S, S, d]
        qr = q + r_r_bias[None, None].astype(q.dtype)
        bd = jnp.einsum("bqnd,qkd->bnqk", qr, r_mat,
                        preferred_element_type=jnp.float32)

        scores = (ac + bd) / jnp.sqrt(jnp.asarray(head_dim, jnp.float32))
        if attention_mask is not None:
            scores = jnp.where(
                attention_mask[:, None, None, :].astype(bool), scores,
                -1e9)
        probs = jax.nn.softmax(scores, -1)
        probs = nn.Dropout(cfg.attention_probs_dropout_prob)(
            probs, deterministic=deterministic)
        out = jnp.einsum("bnqk,bknd->bqnd", probs.astype(v.dtype), v)
        out = out.reshape(batch, seq, cfg.hidden_size)
        return _dense(cfg, cfg.hidden_size, "attention_output_dense")(out)


class Zen2Layer(nn.Module):
    config: Zen2Config

    @nn.compact
    def __call__(self, hidden, attention_mask=None, deterministic=True):
        cfg = self.config
        h = Zen2SelfAttention(cfg, name="attention")(
            hidden, attention_mask, deterministic)
        h = nn.Dropout(cfg.hidden_dropout_prob)(h,
                                                deterministic=deterministic)
        hidden = LayerNorm(epsilon=cfg.layer_norm_eps,
                           name="attention_ln")(hidden + h)
        h = _dense(cfg, cfg.intermediate_size, "intermediate_dense")(hidden)
        h = get_activation(cfg.hidden_act)(h)
        h = _dense(cfg, cfg.hidden_size, "output_dense")(h)
        h = nn.Dropout(cfg.hidden_dropout_prob)(h,
                                                deterministic=deterministic)
        return LayerNorm(epsilon=cfg.layer_norm_eps,
                         name="output_ln")(hidden + h)


class Zen2Model(nn.Module):
    """Char stack + n-gram side stack with positional fusion
    (reference: ZenEncoder modeling.py:609-645)."""

    config: Zen2Config
    add_pooling_layer: bool = True

    @nn.compact
    def __call__(self, input_ids, ngram_ids=None, ngram_positions=None,
                 attention_mask=None, token_type_ids=None,
                 deterministic=True, **unused):
        cfg = self.config
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        embed = lambda n, name, cls=nn.Embed: cls(  # noqa: E731
            n, cfg.hidden_size, dtype=_dt(cfg),
            param_dtype=jnp.dtype(cfg.param_dtype),
            embedding_init=nn.initializers.normal(cfg.initializer_range),
            name=name)
        # NOTE: no absolute position embeddings — relative attention
        hidden = embed(cfg.vocab_size, "word_embeddings",
                       VocabParallelEmbed)(input_ids) + \
            embed(cfg.type_vocab_size,
                  "token_type_embeddings")(token_type_ids)
        hidden = LayerNorm(epsilon=cfg.layer_norm_eps,
                           name="embeddings_ln")(hidden)
        hidden = nn.Dropout(cfg.hidden_dropout_prob)(
            hidden, deterministic=deterministic)

        ngram_hidden = ngram_mask = None
        if ngram_ids is not None:
            # ngram side carries its own token-type table (reference:
            # modeling.py:317-340 BertWordEmbeddings — word + token_type
            # + LayerNorm); ngram token types are 0 in every published
            # pipeline, so the zeros default matches
            ngram_hidden = embed(cfg.ngram_vocab_size,
                                 "ngram_embeddings")(ngram_ids) + \
                embed(cfg.type_vocab_size, "ngram_token_type_embeddings")(
                    jnp.zeros_like(ngram_ids))
            ngram_hidden = LayerNorm(epsilon=cfg.layer_norm_eps,
                                     name="ngram_ln")(ngram_hidden)
            ngram_mask = (ngram_ids != 0).astype(jnp.int32)

        for i in range(cfg.num_hidden_layers):
            hidden = Zen2Layer(cfg, name=f"layer_{i}")(
                hidden, attention_mask, deterministic)
            if ngram_hidden is not None:
                if i < cfg.num_hidden_word_layers:
                    ngram_hidden = Zen2Layer(
                        cfg, name=f"ngram_layer_{i}")(
                        ngram_hidden, ngram_mask, deterministic)
                # fusion runs on EVERY layer — the reference bmm
                # (modeling.py:636) sits OUTSIDE the word-layer gate, so
                # layers past num_hidden_word_layers keep receiving the
                # LAST ngram states; matrix arrives freq-normalised from
                # data prep (examples/zen2_finetune/...:393-404)
                fused = jnp.einsum(
                    "bsm,bmh->bsh", ngram_positions.astype(jnp.float32),
                    ngram_hidden.astype(jnp.float32))
                hidden = hidden + fused.astype(hidden.dtype)

        pooled = None
        if self.add_pooling_layer:
            pooled = jnp.tanh(_dense(cfg, cfg.hidden_size,
                                     "pooler")(hidden[:, 0]))
        return hidden, pooled

    def partition_rules(self):
        return PARTITION_RULES


class Zen2ForMaskedLM(nn.Module):
    config: Zen2Config

    @nn.compact
    def __call__(self, input_ids, ngram_ids=None, ngram_positions=None,
                 attention_mask=None, token_type_ids=None,
                 deterministic=True):
        cfg = self.config
        hidden, _ = Zen2Model(cfg, add_pooling_layer=False, name="zen")(
            input_ids, ngram_ids, ngram_positions, attention_mask,
            token_type_ids, deterministic)
        h = _dense(cfg, cfg.hidden_size, "transform_dense")(hidden)
        h = get_activation(cfg.hidden_act)(h)
        h = LayerNorm(epsilon=cfg.layer_norm_eps, name="transform_ln")(h)
        wte = self.variables["params"]["zen"]["word_embeddings"][
            "embedding"]
        logits = h @ wte.T.astype(h.dtype)
        bias = self.param("bias", nn.initializers.zeros,
                          (cfg.vocab_size,), jnp.dtype(cfg.param_dtype))
        return logits + bias

    def partition_rules(self):
        return PARTITION_RULES
