"""torch → jax weights for ZEN2 (relative-attention n-gram BERT).

Importer for released Erlangshen-ZEN2 checkpoints (reference:
fengshen/models/zen2/modeling.py — char embeddings :293-315, ngram
BertWordEmbeddings :317-340, relative BertSelfAttention with per-layer
r_r_bias/r_w_bias :407-509, encoder `layer` + `word_layers` :609-645,
ZenOnlyMLMHead :697-706).

Bias-role note: the reference adds **r_r_bias** to the query for the
content (AC) term and pairs **r_w_bias** with the positional basis in the
BD term (modeling.py:451-457) — the OPPOSITE of the Transformer-XL paper
naming our `Zen2SelfAttention` follows (r_w = content, r_r = position).
The converter swaps them so the imported math is identical.
"""

from __future__ import annotations

from typing import Any, Mapping

from fengshen_tpu.models.zen2.modeling_zen2 import Zen2Config
from fengshen_tpu.utils.convert_common import (make_helpers,
                                               unwrap_lightning)


def _zen2_layer(sd, prefix: str) -> dict:
    t, lin, ln = make_helpers(sd)
    return {
        "attention": {
            "query": lin(f"{prefix}.attention.self.query"),
            "key": lin(f"{prefix}.attention.self.key"),
            "value": lin(f"{prefix}.attention.self.value"),
            # swapped on purpose — see module docstring
            "r_w_bias": t(f"{prefix}.attention.self.r_r_bias"),
            "r_r_bias": t(f"{prefix}.attention.self.r_w_bias"),
            "attention_output_dense": lin(f"{prefix}.attention.output"
                                          ".dense"),
        },
        "attention_ln": ln(f"{prefix}.attention.output.LayerNorm"),
        "intermediate_dense": lin(f"{prefix}.intermediate.dense"),
        "output_dense": lin(f"{prefix}.output.dense"),
        "output_ln": ln(f"{prefix}.output.LayerNorm"),
    }


def torch_to_params(state_dict: Mapping[str, Any], config: Zen2Config,
                    head: str = "none") -> dict:
    """`head` ∈ {none, masked_lm, sequence_classification,
    token_classification}. Returns the Zen2Model tower for "none", else
    the head model's tree with the tower under "zen"."""
    sd = unwrap_lightning(state_dict)
    if not any(k.startswith("bert.") for k in sd):
        sd = {f"bert.{k}": v for k, v in sd.items()}
    t, lin, ln = make_helpers(sd)

    tower: dict = {
        "word_embeddings": {
            "embedding": t("bert.embeddings.word_embeddings.weight")},
        "token_type_embeddings": {
            "embedding": t("bert.embeddings.token_type_embeddings.weight")},
        "embeddings_ln": ln("bert.embeddings.LayerNorm"),
        "ngram_embeddings": {
            "embedding": t("bert.word_embeddings.word_embeddings.weight")},
        "ngram_token_type_embeddings": {
            "embedding": t(
                "bert.word_embeddings.token_type_embeddings.weight")},
        "ngram_ln": ln("bert.word_embeddings.LayerNorm"),
    }
    for i in range(config.num_hidden_layers):
        tower[f"layer_{i}"] = _zen2_layer(sd, f"bert.encoder.layer.{i}")
    for i in range(config.num_hidden_word_layers):
        tower[f"ngram_layer_{i}"] = _zen2_layer(
            sd, f"bert.encoder.word_layers.{i}")
    if "bert.pooler.dense.weight" in sd:
        tower["pooler"] = lin("bert.pooler.dense")

    if head == "none":
        return tower
    params: dict = {"zen": tower}
    if head == "masked_lm":
        params.update({
            "transform_dense": lin("cls.predictions.transform.dense"),
            "transform_ln": ln("cls.predictions.transform.LayerNorm"),
            "bias": t("cls.predictions.bias"),
        })
    elif head in ("sequence_classification", "token_classification"):
        params["classifier"] = lin("classifier")
    return params


#: fs→torch export: derived exact inverse of `torch_to_params`
#: (template_state = the source checkpoint: dict, Lightning ckpt, or dir)
from fengshen_tpu.utils.convert_common import (  # noqa: E402
    make_derived_export)

params_to_torch_state = make_derived_export(torch_to_params)
