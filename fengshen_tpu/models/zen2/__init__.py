"""ZEN2 family (reference: fengshen/models/zen2/, 2,129 LoC)."""

from fengshen_tpu.models.heads import make_task_heads
from fengshen_tpu.models.zen2.modeling_zen2 import (
    Zen2Config, Zen2Model, Zen2ForMaskedLM, relative_sinusoidal_embedding)
from fengshen_tpu.models.bert.modeling_bert import PARTITION_RULES as _RULES

(Zen2ForSequenceClassification, Zen2ForTokenClassification,
 Zen2ForQuestionAnswering, Zen2ForMultipleChoice) = make_task_heads(
    Zen2Model, has_pooler=True, encoder_name="zen",
    rules=lambda cfg: _RULES)
Zen2ForSequenceClassification.__name__ = "Zen2ForSequenceClassification"
Zen2ForTokenClassification.__name__ = "Zen2ForTokenClassification"
Zen2ForQuestionAnswering.__name__ = "Zen2ForQuestionAnswering"
Zen2ForMultipleChoice.__name__ = "Zen2ForMultipleChoice"

__all__ = ["Zen2Config", "Zen2Model", "Zen2ForMaskedLM",
           "relative_sinusoidal_embedding",
           "Zen2ForSequenceClassification", "Zen2ForTokenClassification",
           "Zen2ForQuestionAnswering", "Zen2ForMultipleChoice"]
