"""Transfo-XL paraphrase family (reference:
fengshen/models/transfo_xl_paraphrase/)."""

from fengshen_tpu.models.transfo_xl_denoise import (
    TransfoXLDenoiseConfig as TransfoXLParaphraseConfig,
    TransfoXLDenoiseModel as TransfoXLParaphraseModel)
from fengshen_tpu.models.transfo_xl_paraphrase.generate import (
    paraphrase_generate)

__all__ = ["TransfoXLParaphraseConfig", "TransfoXLParaphraseModel",
           "paraphrase_generate"]
