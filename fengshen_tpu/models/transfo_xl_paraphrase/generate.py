"""Paraphrase generation with the fixed pretrained prompt.

Port of reference: fengshen/models/transfo_xl_paraphrase/generate.py:16-60 —
the released Randeng-TransformerXL-Paraphrase checkpoint is prompted with
``“{text}”的相似句是“`` and sampled until the closing quote. Batching and
sampling ride the shared utils.generate.generate_with_prompts.
"""

from __future__ import annotations

from typing import Any, List, Union

from fengshen_tpu.utils.generate import generate_with_prompts


def paraphrase_generate(model: Any, params: Any, tokenizer: Any,
                        input_text: Union[str, List[str]],
                        max_out_seq: int = 128,
                        temperature: float = 1.0, top_k: int = 0,
                        top_p: float = 0.9, seed: int = 0) -> List[str]:
    """reference: generate.py:16-60 (prompt at :25)."""
    if isinstance(input_text, str):
        input_text = [input_text]
    prompts = [f"“{text}”的相似句是“" for text in input_text]
    outs = generate_with_prompts(
        model, params, tokenizer, prompts, max_out_seq=max_out_seq,
        temperature=temperature, top_k=top_k, top_p=top_p, seed=seed)
    return [o.split("”")[0].replace(" ", "") for o in outs]
