"""Paraphrase generation with the fixed pretrained prompt.

Port of reference: fengshen/models/transfo_xl_paraphrase/generate.py:16-60 —
the released Randeng-TransformerXL-Paraphrase checkpoint is prompted with
``“{text}”的相似句是“`` and sampled until the closing quote.
"""

from __future__ import annotations

from typing import Any, List, Union

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.utils.generate import sample_sequence_batch


def paraphrase_generate(model: Any, params: Any, tokenizer: Any,
                        input_text: Union[str, List[str]],
                        max_out_seq: int = 128,
                        temperature: float = 1.0, top_k: int = 0,
                        top_p: float = 0.9, seed: int = 0) -> List[str]:
    """reference: generate.py:16-60 (prompt at :25)."""
    if isinstance(input_text, str):
        input_text = [input_text]
    prompts = [f"“{text}”的相似句是“" for text in input_text]
    enc = [tokenizer.encode(p) for p in prompts]
    enc = [ids[:-1] if ids and ids[-1] == tokenizer.eos_token_id else ids
           for ids in enc]
    max_len = max(len(x) for x in enc)
    pad = tokenizer.pad_token_id or 0
    # left-pad so every prompt ends at the same position
    batch = np.full((len(enc), max_len), pad, np.int32)
    for i, ids in enumerate(enc):
        batch[i, max_len - len(ids):] = ids
    out = sample_sequence_batch(
        model, params, jnp.asarray(batch), max_out_seq=max_out_seq,
        temperature=temperature, top_k=top_k, top_p=top_p,
        eos_token_id=tokenizer.eos_token_id,
        rng=jax.random.PRNGKey(seed))
    results = []
    for row in np.asarray(out):
        text = tokenizer.decode([int(t) for t in row[max_len:]])
        results.append(text.split("”")[0].replace(" ", ""))
    return results
