"""torch(HF) → jax weights for Pegasus.

Importer for released Randeng-Pegasus checkpoints (the reference uses HF
PegasusForConditionalGeneration directly,
reference: fengshen/examples/pegasus/pretrain_pegasus.py).
"""

from __future__ import annotations

from typing import Any, Mapping

from fengshen_tpu.models.pegasus.modeling_pegasus import PegasusConfig
from fengshen_tpu.utils.convert_common import (make_helpers,
                                               seq2seq_attention)


def torch_to_params(state_dict: Mapping[str, Any],
                    config: PegasusConfig) -> dict:
    t, lin, ln = make_helpers(state_dict)

    def enc_layer(i):
        p = f"model.encoder.layers.{i}"
        return {
            "self_attn": seq2seq_attention(state_dict, f"{p}.self_attn"),
            "self_attn_layer_norm": ln(f"{p}.self_attn_layer_norm"),
            "fc1": lin(f"{p}.fc1"),
            "fc2": lin(f"{p}.fc2"),
            "final_layer_norm": ln(f"{p}.final_layer_norm"),
        }

    def dec_layer(i):
        p = f"model.decoder.layers.{i}"
        return {
            "self_attn": seq2seq_attention(state_dict, f"{p}.self_attn"),
            "self_attn_layer_norm": ln(f"{p}.self_attn_layer_norm"),
            "encoder_attn": seq2seq_attention(state_dict,
                                              f"{p}.encoder_attn"),
            "encoder_attn_layer_norm": ln(f"{p}.encoder_attn_layer_norm"),
            "fc1": lin(f"{p}.fc1"),
            "fc2": lin(f"{p}.fc2"),
            "final_layer_norm": ln(f"{p}.final_layer_norm"),
        }

    params: dict = {
        "shared": {"embedding": t("model.shared.weight")},
        "encoder_layer_norm": ln("model.encoder.layer_norm"),
        "decoder_layer_norm": ln("model.decoder.layer_norm"),
        "final_logits_bias": t("final_logits_bias").reshape(-1),
    }
    for i in range(config.encoder_layers):
        params[f"encoder_layer_{i}"] = enc_layer(i)
    for i in range(config.decoder_layers):
        params[f"decoder_layer_{i}"] = dec_layer(i)
    return params


#: fs→torch export: derived exact inverse of `torch_to_params`
#: (template_state = the source checkpoint: dict, Lightning ckpt, or dir)
from fengshen_tpu.utils.convert_common import (  # noqa: E402
    make_derived_export)

params_to_torch_state = make_derived_export(torch_to_params)
