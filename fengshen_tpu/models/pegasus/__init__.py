"""Pegasus family (reference: fengshen/examples/pegasus/ — Randeng-Pegasus
LCSTS summarization, pretrain_pegasus.py gap-sentence objective)."""

from fengshen_tpu.models.pegasus.modeling_pegasus import (
    PegasusConfig, PegasusForConditionalGeneration)

__all__ = ["PegasusConfig", "PegasusForConditionalGeneration"]
