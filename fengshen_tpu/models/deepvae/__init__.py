"""Della / deepVAE family (reference: fengshen/models/deepVAE/, 947 LoC)."""

from fengshen_tpu.models.deepvae.modeling_deepvae import (
    DellaConfig, DellaModel, AverageSelfAttention, LatentLayer, della_loss)

__all__ = ["DellaConfig", "DellaModel", "AverageSelfAttention",
           "LatentLayer", "della_loss"]
