"""Della (deepVAE) — hierarchical per-layer latent VAE on GPT-2.

Behavioural port of reference: fengshen/models/deepVAE/ (947 LoC):
every encoder layer's hidden states are pooled by a learned attention
(AverageSelfAttention, deep_vae.py:56-75) into a per-layer sentence
representation; latents are extracted recursively — the posterior of layer
l conditions on z_{<l} (latent_layer gating, :44-54, posterior/prior nets
:95-96) — and the decoder injects each layer's latent into the matching
GPT-2 decoder layer (latent_connector.GPT2ForDecoderLatentConnector). The
loss is reconstruction + Σ_l KL(posterior_l ‖ prior_l), both gaussians
(utils.compute_kl_loss).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from fengshen_tpu.models.gpt2 import GPT2Config
from fengshen_tpu.models.gpt2.modeling_gpt2 import GPT2Block
from fengshen_tpu.ops.norms import LayerNorm
from fengshen_tpu.parallel.cross_entropy import stable_cross_entropy


@dataclasses.dataclass
class DellaConfig:
    latent_dim: int = 32
    gpt2: GPT2Config = None

    @classmethod
    def small_test_config(cls, **overrides: Any) -> "DellaConfig":
        base = dict(latent_dim=8,
                    gpt2=GPT2Config.small_test_config(dtype="float32"))
        base.update(overrides)
        return cls(**base)


class AverageSelfAttention(nn.Module):
    """Learned-query pooling over a layer's hidden states
    (reference: deep_vae.py:56-75)."""

    hidden_dim: int

    @nn.compact
    def __call__(self, hidden, attention_mask=None):
        query = self.param("attention_weights",
                           nn.initializers.normal(0.02),
                           (self.hidden_dim,))
        # tanh over the SCORES, not the inputs (reference:
        # deep_vae.py:66 `non_linearity(inputs.matmul(w))`)
        scores = jnp.tanh(jnp.einsum("bsh,h->bs", hidden,
                                     query.astype(hidden.dtype)))
        if attention_mask is not None:
            scores = jnp.where(attention_mask > 0, scores, -1e9)
        probs = jax.nn.softmax(scores, -1)
        return jnp.einsum("bs,bsh->bh", probs, hidden)


class LatentLayer(nn.Module):
    """Recursive latent combiner z_{<l+1} = tanh(W_hh z_{<l} + W_ih z_l)
    (reference: deep_vae.py:44-54 — two bias-free Linears + tanh)."""

    latent_dim: int

    @nn.compact
    def __call__(self, z_prev, z_new):
        h = nn.Dense(self.latent_dim, use_bias=False, name="W_hh")(z_prev)
        i = nn.Dense(self.latent_dim, use_bias=False, name="W_ih")(z_new)
        return jnp.tanh(h + i)


class DellaModel(nn.Module):
    """Separate encoder/decoder GPT-2 towers with per-layer recursive
    latents (reference: deep_vae.py DeepVAE + latent_connector.py —
    GPT2ForEncoderLatentConnector / GPT2ForDecoderLatentConnector each
    carry their own wte/wpe/blocks/ln_f; the decoder adds a projected
    latent BEFORE every block and an untied lm_head)."""

    config: DellaConfig

    @nn.compact
    def __call__(self, input_ids, decoder_input_ids=None,
                 attention_mask=None, rng=None, deterministic=True):
        cfg = self.config
        gcfg = cfg.gpt2
        if decoder_input_ids is None:
            decoder_input_ids = input_ids
        batch, seq = input_ids.shape
        L, D = gcfg.n_layer, cfg.latent_dim

        def tower_embed(prefix):
            wte = nn.Embed(gcfg.vocab_size, gcfg.n_embd,
                           embedding_init=nn.initializers.normal(
                               gcfg.initializer_range),
                           name=f"{prefix}_wte")
            wpe = nn.Embed(gcfg.n_positions, gcfg.n_embd,
                           embedding_init=nn.initializers.normal(
                               gcfg.initializer_range),
                           name=f"{prefix}_wpe")
            return wte, wpe

        # -- encoder: pooled representation per layer ----------------------
        # HF hidden_states[1:] indexing (deep_vae.py:163-165): entries are
        # block_0..block_{L-2} outputs, then ln_f(block_{L-1} output)
        enc_wte, enc_wpe = tower_embed("enc")
        pos = jnp.arange(seq)[None]
        hidden = enc_wte(input_ids) + enc_wpe(pos)
        layer_states = []
        for i in range(L):
            hidden = GPT2Block(gcfg, name=f"enc_h_{i}")(
                hidden, attention_mask, pos, False, deterministic)
            layer_states.append(hidden)
        layer_states[-1] = LayerNorm(epsilon=gcfg.layer_norm_epsilon,
                                     name="enc_ln_f")(layer_states[-1])
        # reference pools WITHOUT the padding mask (deep_vae.py:118-126,
        # its own TODO) — kept identical so imported checkpoints match
        reps = [AverageSelfAttention(gcfg.n_embd, name=f"pool_{i}")(
            layer_states[i]) for i in range(L)]

        # -- recursive latent extraction (deep_vae.py:111-139) -------------
        z = jnp.zeros((batch, D), hidden.dtype)
        posts, priors, zs = [], [], []
        for i in range(L):
            prior_stats = nn.Dense(2 * D, use_bias=False,
                                   name=f"prior_{i}")(z)
            post_stats = nn.Dense(2 * D, use_bias=False,
                                  name=f"posterior_{i}")(
                jnp.concatenate([reps[i], z], -1))
            p_mean, p_logvar = jnp.split(post_stats, 2, -1)
            if rng is not None:
                rng, key = jax.random.split(rng)
                z_l = p_mean + jnp.exp(0.5 * p_logvar) * \
                    jax.random.normal(key, p_mean.shape)
            else:
                z_l = p_mean
            posts.append((p_mean, p_logvar))
            priors.append(tuple(jnp.split(prior_stats, 2, -1)))
            zs.append(z_l)
            if i < L - 1:
                z = LatentLayer(D, name=f"latent_net_{i}")(z, z_l)

        # -- decoder: inject z_l BEFORE block l (latent_connector.py:
        # 172-179) over its own tower, untied lm_head ----------------------
        dec_wte, dec_wpe = tower_embed("dec")
        dec_pos = jnp.arange(decoder_input_ids.shape[1])[None]
        dec = dec_wte(decoder_input_ids) + dec_wpe(dec_pos)
        for i in range(L):
            inject = nn.Dense(gcfg.n_embd, use_bias=False,
                              name=f"latent_proj_{i}")(zs[i])
            dec = dec + inject[:, None, :].astype(dec.dtype)
            dec = GPT2Block(gcfg, name=f"dec_h_{i}")(
                dec, None, dec_pos, False, deterministic)
        dec = LayerNorm(epsilon=gcfg.layer_norm_epsilon, name="ln_f")(dec)
        logits = nn.Dense(gcfg.vocab_size, use_bias=False,
                          name="lm_head")(dec)
        return logits, posts, priors


def della_loss(logits, target_ids, posts, priors,
               kl_weight: float = 1.0, free_bits: float = 0.0):
    """recon + Σ_l KL(N(post_l) ‖ N(prior_l))
    (reference: utils.compute_kl_loss)."""
    recon, _ = stable_cross_entropy(logits[:, :-1], target_ids[:, 1:])
    kl_total = 0.0
    for (pm, plv), (qm, qlv) in zip(posts, priors):
        kl = 0.5 * (qlv - plv + (jnp.exp(plv) + (pm - qm) ** 2) /
                    jnp.exp(qlv) - 1.0)
        kl = kl.sum(-1).mean()
        kl_total = kl_total + jnp.maximum(kl, free_bits)
    return recon + kl_weight * kl_total, {"recon": recon, "kl": kl_total}
