"""torch → jax weights for Della (deepVAE).

Reference state-dict naming (fengshen/models/deepVAE/deep_vae.py:77-99 +
latent_connector.py:40-62,310-314): `encoder.transformer.*` and
`decoder.transformer.*` are HF-GPT2 towers (wte/wpe/h.N/ln_f, Conv1D
kernels already [in, out]), the decoder adds per-layer bias-free
`transformer.linear_emb_layers.N` and an untied `lm_head`;
`latent_nets.N.{W_hh,W_ih}` (bias-free), `posterior_nets.N` /
`prior_nets.N` (bias-free), `pooling.N.attention_weights`.
"""

from __future__ import annotations

from typing import Any, Mapping

from fengshen_tpu.utils.convert_common import tensor, unwrap_lightning


def _gpt2_block(sd, prefix: str) -> dict:
    def t(name):
        return tensor(sd, name)

    def ln(p):
        return {"scale": t(f"{p}.weight"), "bias": t(f"{p}.bias")}

    def conv(p):  # HF Conv1D weight is already [in, out]
        return {"kernel": t(f"{p}.weight"), "bias": t(f"{p}.bias")}

    return {
        "ln_1": ln(f"{prefix}.ln_1"),
        "ln_2": ln(f"{prefix}.ln_2"),
        "attn": {"c_attn": conv(f"{prefix}.attn.c_attn"),
                 "c_proj": conv(f"{prefix}.attn.c_proj")},
        "c_fc": conv(f"{prefix}.mlp.c_fc"),
        "c_proj": conv(f"{prefix}.mlp.c_proj"),
    }


def torch_to_params(state_dict: Mapping[str, Any], config) -> dict:
    sd = unwrap_lightning(state_dict)

    def t(name):
        return tensor(sd, name)

    def ln(p):
        return {"scale": t(f"{p}.weight"), "bias": t(f"{p}.bias")}

    L = config.gpt2.n_layer
    params: dict = {
        "enc_wte": {"embedding": t("encoder.transformer.wte.weight")},
        "enc_wpe": {"embedding": t("encoder.transformer.wpe.weight")},
        "enc_ln_f": ln("encoder.transformer.ln_f"),
        "dec_wte": {"embedding": t("decoder.transformer.wte.weight")},
        "dec_wpe": {"embedding": t("decoder.transformer.wpe.weight")},
        "ln_f": ln("decoder.transformer.ln_f"),
    }
    lm_key = "decoder.lm_head.weight"
    lm = t(lm_key) if lm_key in sd else \
        t("decoder.transformer.wte.weight")
    params["lm_head"] = {"kernel": lm.T}
    for i in range(L):
        params[f"enc_h_{i}"] = _gpt2_block(sd, f"encoder.transformer.h.{i}")
        params[f"dec_h_{i}"] = _gpt2_block(sd, f"decoder.transformer.h.{i}")
        params[f"latent_proj_{i}"] = {"kernel": t(
            f"decoder.transformer.linear_emb_layers.{i}.weight").T}
        params[f"pool_{i}"] = {
            "attention_weights": t(f"pooling.{i}.attention_weights")}
        params[f"posterior_{i}"] = {"kernel": t(
            f"posterior_nets.{i}.weight").T}
        params[f"prior_{i}"] = {"kernel": t(f"prior_nets.{i}.weight").T}
        if i < L - 1:
            params[f"latent_net_{i}"] = {
                "W_hh": {"kernel": t(f"latent_nets.{i}.W_hh.weight").T},
                "W_ih": {"kernel": t(f"latent_nets.{i}.W_ih.weight").T},
            }
    return params


#: fs→torch export: derived exact inverse of `torch_to_params`
#: (template_state = the source checkpoint: dict, Lightning ckpt, or dir)
from fengshen_tpu.utils.convert_common import (  # noqa: E402
    make_derived_export)

params_to_torch_state = make_derived_export(torch_to_params)
