"""torch(HF) → jax weights for GPT-2.

HF GPT2 uses Conv1D modules whose `weight` is already [in, out], so kernels
map without transpose; LayerNorm weight→scale.
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from fengshen_tpu.utils.convert_common import tensor as _tensor

from fengshen_tpu.models.gpt2.configuration_gpt2 import GPT2Config


def torch_to_params(state_dict: Mapping[str, Any],
                    config: GPT2Config) -> dict:
    def t(name):
        return _tensor(state_dict, name)

    def ln(prefix):
        return {"scale": t(f"{prefix}.weight"), "bias": t(f"{prefix}.bias")}

    def conv(prefix):
        return {"kernel": t(f"{prefix}.weight"), "bias": t(f"{prefix}.bias")}

    def layer_tree(i: int) -> dict:
        pre = f"transformer.h.{i}"
        return {
            "ln_1": ln(f"{pre}.ln_1"),
            "ln_2": ln(f"{pre}.ln_2"),
            "attn": {"c_attn": conv(f"{pre}.attn.c_attn"),
                     "c_proj": conv(f"{pre}.attn.c_proj")},
            "c_fc": conv(f"{pre}.mlp.c_fc"),
            "c_proj": conv(f"{pre}.mlp.c_proj"),
        }

    params: dict = {"transformer": {
        "wte": {"embedding": t("transformer.wte.weight")},
        "wpe": {"embedding": t("transformer.wpe.weight")},
        "ln_f": ln("transformer.ln_f"),
    }}
    if config.scan_layers:
        import jax
        trees = [layer_tree(i) for i in range(config.n_layer)]
        params["transformer"]["h"] = {"block": jax.tree_util.tree_map(
            lambda *xs: np.stack(xs), *trees)}
    else:
        for i in range(config.n_layer):
            params["transformer"][f"h_{i}"] = layer_tree(i)
    return params


def load_hf_pretrained(path: str, config: GPT2Config | None = None):
    import glob
    import os

    import torch

    config = config or GPT2Config.from_pretrained(path)
    state: dict = {}
    st_files = sorted(glob.glob(os.path.join(path, "*.safetensors")))
    if st_files:
        from safetensors import safe_open
        for f in st_files:
            with safe_open(f, framework="pt") as sf:
                for key in sf.keys():
                    state[key] = sf.get_tensor(key)
    else:
        for f in sorted(glob.glob(os.path.join(path, "pytorch_model*.bin"))):
            state.update(torch.load(f, map_location="cpu",
                                    weights_only=True))
    if not any(k.startswith("transformer.") for k in state):
        state = {f"transformer.{k}": v for k, v in state.items()
                 if not k.startswith("lm_head")}
    return config, torch_to_params(state, config)


#: fs→torch export: derived exact inverse of `torch_to_params`
#: (template_state = the source checkpoint: dict, Lightning ckpt, or dir)
from fengshen_tpu.utils.convert_common import (  # noqa: E402
    make_derived_export)

params_to_torch_state = make_derived_export(torch_to_params)
