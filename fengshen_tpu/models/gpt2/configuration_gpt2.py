"""GPT-2 config (HF-compatible field names)."""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Optional


@dataclasses.dataclass
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    n_inner: Optional[int] = None          # default 4*n_embd
    activation_function: str = "gelu_new"
    resid_pdrop: float = 0.1
    embd_pdrop: float = 0.1
    attn_pdrop: float = 0.1
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    bos_token_id: int = 50256
    eos_token_id: int = 50256
    # TPU-native knobs
    # >0: chunked fused LM-head+CE (ops/fused_ce.py) in CausalLMModule
    fused_ce_chunks: int = 0
    dtype: str = "bfloat16"
    param_dtype: str = "float32"
    gradient_checkpointing: bool = False
    scan_layers: bool = False
    attention_impl: str = "dense"

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head

    @property
    def inner_dim(self) -> int:
        return self.n_inner or 4 * self.n_embd

    # alias used by shared utilities
    @property
    def hidden_size(self) -> int:
        return self.n_embd

    @property
    def num_hidden_layers(self) -> int:
        return self.n_layer

    @property
    def intermediate_size(self) -> int:
        return self.inner_dim

    @property
    def max_position_embeddings(self) -> int:
        return self.n_positions

    @classmethod
    def from_pretrained(cls, path: str) -> "GPT2Config":
        cfg_file = os.path.join(path, "config.json") if os.path.isdir(path) \
            else path
        with open(cfg_file) as f:
            raw = json.load(f)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in raw.items() if k in known})

    def save_pretrained(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "config.json"), "w") as f:
            json.dump(dataclasses.asdict(self) |
                      {"model_type": "gpt2"}, f, indent=2)

    @classmethod
    def small_test_config(cls, **overrides: Any) -> "GPT2Config":
        base = dict(vocab_size=128, n_positions=64, n_embd=32, n_layer=2,
                    n_head=4)
        base.update(overrides)
        return cls(**base)
