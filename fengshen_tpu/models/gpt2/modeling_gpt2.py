"""GPT-2 in flax, HF-weight-compatible.

Wenzhong is an HF GPT2 checkpoint
(reference: fengshen/examples/wenzhong_qa/finetune_wenzhong.py uses
GPT2LMHeadModel from transformers). Parameter paths mirror the HF torch
layout (transformer/wte, h_{i}/attn/c_attn, ...) so state_dicts import by
direct mapping (HF Conv1D already stores kernels [in, out] — no transpose).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from fengshen_tpu.models.gpt2.configuration_gpt2 import GPT2Config
from fengshen_tpu.ops.activations import get_activation
from fengshen_tpu.ops.embedding import VocabParallelEmbed
from fengshen_tpu.ops.attention import dot_product_attention
from fengshen_tpu.ops.masks import causal_mask
from fengshen_tpu.ops.norms import LayerNorm
from fengshen_tpu.sharding import (to_partition_rules,
                                    with_logical_constraint)

PARAM_LOGICAL_AXES: list[tuple[str, tuple]] = [
    ("wte/embedding", ("vocab", "embed")),
    ("wpe/embedding", ("relpos", None)),
    (r"c_attn/kernel", ("embed", "heads")),
    (r"c_fc/kernel", ("embed", "mlp")),
    (r"attn/c_proj/kernel", ("heads", "embed")),
    (r"c_proj/kernel", ("mlp", "embed")),
    ("ln_", ("norm",)),
    (".*", (None,)),
]
PARTITION_RULES = to_partition_rules(PARAM_LOGICAL_AXES)

SCAN_PARAM_LOGICAL_AXES: list[tuple[str, tuple]] = [
    ("wte/embedding", ("vocab", "embed")),
    ("wpe/embedding", ("relpos", None)),
    (r"h/.*c_attn/kernel", ("layers", "embed", "heads")),
    (r"h/.*c_fc/kernel", ("layers", "embed", "mlp")),
    (r"h/.*attn/c_proj/kernel", ("layers", "heads", "embed")),
    (r"h/.*c_proj/kernel", ("layers", "mlp", "embed")),
    ("ln_", ("norm",)),
    (".*", (None,)),
]
SCAN_PARTITION_RULES = to_partition_rules(SCAN_PARAM_LOGICAL_AXES)


def _dt(config: GPT2Config):
    return jnp.dtype(config.dtype)


class GPT2Attention(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, hidden, attention_mask=None, position_ids=None,
                 init_cache=False, deterministic=True):
        cfg = self.config
        batch, seq, _ = hidden.shape
        n_head, head_dim = cfg.n_head, cfg.head_dim

        dense = lambda feats, name: nn.Dense(  # noqa: E731
            feats, dtype=_dt(cfg), param_dtype=jnp.dtype(cfg.param_dtype),
            kernel_init=nn.initializers.normal(cfg.initializer_range),
            name=name)
        qkv = dense(3 * cfg.n_embd, "c_attn")(hidden)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(batch, seq, n_head, head_dim)
        k = k.reshape(batch, seq, n_head, head_dim)
        v = v.reshape(batch, seq, n_head, head_dim)

        is_decode = self.has_variable("cache", "cached_key") or init_cache
        if is_decode:
            k, v, mask = self._update_cache(k, v, attention_mask)
            mask = mask[:, None]
        else:
            mask = causal_mask(seq, k.shape[1])[None, None]
            if attention_mask is not None:
                mask = mask & attention_mask[:, None, None, :].astype(bool)

        drop_rng = None
        if not deterministic and cfg.attn_pdrop > 0.0:
            drop_rng = self.make_rng("dropout")
        out = dot_product_attention(
            q, k, v, mask=mask, dropout_rng=drop_rng,
            dropout_rate=cfg.attn_pdrop, deterministic=deterministic)
        out = with_logical_constraint(
            out, ("batch", "seq", "heads", None))
        out = out.reshape(batch, seq, cfg.n_embd)
        out = dense(cfg.n_embd, "c_proj")(out)
        return nn.Dropout(cfg.resid_pdrop)(out, deterministic=deterministic)

    def _update_cache(self, k, v, attention_mask):
        cfg = self.config
        batch, seq, n_head, head_dim = k.shape
        max_len = cfg.n_positions
        is_initialized = self.has_variable("cache", "cached_key")
        cached_k = self.variable("cache", "cached_key", jnp.zeros,
                                 (batch, max_len, n_head, head_dim), k.dtype)
        cached_v = self.variable("cache", "cached_value", jnp.zeros,
                                 (batch, max_len, n_head, head_dim), v.dtype)
        cache_index = self.variable("cache", "cache_index",
                                    lambda: jnp.zeros((), jnp.int32))
        if not is_initialized:
            valid = jnp.broadcast_to(
                (jnp.arange(max_len) < seq)[None, None],
                (batch, seq, max_len))
            return k, v, valid[:, :, :seq]
        idx = cache_index.value
        k_all = jax.lax.dynamic_update_slice(cached_k.value, k,
                                             (0, idx, 0, 0))
        v_all = jax.lax.dynamic_update_slice(cached_v.value, v,
                                             (0, idx, 0, 0))
        cached_k.value, cached_v.value = k_all, v_all
        cache_index.value = idx + seq
        q_pos = idx + jnp.arange(seq)
        valid = jnp.arange(max_len)[None, :] <= q_pos[:, None]
        valid = jnp.broadcast_to(valid[None], (batch, seq, max_len))
        if attention_mask is not None:
            pad = jnp.ones((attention_mask.shape[0],
                            max_len - attention_mask.shape[1]),
                           attention_mask.dtype)
            full = jnp.concatenate([attention_mask, pad], axis=1)
            valid = valid & full[:, None, :].astype(bool)
        return k_all, v_all, valid


class GPT2Block(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, hidden, attention_mask=None, position_ids=None,
                 init_cache=False, deterministic=True):
        cfg = self.config
        h = LayerNorm(epsilon=cfg.layer_norm_epsilon, name="ln_1")(hidden)
        h = GPT2Attention(cfg, name="attn")(
            h, attention_mask, position_ids, init_cache, deterministic)
        hidden = hidden + h
        h = LayerNorm(epsilon=cfg.layer_norm_epsilon, name="ln_2")(hidden)
        dense = lambda feats, name: nn.Dense(  # noqa: E731
            feats, dtype=_dt(cfg), param_dtype=jnp.dtype(cfg.param_dtype),
            kernel_init=nn.initializers.normal(cfg.initializer_range),
            name=name)
        h = dense(cfg.inner_dim, "c_fc")(h)
        h = get_activation(cfg.activation_function)(h)
        h = with_logical_constraint(h, ("batch", "seq", "mlp"))
        h = dense(cfg.n_embd, "c_proj")(h)
        h = nn.Dropout(cfg.resid_pdrop)(h, deterministic=deterministic)
        return hidden + h


class _ScanGPT2Block(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, hidden, attention_mask, position_ids, init_cache,
                 deterministic):
        out = GPT2Block(self.config, name="block")(
            hidden, attention_mask, position_ids, init_cache, deterministic)
        return out, None


class GPT2Model(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, position_ids=None,
                 init_cache=False, deterministic=True):
        cfg = self.config
        wte = VocabParallelEmbed(
            cfg.vocab_size, cfg.n_embd, dtype=_dt(cfg),
            param_dtype=jnp.dtype(cfg.param_dtype),
            embedding_init=nn.initializers.normal(
                cfg.initializer_range), name="wte")
        wpe = nn.Embed(cfg.n_positions, cfg.n_embd, dtype=_dt(cfg),
                       param_dtype=jnp.dtype(cfg.param_dtype),
                       embedding_init=nn.initializers.normal(
                           cfg.initializer_range), name="wpe")
        if position_ids is None:
            position_ids = jnp.arange(input_ids.shape[1])[None, :]
        hidden = wte(input_ids) + wpe(position_ids)
        hidden = nn.Dropout(cfg.embd_pdrop)(hidden,
                                            deterministic=deterministic)
        hidden = with_logical_constraint(
            hidden, ("batch", "seq", None))

        if cfg.scan_layers:
            body = _ScanGPT2Block
            if cfg.gradient_checkpointing:
                body = nn.remat(body, static_argnums=(4, 5),
                                policy=jax.checkpoint_policies
                                .nothing_saveable, prevent_cse=False)
            scan = nn.scan(body, variable_axes={"params": 0, "cache": 0},
                           split_rngs={"params": True, "dropout": True},
                           in_axes=(nn.broadcast,) * 4, length=cfg.n_layer)
            hidden, _ = scan(cfg, name="h")(
                hidden, attention_mask, position_ids, init_cache,
                deterministic)
        else:
            block_cls = GPT2Block
            if cfg.gradient_checkpointing:
                block_cls = nn.remat(
                    GPT2Block, static_argnums=(4, 5),
                    policy=jax.checkpoint_policies.nothing_saveable)
            for i in range(cfg.n_layer):
                hidden = block_cls(cfg, name=f"h_{i}")(
                    hidden, attention_mask, position_ids, init_cache,
                    deterministic)
        return LayerNorm(epsilon=cfg.layer_norm_epsilon,
                         name="ln_f")(hidden)


class GPT2LMHeadModel(nn.Module):
    """LM head tied to wte (HF GPT2LMHeadModel semantics)."""

    config: GPT2Config

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, position_ids=None,
                 init_cache=False, deterministic=True,
                 return_hidden=False):
        hidden = GPT2Model(self.config, name="transformer")(
            input_ids, attention_mask, position_ids, init_cache,
            deterministic)
        if return_hidden:
            # the fused chunked LM-head+CE path applies the tied head
            # itself (see lm_head_kernel)
            return hidden
        wte = self.variables["params"]["transformer"]["wte"]["embedding"]
        return hidden @ wte.T.astype(hidden.dtype)

    @staticmethod
    def lm_head_kernel(params):
        """[H, V] head weight for the fused-CE path (tied to wte)."""
        return params["transformer"]["wte"]["embedding"].T

    def partition_rules(self):
        return to_partition_rules(
            SCAN_PARAM_LOGICAL_AXES if self.config.scan_layers
            else PARAM_LOGICAL_AXES)
