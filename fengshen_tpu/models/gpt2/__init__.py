"""GPT-2 / Wenzhong family.

The reference uses HF GPT2 directly for Wenzhong
(reference: fengshen/examples/wenzhong_qa/finetune_wenzhong.py); here it is
a native flax implementation with an HF torch weight importer.
"""

from fengshen_tpu.models.gpt2.configuration_gpt2 import GPT2Config
from fengshen_tpu.models.gpt2.modeling_gpt2 import GPT2Model, GPT2LMHeadModel

__all__ = ["GPT2Config", "GPT2Model", "GPT2LMHeadModel"]
