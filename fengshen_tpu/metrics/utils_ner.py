"""NER tag-sequence decoding.

Port of reference: fengshen/metric/utils_ner.py:103-250 — BIO/BIOS chunk
extraction and span-head/tail pairing (`bert_extract_item`).
"""

from __future__ import annotations

from typing import Sequence, Union


def get_entity_bio(seq: Sequence, id2label=None) -> list[list]:
    """[(type, start, end)] from a BIO tag sequence."""
    chunks: list[list] = []
    chunk = [-1, -1, -1]
    for i, tag in enumerate(seq):
        if not isinstance(tag, str):
            tag = id2label[tag] if id2label else str(tag)
        if tag.startswith("B-"):
            if chunk[2] != -1:
                chunks.append(chunk[:])
            chunk = [tag.split("-", 1)[1], i, i]
        elif tag.startswith("I-") and chunk[1] != -1:
            if tag.split("-", 1)[1] == chunk[0]:
                chunk[2] = i
        else:
            if chunk[2] != -1:
                chunks.append(chunk[:])
            chunk = [-1, -1, -1]
    if chunk[2] != -1:
        chunks.append(chunk[:])
    return chunks


def get_entity_bios(seq: Sequence, id2label=None) -> list[list]:
    """[(type, start, end)] from a BIOS tag sequence (S- singletons)."""
    chunks: list[list] = []
    chunk = [-1, -1, -1]
    for i, tag in enumerate(seq):
        if not isinstance(tag, str):
            tag = id2label[tag] if id2label else str(tag)
        if tag.startswith("S-"):
            if chunk[2] != -1:
                chunks.append(chunk[:])
            chunks.append([tag.split("-", 1)[1], i, i])
            chunk = [-1, -1, -1]
        elif tag.startswith("B-"):
            if chunk[2] != -1:
                chunks.append(chunk[:])
            chunk = [tag.split("-", 1)[1], i, i]
        elif tag.startswith("I-") and chunk[1] != -1:
            if tag.split("-", 1)[1] == chunk[0]:
                chunk[2] = i
        else:
            if chunk[2] != -1:
                chunks.append(chunk[:])
            chunk = [-1, -1, -1]
    if chunk[2] != -1:
        chunks.append(chunk[:])
    return chunks


def get_entities(seq, id2label=None, markup: str = "bios"):
    """Reference: utils_ner.py get_entities dispatch."""
    assert markup in ("bio", "bios")
    if markup == "bio":
        return get_entity_bio(seq, id2label)
    return get_entity_bios(seq, id2label)


def bert_extract_item(start_logits, end_logits) -> list[tuple]:
    """Pair span-head/tail predictions
    (reference: utils_ner.py bert_extract_item): for each start position
    with a non-O label, find the nearest end position with the same label."""
    import numpy as np
    S = []
    start_pred = np.asarray(start_logits).argmax(-1)[1:-1]
    end_pred = np.asarray(end_logits).argmax(-1)[1:-1]
    for i, s_l in enumerate(start_pred):
        if s_l == 0:
            continue
        for j, e_l in enumerate(end_pred[i:]):
            if s_l == e_l:
                S.append((int(s_l), i, i + j))
                break
    return S
