"""ROUGE-1/2/L for summarization eval.

The reference scores summaries with torchmetrics' ROUGEScore after
splitting Chinese into space-separated chars
(reference: fengshen/examples/summary/seq2seq_summary.py:12,87-96).
torchmetrics is not in this image, so the three standard variants are
implemented directly: n-gram overlap F-measure (rouge1/rouge2) and
LCS-based F-measure (rougeL), over whitespace-split tokens.
"""

from __future__ import annotations

from collections import Counter
from typing import Iterable


def _ngrams(tokens: list[str], n: int) -> Counter:
    return Counter(tuple(tokens[i:i + n])
                   for i in range(len(tokens) - n + 1))


def _fmeasure(match: int, pred_total: int, ref_total: int) -> float:
    if pred_total == 0 or ref_total == 0 or match == 0:
        return 0.0
    p = match / pred_total
    r = match / ref_total
    return 2 * p * r / (p + r)


def _lcs_len(a: list[str], b: list[str]) -> int:
    if not a or not b:
        return 0
    prev = [0] * (len(b) + 1)
    for x in a:
        cur = [0]
        for j, y in enumerate(b, 1):
            cur.append(prev[j - 1] + 1 if x == y
                       else max(prev[j], cur[-1]))
        prev = cur
    return prev[-1]


def rouge_n(pred: str, ref: str, n: int) -> float:
    p, r = pred.split(), ref.split()
    if len(p) < n or len(r) < n:
        return 0.0
    pg, rg = _ngrams(p, n), _ngrams(r, n)
    match = sum((pg & rg).values())
    return _fmeasure(match, sum(pg.values()), sum(rg.values()))


def rouge_l(pred: str, ref: str) -> float:
    p, r = pred.split(), ref.split()
    return _fmeasure(_lcs_len(p, r), len(p), len(r))


def chinese_char_split(text: str) -> str:
    """Space-separate chars so char-level ROUGE works for Chinese — the
    reference's normalisation before `rouge_metric.update`
    (reference: seq2seq_summary.py:87-91)."""
    return " ".join(list(text.replace(" ", "")))


def rouge_scores(preds: Iterable[str], refs: Iterable[str],
                 keys: tuple = ("rouge1", "rouge2", "rougeL"),
                 char_level: bool = True) -> dict:
    """Corpus-mean F-measures for the requested keys."""
    fns = {"rouge1": lambda p, r: rouge_n(p, r, 1),
           "rouge2": lambda p, r: rouge_n(p, r, 2),
           "rougeL": rouge_l}
    sums = {k: 0.0 for k in keys}
    count = 0
    for pred, ref in zip(preds, refs):
        if char_level:
            pred, ref = chinese_char_split(pred), chinese_char_split(ref)
        for k in keys:
            sums[k] += fns[k](pred, ref)
        count += 1
    return {f"{k}_fmeasure": (sums[k] / count if count else 0.0)
            for k in keys}
