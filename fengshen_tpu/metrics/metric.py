"""Task metrics.

Port of reference: fengshen/metric/metric.py:10-110 — `metrics_mlm_acc`,
`EntityScore` (span sets), `SeqEntityScore` (BIO-decoded P/R/F1).
"""

from __future__ import annotations

from collections import Counter

import numpy as np

from fengshen_tpu.metrics.utils_ner import get_entities


def metrics_mlm_acc(logits, labels, ignore_index: int = -100) -> float:
    """Accuracy over non-ignored MLM positions
    (reference: metric.py:10-25)."""
    logits = np.asarray(logits)
    labels = np.asarray(labels)
    preds = logits.argmax(-1)
    valid = labels != ignore_index
    if valid.sum() == 0:
        return 0.0
    return float(((preds == labels) & valid).sum() / valid.sum())


class _ScoreBase:
    def __init__(self):
        self.reset()

    def reset(self):
        self.origins: list = []
        self.founds: list = []
        self.rights: list = []

    @staticmethod
    def _prf(origin: int, found: int, right: int):
        recall = 0.0 if origin == 0 else right / origin
        precision = 0.0 if found == 0 else right / found
        f1 = 0.0 if recall + precision == 0 else \
            2 * precision * recall / (precision + recall)
        return round(recall, 4), round(precision, 4), round(f1, 4)

    def result(self):
        class_info = {}
        origin_counter = Counter(x[0] for x in self.origins)
        found_counter = Counter(x[0] for x in self.founds)
        right_counter = Counter(x[0] for x in self.rights)
        for label, count in origin_counter.items():
            found = found_counter.get(label, 0)
            right = right_counter.get(label, 0)
            recall, precision, f1 = self._prf(count, found, right)
            class_info[label] = {"acc": precision, "recall": recall,
                                 "f1": f1}
        recall, precision, f1 = self._prf(len(self.origins),
                                          len(self.founds),
                                          len(self.rights))
        return {"acc": precision, "recall": recall, "f1": f1}, class_info


class EntityScore(_ScoreBase):
    """Set-match span scoring (reference: metric.py EntityScore)."""

    def update(self, true_subject: list, pred_subject: list):
        self.origins.extend(true_subject)
        self.founds.extend(pred_subject)
        self.rights.extend([p for p in pred_subject if p in true_subject])


class SeqEntityScore(_ScoreBase):
    """BIO/BIOS-decoded sequence scoring
    (reference: metric.py SeqEntityScore)."""

    def __init__(self, id2label, markup: str = "bios"):
        self.id2label = id2label
        self.markup = markup
        super().__init__()

    def update(self, label_paths: list, pred_paths: list):
        for labels, preds in zip(label_paths, pred_paths):
            label_entities = get_entities(labels, self.id2label, self.markup)
            pred_entities = get_entities(preds, self.id2label, self.markup)
            self.origins.extend(label_entities)
            self.founds.extend(pred_entities)
            self.rights.extend(
                [p for p in pred_entities if p in label_entities])
