"""Metrics (reference: fengshen/metric/)."""

from fengshen_tpu.metrics.metric import (metrics_mlm_acc, EntityScore,
                                         SeqEntityScore)
from fengshen_tpu.metrics.utils_ner import (get_entities, bert_extract_item)

__all__ = ["metrics_mlm_acc", "EntityScore", "SeqEntityScore",
           "get_entities", "bert_extract_item"]
