"""End-to-end FewCLUE/ZeroCLUE quality harness for UniMC (VERDICT r2 #5).

One command takes a LOCAL UniMC checkpoint directory in the reference's
own format (config.json + pytorch_model.bin / Lightning .ckpt with the
HF MegatronBert naming, plus tokenizer files), imports it with
fengshen_tpu.models.unimc.convert, runs the CLUE task evals, and prints
the comparison table against the published UniMC-MegatronBERT-1.3B
numbers (reference: fengshen/examples/unimc/README.md:107-131 —
few-shot avg 72.05, zero-shot avg 64.53).

    python -m fengshen_tpu.metrics.clue_harness \
        --checkpoint /path/to/Erlangshen-UniMC-MegatronBERT-1.3B-Chinese \
        --data_dir /path/to/fewclue_unimc_json --split test_public

`data_dir` holds one `<task>.json(l)` per task, each line in the UniMC
data format (README.md:135-176): {texta, textb, question, choice,
label}. The encoding below replicates the reference UniMCDataset exactly
(modeling_unimc.py:140-231): '[MASK]'-joined options, block-diagonal
option attention, option-wise position restarts, yes-token scoring at
the option mask positions.
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Any, Optional

import numpy as np

# published UniMC-MegatronBERT-1.3B rows (README.md:107-131)
PUBLISHED = {
    "few_shot": {
        "eprstmt": 89.278, "csldcp": 60.9, "tnews": 57.46,
        "iflytek": 52.89, "ocnli": 76.33, "bustm": 80.37, "chid": 90.33,
        "csl": 61.73, "wsc": 79.15, "avg": 72.05},
    "zero_shot": {
        "eprstmt": 88.79, "csldcp": 42.06, "tnews": 55.21,
        "iflytek": 33.93, "ocnli": 75.57, "bustm": 79.5, "chid": 89.4,
        "csl": 50.25, "wsc": 66.67, "avg": 64.53},
}


from fengshen_tpu.models.unimc.modeling_unimc import (collate_unimc,
                                                      encode_unimc)


def load_unimc_checkpoint(ckpt_dir: str):
    """Reference-format dir → (UniMCModel, params, tokenizer)."""
    from transformers import AutoTokenizer

    from fengshen_tpu.models.megatron_bert import MegatronBertConfig
    from fengshen_tpu.models.unimc.convert import torch_to_params
    from fengshen_tpu.models.unimc.modeling_unimc import UniMCModel
    from fengshen_tpu.utils.convert_common import (detect_bert_arch,
                                                   load_torch_checkpoint,
                                                   unwrap_lightning)

    config = MegatronBertConfig.from_pretrained(ckpt_dir)
    state = load_torch_checkpoint(ckpt_dir)
    backbone_type = detect_bert_arch(unwrap_lightning(state))
    params = torch_to_params(state, config, backbone_type=backbone_type)
    tokenizer = AutoTokenizer.from_pretrained(ckpt_dir)
    yes_id = tokenizer.convert_tokens_to_ids("是")
    if yes_id is None or yes_id == tokenizer.unk_token_id:
        raise ValueError(
            f"tokenizer in {ckpt_dir} has no '是' token — yes-token "
            "scoring would silently read the [UNK] column")
    model = UniMCModel(config, yes_token_id=yes_id,
                       backbone_type=backbone_type)
    return model, params, tokenizer


def evaluate_task(model, params, items: list[dict], tokenizer,
                  batch_size: int = 8, max_length: int = 512) -> float:
    import jax.numpy as jnp

    correct = total = 0
    for i in range(0, len(items), batch_size):
        chunk = [encode_unimc(it, tokenizer, max_length)
                 for it in items[i:i + batch_size]]
        batch = collate_unimc(chunk)
        scores = model.apply(
            {"params": params}, jnp.asarray(batch["input_ids"]),
            attention_mask=jnp.asarray(batch["attention_mask"]),
            token_type_ids=jnp.asarray(batch["token_type_ids"]),
            option_positions=jnp.asarray(batch["option_positions"]),
            position_ids=jnp.asarray(batch["position_ids"]))
        scores = np.asarray(scores) + (batch["option_mask"] - 1) * 1e4
        pred = scores.argmax(-1)
        correct += int((pred == batch["labels"]).sum())
        total += len(chunk)
    return 100.0 * correct / max(total, 1)


def load_task_file(data_dir: str, task: str, split: str) -> list[dict]:
    for name in (f"{task}.jsonl", f"{task}.json",
                 os.path.join(task, f"{split}.json"),
                 os.path.join(task, f"{split}.jsonl")):
        path = os.path.join(data_dir, name)
        if os.path.exists(path):
            with open(path) as f:
                text = f.read().strip()
            if text.startswith("["):
                return json.loads(text)
            return [json.loads(line) for line in text.splitlines()
                    if line.strip()]
    return []


def run(checkpoint: str, data_dir: str, split: str = "test_public",
        mode: str = "zero_shot", tasks: Optional[list[str]] = None,
        batch_size: int = 8, max_length: int = 512,
        model_params_tok: Optional[tuple] = None) -> dict:
    """Returns {task: accuracy}; prints the comparison table."""
    if model_params_tok is not None:
        model, params, tokenizer = model_params_tok
    else:
        model, params, tokenizer = load_unimc_checkpoint(checkpoint)
    published = PUBLISHED[mode]
    tasks = tasks or [t for t in published if t != "avg"]
    results: dict[str, Any] = {}
    for task in tasks:
        items = load_task_file(data_dir, task, split)
        if not items:
            print(f"[clue-harness] {task}: no data file, skipped")
            continue
        results[task] = evaluate_task(model, params, items, tokenizer,
                                      batch_size, max_length)
    if results:
        results["avg"] = float(np.mean([results[t] for t in results]))

    header = f"{'task':10s} {'ours':>8s} {'published':>10s} {'delta':>8s}"
    print(header)
    print("-" * len(header))
    for task, acc in results.items():
        pub = published.get(task)
        delta = f"{acc - pub:+8.2f}" if pub is not None else "       -"
        pub_s = f"{pub:10.2f}" if pub is not None else "         -"
        print(f"{task:10s} {acc:8.2f} {pub_s} {delta}")
    return results


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="UniMC FewCLUE/ZeroCLUE quality harness")
    parser.add_argument("--checkpoint", required=True,
                        help="reference-format UniMC checkpoint dir")
    parser.add_argument("--data_dir", required=True,
                        help="dir of <task>.json(l) files in UniMC format")
    parser.add_argument("--split", default="test_public")
    parser.add_argument("--mode", default="zero_shot",
                        choices=["few_shot", "zero_shot"])
    parser.add_argument("--tasks", default=None,
                        help="comma-separated subset")
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--max_length", type=int, default=512)
    args = parser.parse_args(argv)
    tasks = args.tasks.split(",") if args.tasks else None
    run(args.checkpoint, args.data_dir, args.split, args.mode, tasks,
        args.batch_size, args.max_length)


if __name__ == "__main__":
    main()
