"""Vocab-parallel cross entropy.

TPU-native port of the reference's numerically-stable softmax CE over a
vocab-sharded logits tensor (reference:
fengshen/models/megatron/mpu/cross_entropy.py:27-117): global max via
allreduce(MAX), per-shard target masking, sum-exp allreduce. Here the
collectives are `jax.lax.psum`/`pmax` inside `shard_map` over the 'tensor'
mesh axis, and the backward pass comes from autodiff instead of a
hand-written autograd.Function.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from fengshen_tpu.compat import shard_map

from fengshen_tpu.parallel.mesh import (BATCH_AXES, SEQUENCE_AXIS,
                                        TENSOR_AXIS, get_mesh)


def stable_cross_entropy(logits: jax.Array, targets: jax.Array,
                         ignore_index: int = -100) -> tuple[jax.Array, jax.Array]:
    """Replicated-logits CE with -100 masking (HF convention used throughout
    the reference's examples, e.g. reference:
    fengshen/models/llama/modeling_llama.py:334-339).

    Returns (mean_loss, n_valid_tokens).
    """
    valid = targets != ignore_index
    safe_targets = jnp.where(valid, targets, 0)
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, safe_targets[..., None], axis=-1)[..., 0]
    token_loss = (logz - gold) * valid
    n_valid = jnp.maximum(valid.sum(), 1)
    return token_loss.sum() / n_valid, valid.sum()


def _sharded_ce_block(logits: jax.Array, targets: jax.Array,
                      axis_name: str, ignore_index: int) -> jax.Array:
    """Per-shard CE body: logits [..., V/t] local shard, targets global ids."""
    vocab_shard = logits.shape[-1]
    rank = jax.lax.axis_index(axis_name)
    vocab_start = rank * vocab_shard

    logits = logits.astype(jnp.float32)
    # global max for stability (reference: mpu/cross_entropy.py:36-41);
    # gradient-neutral, and pmax has no differentiation rule, so detach
    local_max = jax.lax.stop_gradient(logits.max(axis=-1))
    global_max = jax.lax.pmax(local_max, axis_name)
    shifted = logits - global_max[..., None]
    sum_exp = jax.lax.psum(jnp.exp(shifted).sum(axis=-1), axis_name)

    # gold logit lives on exactly one shard
    # (reference: mpu/cross_entropy.py:49-67 target masking)
    local_t = targets - vocab_start
    in_shard = (local_t >= 0) & (local_t < vocab_shard)
    safe_t = jnp.clip(local_t, 0, vocab_shard - 1)
    gold_local = jnp.take_along_axis(shifted, safe_t[..., None], axis=-1)[..., 0]
    gold = jax.lax.psum(jnp.where(in_shard, gold_local, 0.0), axis_name)

    return jnp.log(sum_exp) - gold


def _leading_dims_spec(shape: tuple, mesh: Mesh) -> list:
    """Mesh axes for the leading (batch, seq, ...) dims: the batch dim over
    whichever BATCH_AXES divide it, the sequence dim over 'sequence'; an
    axis is only used when its size divides the dim (spec must fit shape)."""
    dims: list = []
    axes, div = [], 1
    for ax in BATCH_AXES:
        size = mesh.shape.get(ax, 1)
        if size > 1 and shape[0] % (div * size) == 0:
            axes.append(ax)
            div *= size
    dims.append(tuple(axes) if axes else None)
    for d in range(1, len(shape)):
        seq_size = mesh.shape.get(SEQUENCE_AXIS, 1)
        if d == 1 and seq_size > 1 and shape[1] % seq_size == 0:
            dims.append(SEQUENCE_AXIS)
        else:
            dims.append(None)
    return dims


def vocab_parallel_cross_entropy(logits: jax.Array, targets: jax.Array,
                                 mesh: Optional[Mesh] = None,
                                 ignore_index: int = -100) -> tuple[jax.Array, jax.Array]:
    """CE over logits sharded on the last (vocab) dim along the 'tensor' axis.

    Avoids materialising the all-gathered [B, S, V] logits that the
    reference's ``parallel_output=False`` eval path pays for
    (reference: fengshen/models/megatron/layers/transformer.py:800-815).
    Falls back to the replicated implementation when no mesh / no tensor
    parallelism is active.
    """
    mesh = mesh or get_mesh()
    if mesh is None or TENSOR_AXIS not in mesh.shape or mesh.shape[TENSOR_AXIS] == 1:
        return stable_cross_entropy(logits, targets, ignore_index)
    if logits.shape[-1] % mesh.shape[TENSOR_AXIS] != 0:
        return stable_cross_entropy(logits, targets, ignore_index)

    # Keep the batch/sequence dims sharded inside the shard_map (the normal
    # training layout shards them over data/fsdp/sequence); replicating them
    # here would force an all-gather of the [B, S, V/t] logits along the
    # batch axes and inflate per-device memory for no reason.
    lead = _leading_dims_spec(targets.shape, mesh)
    batch_spec = P(*lead)
    logits_spec = P(*lead, TENSOR_AXIS)

    token_loss = shard_map(
        partial(_sharded_ce_block, axis_name=TENSOR_AXIS,
                ignore_index=ignore_index),
        mesh=mesh,
        in_specs=(logits_spec, batch_spec),
        out_specs=batch_spec,
        check_vma=False,
    )(logits, targets)

    valid = targets != ignore_index
    token_loss = token_loss * valid
    n_valid = jnp.maximum(valid.sum(), 1)
    return token_loss.sum() / n_valid, valid.sum()


def _fused_sharded_block(hidden: jax.Array, kernel: jax.Array,
                         targets: jax.Array, *, axis_name: str,
                         num_chunks: int, ignore_index: int):
    """Per-shard fused LM-head + CE body: hidden ``[b, s, H]`` (local
    batch/seq shard), kernel ``[H, V/t]`` (local vocab shard), targets
    global ids. Runs the head matmul per sequence chunk inside a
    ``lax.scan`` with ``jax.checkpoint`` (the ops/fused_ce.py scheme),
    so only one ``[b, chunk, V/t]`` logits slice is ever live; each
    chunk's CE reuses :func:`_sharded_ce_block` verbatim — per-token
    reductions are row-independent, which is what keeps the chunked
    loss bitwise equal to the whole-sequence one.

    Returns per-token ``(loss, predicted id)`` — the global argmax
    (pmax on the value, pmin on the candidate id) follows
    ``jnp.argmax``'s lowest-index tie rule across shards."""
    b, s, hd = hidden.shape
    vocab_shard = kernel.shape[-1]
    vocab_start = jax.lax.axis_index(axis_name) * vocab_shard
    nc = min(num_chunks, s)
    padded = s
    if s % nc:
        pad = nc - s % nc
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)),
                          constant_values=ignore_index)
        padded = s + pad
    chunk = padded // nc
    hidden_c = jnp.moveaxis(hidden.reshape(b, nc, chunk, hd), 1, 0)
    targets_c = jnp.moveaxis(targets.reshape(b, nc, chunk), 1, 0)

    @jax.checkpoint
    def chunk_stats(h, t):
        # the ONLY live logits: one [b, chunk, V/t] slice
        logits = h @ kernel
        token_loss = _sharded_ce_block(logits, t, axis_name,
                                       ignore_index)
        f32 = logits.astype(jnp.float32)
        local_max = jax.lax.stop_gradient(f32.max(-1))
        local_arg = f32.argmax(-1).astype(jnp.int32) + vocab_start
        global_max = jax.lax.pmax(local_max, axis_name)
        candidate = jnp.where(local_max == global_max, local_arg,
                              jnp.int32(2**31 - 1))
        pred = jax.lax.pmin(candidate, axis_name)
        return token_loss, pred

    def body(carry, xs):
        h, t = xs
        return carry, chunk_stats(h, t)

    _, (token_loss, pred) = lax.scan(body, None, (hidden_c, targets_c))
    token_loss = jnp.moveaxis(token_loss, 0, 1).reshape(b, padded)[:, :s]
    pred = jnp.moveaxis(pred, 0, 1).reshape(b, padded)[:, :s]
    return token_loss, pred


def fused_vocab_parallel_ce(hidden: jax.Array, kernel: jax.Array,
                            targets: jax.Array,
                            mesh: Optional[Mesh] = None,
                            num_chunks: int = 8,
                            ignore_index: int = -100
                            ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Fused LM-head + CE over a vocab-SHARDED head: hidden ``[B, S,
    H]`` @ kernel ``[H, V]`` (sharded on V along 'tensor') scored
    against targets ``[B, S]`` → (mean_loss, n_valid, n_correct).

    The upgrade the kernel layer brings to this module
    (docs/kernels.md): under tensor parallelism the trainer previously
    had to materialize the full sharded ``[B, S, V/t]`` logits tensor
    to feed :func:`vocab_parallel_cross_entropy`; this runs the head
    matmul chunk-by-chunk inside the shard, so peak logits memory
    drops by the chunk factor AND the vocab stays sharded — the mpu
    collectives (global max / sum-exp / gold psum) are unchanged,
    reused per chunk, which keeps the loss bitwise equal to the
    unfused path. Falls back to the replicated fused seam
    (``ops.pallas.fused_ce_loss``) when no mesh / no tensor axis /
    vocab not divisible."""
    mesh = mesh or get_mesh()
    tensor = 0 if mesh is None else mesh.shape.get(TENSOR_AXIS, 1)
    if mesh is None or tensor <= 1 or kernel.shape[-1] % tensor != 0:
        from fengshen_tpu.ops.pallas.fused_ce import fused_ce_loss
        return fused_ce_loss(hidden, kernel, targets,
                             num_chunks=num_chunks,
                             ignore_index=ignore_index)
    lead = _leading_dims_spec(targets.shape, mesh)
    batch_spec = P(*lead)

    token_loss, pred = shard_map(
        partial(_fused_sharded_block, axis_name=TENSOR_AXIS,
                num_chunks=num_chunks, ignore_index=ignore_index),
        mesh=mesh,
        in_specs=(P(*lead, None), P(None, TENSOR_AXIS), batch_spec),
        out_specs=(batch_spec, batch_spec),
        check_vma=False,
    )(hidden, kernel, targets)

    valid = targets != ignore_index
    token_loss = token_loss * valid
    n_valid = jnp.maximum(valid.sum(), 1)
    n_correct = ((pred == targets) & valid).sum()
    return token_loss.sum() / n_valid, valid.sum(), n_correct
