"""Vocab-parallel cross entropy.

TPU-native port of the reference's numerically-stable softmax CE over a
vocab-sharded logits tensor (reference:
fengshen/models/megatron/mpu/cross_entropy.py:27-117): global max via
allreduce(MAX), per-shard target masking, sum-exp allreduce. Here the
collectives are `jax.lax.psum`/`pmax` inside `shard_map` over the 'tensor'
mesh axis, and the backward pass comes from autodiff instead of a
hand-written autograd.Function.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from fengshen_tpu.compat import shard_map

from fengshen_tpu.parallel.mesh import (BATCH_AXES, SEQUENCE_AXIS,
                                        TENSOR_AXIS, get_mesh)


def stable_cross_entropy(logits: jax.Array, targets: jax.Array,
                         ignore_index: int = -100) -> tuple[jax.Array, jax.Array]:
    """Replicated-logits CE with -100 masking (HF convention used throughout
    the reference's examples, e.g. reference:
    fengshen/models/llama/modeling_llama.py:334-339).

    Returns (mean_loss, n_valid_tokens).
    """
    valid = targets != ignore_index
    safe_targets = jnp.where(valid, targets, 0)
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, safe_targets[..., None], axis=-1)[..., 0]
    token_loss = (logz - gold) * valid
    n_valid = jnp.maximum(valid.sum(), 1)
    return token_loss.sum() / n_valid, valid.sum()


def _sharded_ce_block(logits: jax.Array, targets: jax.Array,
                      axis_name: str, ignore_index: int) -> jax.Array:
    """Per-shard CE body: logits [..., V/t] local shard, targets global ids."""
    vocab_shard = logits.shape[-1]
    rank = jax.lax.axis_index(axis_name)
    vocab_start = rank * vocab_shard

    logits = logits.astype(jnp.float32)
    # global max for stability (reference: mpu/cross_entropy.py:36-41);
    # gradient-neutral, and pmax has no differentiation rule, so detach
    local_max = jax.lax.stop_gradient(logits.max(axis=-1))
    global_max = jax.lax.pmax(local_max, axis_name)
    shifted = logits - global_max[..., None]
    sum_exp = jax.lax.psum(jnp.exp(shifted).sum(axis=-1), axis_name)

    # gold logit lives on exactly one shard
    # (reference: mpu/cross_entropy.py:49-67 target masking)
    local_t = targets - vocab_start
    in_shard = (local_t >= 0) & (local_t < vocab_shard)
    safe_t = jnp.clip(local_t, 0, vocab_shard - 1)
    gold_local = jnp.take_along_axis(shifted, safe_t[..., None], axis=-1)[..., 0]
    gold = jax.lax.psum(jnp.where(in_shard, gold_local, 0.0), axis_name)

    return jnp.log(sum_exp) - gold


def _leading_dims_spec(shape: tuple, mesh: Mesh) -> list:
    """Mesh axes for the leading (batch, seq, ...) dims: the batch dim over
    whichever BATCH_AXES divide it, the sequence dim over 'sequence'; an
    axis is only used when its size divides the dim (spec must fit shape)."""
    dims: list = []
    axes, div = [], 1
    for ax in BATCH_AXES:
        size = mesh.shape.get(ax, 1)
        if size > 1 and shape[0] % (div * size) == 0:
            axes.append(ax)
            div *= size
    dims.append(tuple(axes) if axes else None)
    for d in range(1, len(shape)):
        seq_size = mesh.shape.get(SEQUENCE_AXIS, 1)
        if d == 1 and seq_size > 1 and shape[1] % seq_size == 0:
            dims.append(SEQUENCE_AXIS)
        else:
            dims.append(None)
    return dims


def vocab_parallel_cross_entropy(logits: jax.Array, targets: jax.Array,
                                 mesh: Optional[Mesh] = None,
                                 ignore_index: int = -100) -> tuple[jax.Array, jax.Array]:
    """CE over logits sharded on the last (vocab) dim along the 'tensor' axis.

    Avoids materialising the all-gathered [B, S, V] logits that the
    reference's ``parallel_output=False`` eval path pays for
    (reference: fengshen/models/megatron/layers/transformer.py:800-815).
    Falls back to the replicated implementation when no mesh / no tensor
    parallelism is active.
    """
    mesh = mesh or get_mesh()
    if mesh is None or TENSOR_AXIS not in mesh.shape or mesh.shape[TENSOR_AXIS] == 1:
        return stable_cross_entropy(logits, targets, ignore_index)
    if logits.shape[-1] % mesh.shape[TENSOR_AXIS] != 0:
        return stable_cross_entropy(logits, targets, ignore_index)

    # Keep the batch/sequence dims sharded inside the shard_map (the normal
    # training layout shards them over data/fsdp/sequence); replicating them
    # here would force an all-gather of the [B, S, V/t] logits along the
    # batch axes and inflate per-device memory for no reason.
    lead = _leading_dims_spec(targets.shape, mesh)
    batch_spec = P(*lead)
    logits_spec = P(*lead, TENSOR_AXIS)

    token_loss = shard_map(
        partial(_sharded_ce_block, axis_name=TENSOR_AXIS,
                ignore_index=ignore_index),
        mesh=mesh,
        in_specs=(logits_spec, batch_spec),
        out_specs=batch_spec,
        check_vma=False,
    )(logits, targets)

    valid = targets != ignore_index
    token_loss = token_loss * valid
    n_valid = jnp.maximum(valid.sum(), 1)
    return token_loss.sum() / n_valid, valid.sum()
