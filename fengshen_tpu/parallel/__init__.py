"""Parallelism core: device mesh + GSPMD partition rules.

TPU-native replacement for the reference's Megatron ``mpu`` package
(reference: fengshen/models/megatron/mpu/__init__.py:17-54). Process groups
become mesh axes; ``ColumnParallelLinear``/``RowParallelLinear`` collapse into
PartitionSpec rules; NCCL collectives become XLA collectives emitted by GSPMD.
"""

from fengshen_tpu.parallel.mesh import (
    MeshConfig,
    make_mesh,
    get_mesh,
    set_mesh,
    mesh_shape_for_devices,
    distributed_initialize,
    data_parallel_rank,
    data_parallel_world_size,
    DATA_AXIS,
    FSDP_AXIS,
    PIPE_AXIS,
    SEQUENCE_AXIS,
    TENSOR_AXIS,
    EXPERT_AXIS,
    BATCH_AXES,
)
from fengshen_tpu.parallel.partition import (
    match_partition_rules,
    make_shardings,
    with_sharding_constraint,
    named_sharding,
    shard_batch_spec,
    tree_paths,
)
from fengshen_tpu.parallel.cross_entropy import (
    fused_vocab_parallel_ce,
    vocab_parallel_cross_entropy,
)
from fengshen_tpu.parallel.pipeline import (pipeline_apply,
                                            pipeline_train_step_1f1b)

__all__ = [
    "MeshConfig",
    "make_mesh",
    "get_mesh",
    "set_mesh",
    "mesh_shape_for_devices",
    "DATA_AXIS",
    "FSDP_AXIS",
    "SEQUENCE_AXIS",
    "TENSOR_AXIS",
    "EXPERT_AXIS",
    "BATCH_AXES",
    "match_partition_rules",
    "make_shardings",
    "with_sharding_constraint",
    "named_sharding",
    "shard_batch_spec",
    "tree_paths",
    "fused_vocab_parallel_ce",
    "vocab_parallel_cross_entropy",
    "pipeline_apply",
    "pipeline_train_step_1f1b",
    "distributed_initialize",
    "data_parallel_rank",
    "data_parallel_world_size",
    "PIPE_AXIS",
]
