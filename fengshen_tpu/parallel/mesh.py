"""Device-mesh bootstrap.

Replaces the reference's process-topology engine
(reference: fengshen/models/megatron/mpu/initialize.py:61-167 builds
_MODEL/_DATA/_PIPE/_IO parallel NCCL groups from a DeepSpeed
PipeModelDataParallelTopology). Here the whole topology is a single
``jax.sharding.Mesh`` whose named axes play the role of the groups:

- ``data``     — data parallelism (reference _DATA_PARALLEL_GROUP)
- ``fsdp``     — ZeRO-style parameter/optimizer-state sharding (reference:
  DeepSpeed ZeRO stages, fengshen/strategies/megatron_deepspeed.py:55-104)
- ``sequence`` — context parallelism over sequence (no reference equivalent;
  fills the long-context gap noted in SURVEY.md §5.7)
- ``tensor``   — tensor parallelism (reference _MODEL_PARALLEL_GROUP)

Axis order matters: the innermost (last) mesh axis maps to the
fastest/nearest ICI neighbours — the same reasoning as the reference putting
the model group innermost so TP rides NVLink
(reference: fengshen/strategies/megatron_deepspeed.py:347-354).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
PIPE_AXIS = "pipe"
SEQUENCE_AXIS = "sequence"
TENSOR_AXIS = "tensor"
EXPERT_AXIS = "expert"

#: canonical axis order, outermost (slowest links, DCN) first; pipeline
#: sits between the batch axes and sequence/tensor (stage hops are
#: infrequent point-to-point transfers, Megatron's pp-outside-tp layout);
#: expert sits next to the batch axes (MoE dispatch is an all-to-all over
#: tokens, which rides the same links the batch is sharded over)
MESH_AXES = (DATA_AXIS, FSDP_AXIS, EXPERT_AXIS, PIPE_AXIS, SEQUENCE_AXIS,
             TENSOR_AXIS)

#: axes over which the global batch is sharded (a batch dim is split over all
#: of these; this is what DeepSpeed called the "data parallel world")
BATCH_AXES = (DATA_AXIS, FSDP_AXIS)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical parallelism degrees.

    The reference exposes ``tensor_model_parallel_size`` /
    ``pipe_model_parallel_size`` on its strategy ctor
    (reference: fengshen/strategies/megatron_deepspeed.py:55-104) and derives
    dp = world // pp // tp. We do the same with dp derived from the device
    count, plus fsdp and sequence degrees that the reference lacks.
    """

    data: int = -1  # -1: derive from device count
    fsdp: int = 1
    expert: int = 1
    pipe: int = 1
    sequence: int = 1
    tensor: int = 1

    @staticmethod
    def add_argparse_args(parent_parser):
        parser = parent_parser.add_argument_group("MeshConfig")
        parser.add_argument("--data_parallel_size", default=-1, type=int)
        parser.add_argument("--fsdp_parallel_size", default=1, type=int)
        parser.add_argument(
            "--pipe_model_parallel_size", default=1, type=int,
            help="pipeline-parallel degree (same flag name as the "
                 "reference's DeepSpeed topology)")
        parser.add_argument("--sequence_parallel_size", default=1, type=int)
        parser.add_argument(
            "--expert_parallel_size", default=1, type=int,
            help="expert-parallel degree for MoE layers (no reference "
                 "equivalent; experts shard over this axis)")
        parser.add_argument(
            "--tensor_model_parallel_size", default=1, type=int,
            help="tensor-parallel degree (same flag name as the reference)")
        return parent_parser

    @classmethod
    def from_argparse_args(cls, args) -> "MeshConfig":
        return cls(
            data=getattr(args, "data_parallel_size", -1),
            fsdp=getattr(args, "fsdp_parallel_size", 1),
            expert=getattr(args, "expert_parallel_size", 1),
            pipe=getattr(args, "pipe_model_parallel_size", 1),
            sequence=getattr(args, "sequence_parallel_size", 1),
            tensor=getattr(args, "tensor_model_parallel_size", 1),
        )

    def resolve(self, n_devices: int) -> tuple[int, int, int, int, int, int]:
        """Concrete (data, fsdp, expert, pipe, sequence, tensor)."""
        fixed = (self.fsdp * self.expert * self.pipe * self.sequence *
                 self.tensor)
        if n_devices % fixed != 0:
            raise ValueError(
                f"device count {n_devices} not divisible by "
                f"fsdp*expert*pipe*sequence*tensor = {fixed}")
        data = self.data if self.data > 0 else n_devices // fixed
        if data * fixed != n_devices:
            raise ValueError(
                f"mesh {data}x{self.fsdp}x{self.expert}x{self.pipe}"
                f"x{self.sequence}x{self.tensor} != device count "
                f"{n_devices}")
        return (data, self.fsdp, self.expert, self.pipe, self.sequence,
                self.tensor)


def mesh_shape_for_devices(config: MeshConfig,
                           n_devices: Optional[int] = None) -> tuple[int, ...]:
    if n_devices is None:
        n_devices = len(jax.devices())
    return config.resolve(n_devices)


def make_mesh(config: Optional[MeshConfig] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the global device mesh.

    Replaces ``mpu.initialize_model_parallel``
    (reference: fengshen/models/megatron/mpu/initialize.py:61-167).
    ``jax.make_mesh`` lays axes out so the last axis is ICI-contiguous.
    """
    config = config or MeshConfig()
    if devices is None:
        devices = jax.devices()
    shape = config.resolve(len(devices))
    # Auto axis types: we drive sharding with GSPMD constraints + shard_map,
    # not the explicit-sharding type system. jax<0.6 has no AxisType (Auto
    # is the only behavior there), so the kwarg is gated.
    axis_type = getattr(jax.sharding, "AxisType", None)
    kwargs = {} if axis_type is None else \
        {"axis_types": (axis_type.Auto,) * len(MESH_AXES)}
    try:
        if list(devices) == list(jax.devices()):
            return jax.make_mesh(shape, MESH_AXES, **kwargs)
    except Exception:  # pragma: no cover - make_mesh can reject odd topologies
        pass
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, MESH_AXES, **kwargs)


_GLOBAL_MESH: Optional[Mesh] = None


def set_mesh(mesh: Optional[Mesh]) -> None:
    """Install the process-global mesh (analog of mpu's module globals,
    reference: fengshen/models/megatron/mpu/initialize.py:33-45)."""
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh


def get_mesh() -> Optional[Mesh]:
    """Current process-global mesh, or None outside distributed contexts."""
    return _GLOBAL_MESH


def distributed_initialize(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host bootstrap.

    Replaces the reference's SLURM/NCCL cluster-environment dance
    (reference: fengshen/strategies/megatron_deepspeed.py:345-346 +
    torch.distributed init): one call, and every host sees the global
    device set; GSPMD handles cross-host collectives over ICI/DCN.
    No-op when running single-process (the common dev path).
    """
    if num_processes is None:
        num_processes = int(os.environ.get("FSTPU_NUM_PROCESSES", "1"))
    if num_processes <= 1 and coordinator_address is None:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def _host_batch_groups(proc_ids: np.ndarray, data_idx: int,
                       fsdp_idx: int) -> dict:
    """process id → set of flattened (data, fsdp) batch coordinates its
    devices cover. Pure (drives the multi-host property tests with
    synthetic layouts — no real processes needed)."""
    fsdp_size = proc_ids.shape[fsdp_idx]
    groups: dict = {}
    for idx in np.ndindex(proc_ids.shape):
        coord = idx[data_idx] * fsdp_size + idx[fsdp_idx]
        groups.setdefault(int(proc_ids[idx]), set()).add(coord)
    return groups


def _dp_rank_world_from_groups(groups: dict, pid: int) -> tuple[int, int]:
    """(data rank, world size) from host batch-coordinate groups.

    Hosts whose devices cover the SAME coordinate set are one replica
    group (they must load identical data); distinct sets are ordered by
    their smallest coordinate, so ranks are dense and every coordinate
    belongs to exactly one rank. Unlike the previous contiguous-range
    shortcut this survives reversed or interleaved device→process
    layouts, and partially-overlapping groups — a layout where
    host-level data sharding is ill-defined — fail LOUDLY instead of
    silently mis-sharding (VERDICT r4 weak #5)."""
    mine = frozenset(groups[pid])
    distinct: list = []
    for s in groups.values():
        fs = frozenset(s)
        if fs not in distinct:
            for other in distinct:
                if fs & other:
                    raise ValueError(
                        "host batch-coordinate groups overlap partially "
                        f"({sorted(fs)[:4]}… vs {sorted(other)[:4]}…): "
                        "this device→process layout does not admit "
                        "host-level data sharding; use a mesh whose "
                        "(data, fsdp) coordinates are host-aligned")
            distinct.append(fs)
    distinct.sort(key=min)
    return distinct.index(mine), len(distinct)


def _mesh_proc_ids(mesh: Mesh) -> tuple[np.ndarray, int, int]:
    axes = list(mesh.axis_names)
    proc_ids = np.vectorize(lambda d: d.process_index)(mesh.devices)
    return proc_ids, axes.index(DATA_AXIS), axes.index(FSDP_AXIS)


def data_parallel_rank(mesh: Mesh) -> int:
    """This host's position among the distinct batch-shard groups — used by
    the resumable samplers the same way the reference uses
    ``mpu.get_data_parallel_rank()``
    (reference: fengshen/data/universal_datamodule/universal_datamodule.py:84-85).

    Mesh-aware: when a model-parallel axis spans hosts, two hosts that hold
    the same batch coordinates get the SAME rank (they are one replica and
    must load identical data), unlike a naive ``jax.process_index()``.
    """
    if jax.process_count() == 1:
        return 0
    groups = _host_batch_groups(*_mesh_proc_ids(mesh))
    return _dp_rank_world_from_groups(groups, jax.process_index())[0]


def data_parallel_world_size(mesh: Mesh) -> int:
    """Number of distinct host-level batch-shard groups."""
    if jax.process_count() == 1:
        return 1
    groups = _host_batch_groups(*_mesh_proc_ids(mesh))
    return _dp_rank_world_from_groups(groups, jax.process_index())[1]
