"""Device-mesh bootstrap.

Replaces the reference's process-topology engine
(reference: fengshen/models/megatron/mpu/initialize.py:61-167 builds
_MODEL/_DATA/_PIPE/_IO parallel NCCL groups from a DeepSpeed
PipeModelDataParallelTopology). Here the whole topology is a single
``jax.sharding.Mesh`` whose named axes play the role of the groups:

- ``data``     — data parallelism (reference _DATA_PARALLEL_GROUP)
- ``fsdp``     — ZeRO-style parameter/optimizer-state sharding (reference:
  DeepSpeed ZeRO stages, fengshen/strategies/megatron_deepspeed.py:55-104)
- ``sequence`` — context parallelism over sequence (no reference equivalent;
  fills the long-context gap noted in SURVEY.md §5.7)
- ``tensor``   — tensor parallelism (reference _MODEL_PARALLEL_GROUP)

Axis order matters: the innermost (last) mesh axis maps to the
fastest/nearest ICI neighbours — the same reasoning as the reference putting
the model group innermost so TP rides NVLink
(reference: fengshen/strategies/megatron_deepspeed.py:347-354).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
PIPE_AXIS = "pipe"
SEQUENCE_AXIS = "sequence"
TENSOR_AXIS = "tensor"
EXPERT_AXIS = "expert"

#: canonical axis order, outermost (slowest links, DCN) first; pipeline
#: sits between the batch axes and sequence/tensor (stage hops are
#: infrequent point-to-point transfers, Megatron's pp-outside-tp layout);
#: expert sits next to the batch axes (MoE dispatch is an all-to-all over
#: tokens, which rides the same links the batch is sharded over)
MESH_AXES = (DATA_AXIS, FSDP_AXIS, EXPERT_AXIS, PIPE_AXIS, SEQUENCE_AXIS,
             TENSOR_AXIS)

#: axes over which the global batch is sharded (a batch dim is split over all
#: of these; this is what DeepSpeed called the "data parallel world")
BATCH_AXES = (DATA_AXIS, FSDP_AXIS)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical parallelism degrees.

    The reference exposes ``tensor_model_parallel_size`` /
    ``pipe_model_parallel_size`` on its strategy ctor
    (reference: fengshen/strategies/megatron_deepspeed.py:55-104) and derives
    dp = world // pp // tp. We do the same with dp derived from the device
    count, plus fsdp and sequence degrees that the reference lacks.
    """

    data: int = -1  # -1: derive from device count
    fsdp: int = 1
    expert: int = 1
    pipe: int = 1
    sequence: int = 1
    tensor: int = 1

    @staticmethod
    def add_argparse_args(parent_parser):
        parser = parent_parser.add_argument_group("MeshConfig")
        parser.add_argument("--data_parallel_size", default=-1, type=int)
        parser.add_argument("--fsdp_parallel_size", default=1, type=int)
        parser.add_argument(
            "--pipe_model_parallel_size", default=1, type=int,
            help="pipeline-parallel degree (same flag name as the "
                 "reference's DeepSpeed topology)")
        parser.add_argument("--sequence_parallel_size", default=1, type=int)
        parser.add_argument(
            "--expert_parallel_size", default=1, type=int,
            help="expert-parallel degree for MoE layers (no reference "
                 "equivalent; experts shard over this axis)")
        parser.add_argument(
            "--tensor_model_parallel_size", default=1, type=int,
            help="tensor-parallel degree (same flag name as the reference)")
        return parent_parser

    @classmethod
    def from_argparse_args(cls, args) -> "MeshConfig":
        return cls(
            data=getattr(args, "data_parallel_size", -1),
            fsdp=getattr(args, "fsdp_parallel_size", 1),
            expert=getattr(args, "expert_parallel_size", 1),
            pipe=getattr(args, "pipe_model_parallel_size", 1),
            sequence=getattr(args, "sequence_parallel_size", 1),
            tensor=getattr(args, "tensor_model_parallel_size", 1),
        )

    def resolve(self, n_devices: int) -> tuple[int, int, int, int, int, int]:
        """Concrete (data, fsdp, expert, pipe, sequence, tensor)."""
        fixed = (self.fsdp * self.expert * self.pipe * self.sequence *
                 self.tensor)
        if n_devices % fixed != 0:
            raise ValueError(
                f"device count {n_devices} not divisible by "
                f"fsdp*expert*pipe*sequence*tensor = {fixed}")
        data = self.data if self.data > 0 else n_devices // fixed
        if data * fixed != n_devices:
            raise ValueError(
                f"mesh {data}x{self.fsdp}x{self.expert}x{self.pipe}"
                f"x{self.sequence}x{self.tensor} != device count "
                f"{n_devices}")
        return (data, self.fsdp, self.expert, self.pipe, self.sequence,
                self.tensor)


def mesh_shape_for_devices(config: MeshConfig,
                           n_devices: Optional[int] = None) -> tuple[int, ...]:
    if n_devices is None:
        n_devices = len(jax.devices())
    return config.resolve(n_devices)


def make_mesh(config: Optional[MeshConfig] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the global device mesh.

    Replaces ``mpu.initialize_model_parallel``
    (reference: fengshen/models/megatron/mpu/initialize.py:61-167).
    ``jax.make_mesh`` lays axes out so the last axis is ICI-contiguous.
    """
    config = config or MeshConfig()
    if devices is None:
        devices = jax.devices()
    shape = config.resolve(len(devices))
    # Auto axis types: we drive sharding with GSPMD constraints + shard_map,
    # not the explicit-sharding type system.
    auto = (jax.sharding.AxisType.Auto,) * len(MESH_AXES)
    try:
        if list(devices) == list(jax.devices()):
            return jax.make_mesh(shape, MESH_AXES, axis_types=auto)
    except Exception:  # pragma: no cover - make_mesh can reject odd topologies
        pass
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, MESH_AXES, axis_types=auto)


_GLOBAL_MESH: Optional[Mesh] = None


def set_mesh(mesh: Optional[Mesh]) -> None:
    """Install the process-global mesh (analog of mpu's module globals,
    reference: fengshen/models/megatron/mpu/initialize.py:33-45)."""
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh


def get_mesh() -> Optional[Mesh]:
    """Current process-global mesh, or None outside distributed contexts."""
    return _GLOBAL_MESH


def distributed_initialize(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host bootstrap.

    Replaces the reference's SLURM/NCCL cluster-environment dance
    (reference: fengshen/strategies/megatron_deepspeed.py:345-346 +
    torch.distributed init): one call, and every host sees the global
    device set; GSPMD handles cross-host collectives over ICI/DCN.
    No-op when running single-process (the common dev path).
    """
    if num_processes is None:
        num_processes = int(os.environ.get("FSTPU_NUM_PROCESSES", "1"))
    if num_processes <= 1 and coordinator_address is None:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def _local_batch_coords(mesh: Mesh) -> list[int]:
    """Flattened (data, fsdp) coordinates covered by this process's devices."""
    axes = list(mesh.axis_names)
    di, fi = axes.index(DATA_AXIS), axes.index(FSDP_AXIS)
    fsdp_size = mesh.devices.shape[fi]
    pid = jax.process_index()
    coords = set()
    for idx, dev in np.ndenumerate(mesh.devices):
        if dev.process_index == pid:
            coords.add(idx[di] * fsdp_size + idx[fi])
    return sorted(coords)


def data_parallel_rank(mesh: Mesh) -> int:
    """This host's position among the distinct batch-shard groups — used by
    the resumable samplers the same way the reference uses
    ``mpu.get_data_parallel_rank()``
    (reference: fengshen/data/universal_datamodule/universal_datamodule.py:84-85).

    Mesh-aware: when a model-parallel axis spans hosts, two hosts that hold
    the same batch coordinates get the SAME rank (they are one replica and
    must load identical data), unlike a naive ``jax.process_index()``.
    """
    if jax.process_count() == 1:
        return 0
    local = _local_batch_coords(mesh)
    group = len(local)
    # hosts cover equal contiguous coordinate ranges under the canonical
    # axis order, so the group index is the host's data rank
    return local[0] // group


def data_parallel_world_size(mesh: Mesh) -> int:
    """Number of distinct host-level batch-shard groups."""
    if jax.process_count() == 1:
        return 1
    axes = list(mesh.axis_names)
    total = (mesh.devices.shape[axes.index(DATA_AXIS)] *
             mesh.devices.shape[axes.index(FSDP_AXIS)])
    return max(1, total // len(_local_batch_coords(mesh)))
