"""Partition rules: regex-on-param-path → PartitionSpec.

This module is where the reference's TP layer classes collapse into data:
``ColumnParallelLinear`` (output-dim shard), ``RowParallelLinear`` (input-dim
shard) and ``VocabParallelEmbedding`` (vocab-dim shard)
(reference: fengshen/models/megatron/mpu/layers.py:55-470) become
PartitionSpec entries matched by parameter path. GSPMD then inserts the
collectives the reference implemented by hand as autograd Functions
(reference: fengshen/models/megatron/mpu/mappings.py:110-172) — the backward
duals come from autodiff for free.
"""

from __future__ import annotations

import re
from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fengshen_tpu.parallel.mesh import BATCH_AXES, get_mesh


def tree_paths(tree: Any) -> Any:
    """Pytree of '/'-joined string paths with the same structure as `tree`."""

    def _name(entry) -> str:
        if isinstance(entry, jax.tree_util.DictKey):
            return str(entry.key)
        if isinstance(entry, jax.tree_util.SequenceKey):
            return str(entry.idx)
        if isinstance(entry, jax.tree_util.GetAttrKey):
            return str(entry.name)
        if isinstance(entry, jax.tree_util.FlattenedIndexKey):
            return str(entry.key)
        return str(entry)

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    paths = ["/".join(_name(k) for k in path) for path, _ in flat]
    return jax.tree_util.tree_unflatten(treedef, paths)


def match_partition_rules(rules: Sequence[tuple[str, P]], tree: Any) -> Any:
    """Map every leaf of `tree` to the PartitionSpec of the first rule whose
    regex matches its path. Scalars are always replicated.

    The rules table plays the role of the reference's per-layer
    ``model_parallel``/``partition_dim`` weight attributes
    (reference: fengshen/models/megatron/mpu/layers.py:42-52).
    """
    paths = tree_paths(tree)

    def assign(path: str, leaf: Any) -> P:
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return P()
        for pattern, spec in rules:
            if re.search(pattern, path) is not None:
                return spec
        raise ValueError(f"no partition rule matched parameter path: {path!r}")

    return jax.tree_util.tree_map(assign, paths, tree)


#: (name, dim) pairs already warned about — one line per parameter/dim,
#: not one per step (VERDICT r3 weak #3)
_SPEC_FIT_WARNED: set = set()


def _spec_fits(spec: P, mesh: Mesh, shape: tuple[int, ...],
               name: Optional[str] = None) -> P:
    """Drop sharded dims that do not divide evenly.

    This keeps tiny test configs runnable, but in production it silently
    REPLICATES a weight the rules wanted sharded (a 13B run with a
    mis-sized axis would OOM or crawl instead of failing loudly) — so
    every drop is logged once per parameter. The reference instead hard-
    asserts divisibility (reference: fengshen/models/megatron/mpu/
    utils.py:22-35 divide()); the warning preserves that visibility
    without breaking the debug-batch degradation the Trainer relies on.
    """
    import logging
    out = []
    for dim, axes in enumerate(spec):
        if axes is None:
            out.append(None)
            continue
        axes_t = (axes,) if isinstance(axes, str) else tuple(axes)
        size = int(np.prod([mesh.shape[a] for a in axes_t]))
        if dim < len(shape) and shape[dim] % size == 0:
            out.append(axes)
        else:
            out.append(None)
            key = (name or f"{tuple(spec)}@{shape}", dim)
            # only parameters (named via make_shardings) warn: activation
            # constraints degrade by design for debug batches/init traces
            if size > 1 and name is not None and \
                    key not in _SPEC_FIT_WARNED:
                _SPEC_FIT_WARNED.add(key)
                logging.getLogger("fengshen_tpu.parallel").warning(
                    "partition spec %s does not divide %s dim %d "
                    "(shape %s, axis size %d)%s — REPLICATING this dim "
                    "instead; on a real mesh this usually means a "
                    "mis-sized parallel axis", tuple(spec),
                    name or "tensor", dim, shape, size,
                    f" [{name}]" if name else "")
    return P(*out)


def make_shardings(rules_or_specs: Any,
                   tree: Any,
                   mesh: Optional[Mesh] = None) -> Any:
    """Pytree of NamedSharding for `tree`.

    `rules_or_specs` is either a rules table (list of (regex, spec)) or an
    already-matched pytree of PartitionSpecs.
    """
    mesh = mesh or get_mesh()
    if mesh is None:
        raise ValueError("no mesh installed; call make_mesh()/set_mesh() first")
    # a bare PartitionSpec must not be mistaken for a rules table: on
    # jax<0.6 PartitionSpec subclasses tuple, so the isinstance probe
    # below would otherwise "match" a multi-axis spec like P(("data",
    # "fsdp"), "sequence")
    if isinstance(rules_or_specs, P):
        specs = rules_or_specs
    elif isinstance(rules_or_specs, (list, tuple)) and rules_or_specs \
            and isinstance(rules_or_specs[0], tuple) \
            and not isinstance(rules_or_specs[0], P):
        specs = match_partition_rules(rules_or_specs, tree)
    else:
        specs = rules_or_specs

    paths = tree_paths(tree)

    def to_sharding(spec: P, leaf: Any, path: str) -> NamedSharding:
        shape = getattr(leaf, "shape", ())
        return NamedSharding(mesh, _spec_fits(spec, mesh, tuple(shape),
                                              name=path))

    return jax.tree_util.tree_map(to_sharding, specs, tree, paths,
                                  is_leaf=lambda x: isinstance(x, P))


def named_sharding(*spec, mesh: Optional[Mesh] = None) -> NamedSharding:
    mesh = mesh or get_mesh()
    if mesh is None:
        raise ValueError("no mesh installed")
    return NamedSharding(mesh, P(*spec))


def shard_batch_spec(ndim: int, sequence_axis: Optional[int] = None) -> P:
    """PartitionSpec for a batch tensor: batch dim over the batch axes
    (data×fsdp — the reference's data-parallel group), optionally the
    sequence dim over 'sequence' (context parallelism)."""
    spec: list = [BATCH_AXES] + [None] * (ndim - 1)
    if sequence_axis is not None and 0 < sequence_axis < ndim:
        spec[sequence_axis] = "sequence"
    return P(*spec)


def with_sharding_constraint(x: Any, spec: P, mesh: Optional[Mesh] = None):
    """`jax.lax.with_sharding_constraint` that degrades to identity when no
    mesh is installed (pure single-device/unit-test path).

    Used inside model code where the reference called its collective region
    mappings (reference: fengshen/models/megatron/mpu/mappings.py:29-193).
    """
    mesh = mesh or get_mesh()
    if mesh is None:
        return x

    # Inside shard_map the mesh axes are Manual and constraints over them
    # are illegal — strip manual axes from the spec (model code then runs
    # unchanged whether it executes under GSPMD or inside a shard_map
    # stage, e.g. the pipeline-parallel body).
    try:
        abstract = jax.sharding.get_abstract_mesh()
        manual = {name for name, t in zip(abstract.axis_names,
                                          abstract.axis_types)
                  if "Manual" in str(t)} if abstract is not None and \
            abstract.axis_names else set()
    except Exception:  # pragma: no cover - jax version probe (older
        # jax lacks get_abstract_mesh / AxisType; degrade to "no
        # manual axes" rather than pinning one jax API surface)
        abstract, manual = None, set()
    if manual:
        def strip(entry):
            if entry is None:
                return None
            if isinstance(entry, (tuple, list)):
                kept = tuple(a for a in entry if a not in manual)
                return kept or None
            return None if entry in manual else entry

        spec = P(*(strip(e) for e in spec))
        if all(e is None for e in spec):
            return x

    # Inside a partial-manual shard_map the constraint must be built on the
    # abstract mesh (whose axis types mark the manual axes) — a NamedSharding
    # over the concrete all-Auto mesh is rejected for values varying over a
    # Manual axis.
    constraint_mesh = abstract if manual else mesh

    def constrain(leaf):
        fitted = _spec_fits(spec, mesh, tuple(leaf.shape))
        return jax.lax.with_sharding_constraint(
            leaf, NamedSharding(constraint_mesh, fitted))

    return jax.tree_util.tree_map(constrain, x)
