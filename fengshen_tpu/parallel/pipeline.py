"""Pipeline parallelism: a GPipe microbatch schedule over a mesh axis.

The reference's pipeline parallelism is plumbing-only: topology/groups/seeds
exist (reference: fengshen/models/megatron/mpu/initialize.py:111-134,
fengshen/strategies/megatron_deepspeed.py:347-361) but no PipelineModule is
ever wired into an example (SURVEY.md §2.4). This module provides a REAL
schedule, TPU-native: stages live on shards of a named mesh axis, stacked
per-stage parameters are sharded over that axis, and activations flow
stage-to-stage with `jax.lax.ppermute` while microbatches fill the pipe
(GPipe). Everything is a single SPMD program — no per-stage processes.

Usage sketch::

    mesh = Mesh(devices.reshape(4, 2), ("pipe", "data"))
    out = pipeline_apply(stage_fn, stacked_params, microbatches,
                         mesh=mesh, axis_name="pipe")

where ``stage_fn(stage_params, x) -> x`` is one stage's computation and
``stacked_params`` has a leading [n_stages] dim on every leaf.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from fengshen_tpu.compat import (axis_size as _axis_size,
                                 pvary as _pvary, shard_map)
from jax.sharding import Mesh, PartitionSpec as P


def _pipeline_body(stage_params: Any, microbatches: jax.Array,
                   stage_fn: Callable, axis_name: str,
                   n_microbatches: int) -> jax.Array:
    """shard_map body. stage_params: this stage's params (leading stage dim
    already split away by sharding). microbatches: [M, mb, ...] replicated.
    Returns [M, mb, ...] outputs valid on the LAST stage."""
    n_stages = _axis_size(axis_name)
    stage_idx = jax.lax.axis_index(axis_name)
    is_first = stage_idx == 0
    is_last = stage_idx == n_stages - 1

    # strip the stage dim the sharding left as size 1
    local_params = jax.tree_util.tree_map(lambda x: x[0], stage_params)

    mb_shape = microbatches.shape[1:]
    # carries are pipe-varying (each stage holds different values); pvary
    # marks them so check_vma accepts the cond/where mixing below
    state = _pvary(jnp.zeros(mb_shape, microbatches.dtype), axis_name)
    outputs = _pvary(
        jnp.zeros((n_microbatches,) + mb_shape, microbatches.dtype),
        axis_name)

    total_ticks = n_microbatches + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(t, carry):
        state, outputs = carry
        # stage 0 ingests microbatch t while t < M; later stages use the
        # activation that arrived from the previous stage
        feed = _pvary(
            jnp.take(microbatches, jnp.clip(t, 0, n_microbatches - 1),
                     axis=0), axis_name)
        x = jnp.where(is_first, feed, state)
        y = stage_fn(local_params, x)
        # last stage emits microbatch (t - n_stages + 1) when it's valid
        out_idx = t - (n_stages - 1)
        emit = jnp.logical_and(is_last, out_idx >= 0)
        outputs = jax.lax.cond(
            emit,
            lambda o: jax.lax.dynamic_update_index_in_dim(
                o, y, jnp.maximum(out_idx, 0), 0),
            lambda o: o, outputs)
        # rotate activations to the next stage (last→0 wraps; stage 0
        # ignores what it receives)
        state = jax.lax.ppermute(y, axis_name, perm)
        return state, outputs

    _, outputs = jax.lax.fori_loop(0, total_ticks, tick, (state, outputs))
    # broadcast the last stage's outputs to every shard so out_specs can be
    # replicated along the pipe axis
    outputs = jax.lax.psum(
        jnp.where(is_last, outputs, jnp.zeros_like(outputs)), axis_name)
    return outputs


def pipeline_apply(stage_fn: Callable, stacked_params: Any,
                   microbatches: jax.Array, mesh: Mesh,
                   axis_name: str = "pipe") -> jax.Array:
    """Run `stage_fn` as a GPipe pipeline over `axis_name`.

    stacked_params: pytree with leading [n_stages] dim on every leaf;
    microbatches: [n_microbatches, microbatch, ...] (replicated); returns
    [n_microbatches, microbatch, ...] outputs.
    """
    n_micro = microbatches.shape[0]
    params_spec = jax.tree_util.tree_map(
        lambda x: P(axis_name), stacked_params)
    # Manual ONLY over the pipe axis: every other mesh axis stays Auto, so
    # GSPMD keeps sharding the within-stage math (fsdp/tensor/sequence) —
    # PP composes with the other parallelism kinds in one SPMD program
    # (the reference's pipe-outer/model-inner topology,
    # fengshen/strategies/megatron_deepspeed.py:347-354).
    fn = shard_map(
        partial(_pipeline_body, stage_fn=stage_fn, axis_name=axis_name,
                n_microbatches=n_micro),
        mesh=mesh,
        in_specs=(params_spec, P()),
        out_specs=P(),
        axis_names=frozenset({axis_name}),
        check_vma=True)
    return fn(stacked_params, microbatches)


def _1f1b_body(stage_params: Any, micro_inputs: jax.Array,
               micro_targets: jax.Array, stage_fn: Callable,
               last_stage_loss: Callable, axis_name: str,
               n_microbatches: int):
    """shard_map body for the 1F1B schedule: forward activations and
    backward cotangents flow through the pipe on EVERY tick, so each stage
    alternates one-forward / one-backward in steady state, holding at most
    2·n_stages microbatch inputs (independent of the microbatch count M —
    GPipe-through-autodiff holds all M).

    fwd of microbatch m at stage s happens on tick m+s; bwd on tick
    m + 2S-1 - s. The backward recomputes the stage forward from the stored
    input (activation recompute, the standard TPU memory/flop trade).
    """
    S = _axis_size(axis_name)
    sid = jax.lax.axis_index(axis_name)
    is_first = sid == 0
    is_last = sid == S - 1
    M = n_microbatches
    local_params = jax.tree_util.tree_map(lambda x: x[0], stage_params)

    mb_shape = micro_inputs.shape[1:]
    ring = 2 * S  # max in-flight inputs per stage is 2S-1-2s <= 2S-1
    pv = lambda x: _pvary(x, axis_name)  # noqa: E731
    in_buf = pv(jnp.zeros((ring,) + mb_shape, micro_inputs.dtype))
    fwd_state = pv(jnp.zeros(mb_shape, micro_inputs.dtype))
    bwd_state = pv(jnp.zeros(mb_shape, micro_inputs.dtype))
    dparams = jax.tree_util.tree_map(
        lambda p: pv(jnp.zeros(p.shape, jnp.float32)), local_params)
    loss_acc = pv(jnp.zeros((), jnp.float32))

    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    bwd_perm = [((i + 1) % S, i) for i in range(S)]
    total_ticks = M + 2 * S - 1

    def fwd_for(m):
        """stage forward for microbatch m; last stage also evaluates the
        per-microbatch loss so its backward can start next tick."""
        return jnp.clip(m, 0, M - 1)

    def tick(t, carry):
        in_buf, fwd_state, bwd_state, dparams, loss_acc = carry

        # ---- forward lane: microbatch m_f = t - sid ----
        m_f = t - sid
        fwd_live = jnp.logical_and(m_f >= 0, m_f < M)
        feed = pv(jnp.take(micro_inputs, fwd_for(m_f), axis=0))
        x = jnp.where(is_first, feed, fwd_state)
        in_buf = jax.lax.cond(
            fwd_live,
            lambda b: jax.lax.dynamic_update_index_in_dim(
                b, x, fwd_for(m_f) % ring, 0),
            lambda b: b, in_buf)
        y = stage_fn(local_params, x)

        # ---- backward lane: microbatch m_b = t - (2S - 1 - sid) ----
        m_b = t - (2 * S - 1 - sid)
        bwd_live = jnp.logical_and(m_b >= 0, m_b < M)
        x_saved = jnp.take(in_buf, fwd_for(m_b) % ring, axis=0)
        target = pv(jnp.take(micro_targets, fwd_for(m_b), axis=0))

        # ONE stage vjp serves both roles: the last stage seeds it with
        # the loss cotangent, others with the received cotangent
        out, s_vjp = jax.vjp(lambda p, x_in: stage_fn(p, x_in),
                             local_params, x_saved)
        l_val, l_vjp = jax.vjp(lambda o: last_stage_loss(o, target), out)
        (d_out,) = l_vjp(pv(jnp.ones((), l_val.dtype)))
        seed = jnp.where(is_last, d_out, bwd_state)
        ds_p, ds_x = s_vjp(seed)

        use_last = jnp.logical_and(bwd_live, is_last)
        dparams = jax.tree_util.tree_map(
            lambda acc, ds: acc +
            jnp.where(bwd_live, ds.astype(jnp.float32), 0.0),
            dparams, ds_p)
        dx_out = jnp.where(bwd_live, ds_x, jnp.zeros_like(ds_x))
        loss_acc = loss_acc + jnp.where(use_last, l_val, 0.0)

        # ---- rotate both lanes ----
        fwd_state = jax.lax.ppermute(y, axis_name, fwd_perm)
        bwd_state = jax.lax.ppermute(dx_out, axis_name, bwd_perm)
        return in_buf, fwd_state, bwd_state, dparams, loss_acc

    carry = (in_buf, fwd_state, bwd_state, dparams, loss_acc)
    _, _, _, dparams, loss_acc = jax.lax.fori_loop(0, total_ticks, tick,
                                                   carry)
    # every stage holds ITS OWN dparams; restore the stacked layout by
    # keeping the local slice (shard_map out_specs put the stage dim back)
    # mean over microbatches for BOTH loss and grads, so the returned
    # grads are exactly d(loss)/d(params)
    dparams = jax.tree_util.tree_map(lambda g: g[None] / M, dparams)
    loss = jax.lax.psum(loss_acc, axis_name) / M
    return loss, dparams


def pipeline_train_step_1f1b(stage_fn: Callable, last_stage_loss: Callable,
                             stacked_params: Any,
                             micro_inputs: jax.Array,
                             micro_targets: jax.Array, mesh: Mesh,
                             axis_name: str = "pipe"):
    """One 1F1B training step over the `axis_name` mesh axis.

    stage_fn(stage_params, x) -> x; last_stage_loss(final_activations,
    target) -> scalar loss (mean over the microbatch). Returns
    (mean_loss, stacked_param_grads) — grads carry the same leading
    [n_stages] dim as `stacked_params`.
    """
    n_micro = micro_inputs.shape[0]
    params_spec = jax.tree_util.tree_map(
        lambda x: P(axis_name), stacked_params)
    fn = shard_map(
        partial(_1f1b_body, stage_fn=stage_fn,
                last_stage_loss=last_stage_loss, axis_name=axis_name,
                n_microbatches=n_micro),
        mesh=mesh,
        in_specs=(params_spec, P(), P()),
        out_specs=(P(), params_spec),
        axis_names=frozenset({axis_name}),
        check_vma=True)
    return fn(stacked_params, micro_inputs, micro_targets)
