"""Pipeline parallelism: a GPipe microbatch schedule over a mesh axis.

The reference's pipeline parallelism is plumbing-only: topology/groups/seeds
exist (reference: fengshen/models/megatron/mpu/initialize.py:111-134,
fengshen/strategies/megatron_deepspeed.py:347-361) but no PipelineModule is
ever wired into an example (SURVEY.md §2.4). This module provides a REAL
schedule, TPU-native: stages live on shards of a named mesh axis, stacked
per-stage parameters are sharded over that axis, and activations flow
stage-to-stage with `jax.lax.ppermute` while microbatches fill the pipe
(GPipe). Everything is a single SPMD program — no per-stage processes.

Usage sketch::

    mesh = Mesh(devices.reshape(4, 2), ("pipe", "data"))
    out = pipeline_apply(stage_fn, stacked_params, microbatches,
                         mesh=mesh, axis_name="pipe")

where ``stage_fn(stage_params, x) -> x`` is one stage's computation and
``stacked_params`` has a leading [n_stages] dim on every leaf.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _pipeline_body(stage_params: Any, microbatches: jax.Array,
                   stage_fn: Callable, axis_name: str,
                   n_microbatches: int) -> jax.Array:
    """shard_map body. stage_params: this stage's params (leading stage dim
    already split away by sharding). microbatches: [M, mb, ...] replicated.
    Returns [M, mb, ...] outputs valid on the LAST stage."""
    n_stages = jax.lax.axis_size(axis_name)
    stage_idx = jax.lax.axis_index(axis_name)
    is_first = stage_idx == 0
    is_last = stage_idx == n_stages - 1

    # strip the stage dim the sharding left as size 1
    local_params = jax.tree_util.tree_map(lambda x: x[0], stage_params)

    mb_shape = microbatches.shape[1:]
    state = jnp.zeros(mb_shape, microbatches.dtype)  # current activation
    outputs = jnp.zeros((n_microbatches,) + mb_shape, microbatches.dtype)

    total_ticks = n_microbatches + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(t, carry):
        state, outputs = carry
        # stage 0 ingests microbatch t while t < M; later stages use the
        # activation that arrived from the previous stage
        feed = jnp.take(microbatches, jnp.clip(t, 0, n_microbatches - 1),
                        axis=0)
        x = jnp.where(is_first, feed, state)
        y = stage_fn(local_params, x)
        # last stage emits microbatch (t - n_stages + 1) when it's valid
        out_idx = t - (n_stages - 1)
        emit = jnp.logical_and(is_last, out_idx >= 0)
        outputs = jax.lax.cond(
            emit,
            lambda o: jax.lax.dynamic_update_index_in_dim(
                o, y, jnp.maximum(out_idx, 0), 0),
            lambda o: o, outputs)
        # rotate activations to the next stage (last→0 wraps; stage 0
        # ignores what it receives)
        state = jax.lax.ppermute(y, axis_name, perm)
        return state, outputs

    _, outputs = jax.lax.fori_loop(0, total_ticks, tick, (state, outputs))
    # broadcast the last stage's outputs to every shard so out_specs can be
    # replicated along the pipe axis
    outputs = jax.lax.psum(
        jnp.where(is_last, outputs, jnp.zeros_like(outputs)), axis_name)
    return outputs


def pipeline_apply(stage_fn: Callable, stacked_params: Any,
                   microbatches: jax.Array, mesh: Mesh,
                   axis_name: str = "pipe") -> jax.Array:
    """Run `stage_fn` as a GPipe pipeline over `axis_name`.

    stacked_params: pytree with leading [n_stages] dim on every leaf;
    microbatches: [n_microbatches, microbatch, ...] (replicated); returns
    [n_microbatches, microbatch, ...] outputs.
    """
    n_micro = microbatches.shape[0]
    params_spec = jax.tree_util.tree_map(
        lambda x: P(axis_name), stacked_params)
    fn = shard_map(
        partial(_pipeline_body, stage_fn=stage_fn, axis_name=axis_name,
                n_microbatches=n_micro),
        mesh=mesh,
        in_specs=(params_spec, P()),
        out_specs=P(),
        check_vma=False)
    return fn(stacked_params, microbatches)
