"""Version-compat shims for the jax API surface this repo targets.

The code is written against the current jax API (`jax.shard_map`,
`check_vma=`); older jax (<0.5) ships the same functionality as
`jax.experimental.shard_map.shard_map` with the replication check
spelled `check_rep=`. Routing every use through this module keeps the
call sites modern and the version fallback in one place.
"""

from __future__ import annotations

try:
    from jax import shard_map as _shard_map
    _LEGACY = False
except ImportError:  # jax<0.5
    from jax.experimental.shard_map import shard_map as _shard_map
    _LEGACY = True


def shard_map(f, /, *args, **kwargs):
    if _LEGACY:
        if "check_vma" in kwargs:
            # the vma type system doesn't exist pre-0.5; the legacy
            # check_rep checker is NOT equivalent (it rejects modern
            # primitives like sharding_constraint), so drop checking
            kwargs.pop("check_vma")
            kwargs["check_rep"] = False
        if "axis_names" in kwargs:
            # the legacy API takes the complement: axes left AUTO
            # instead of axes made manual
            manual = frozenset(kwargs.pop("axis_names"))
            mesh = kwargs.get("mesh")
            if mesh is not None:
                kwargs["auto"] = frozenset(mesh.axis_names) - manual
    return _shard_map(f, *args, **kwargs)


def axis_size(axis_name):
    """`jax.lax.axis_size`, or the psum(1) idiom where it predates."""
    import jax
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def pvary(x, axis_name):
    """Mark `x` varying over `axis_name` for the vma type system; no-op
    when already varying (pvary rejects re-application) or on jax
    builds that predate vma typing entirely."""
    import jax
    try:
        if axis_name in jax.typeof(x).vma:
            return x
    except Exception:  # pragma: no cover - non-traced values / no vma
        pass
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        return pcast(x, axis_name, to="varying")
    pvary_fn = getattr(jax.lax, "pvary", None)
    if pvary_fn is not None:
        return pvary_fn(x, axis_name)
    # jax<0.5 has no vma type system at all — nothing to mark
    return x
