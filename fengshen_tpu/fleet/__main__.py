"""CLI: run the fleet router (docs/fleet.md).

    # front replicas that are already running
    python -m fengshen_tpu.fleet --replicas 10.0.0.1:8000,10.0.0.2:8000

    # or spawn N local stdlib api replicas from one config, then front
    # them (the `make serve-fleet` path)
    python -m fengshen_tpu.fleet --spawn 3 --config api.json

SIGTERM drains gracefully: admission stops (healthz → 503 draining),
in-flight requests finish, spawned replicas are SIGTERMed (each drains
itself), then the process exits 0.
"""

from __future__ import annotations

import argparse
from typing import Optional, Sequence


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m fengshen_tpu.fleet",
        description="health-gated fleet router over api replicas")
    p.add_argument("--replicas", type=str, default=None,
                   help="comma list of replica targets (host:port or "
                        "http://... base URLs)")
    p.add_argument("--spawn", type=int, default=None, metavar="N",
                   help="spawn N local stdlib api replicas from "
                        "--config instead of fronting existing ones")
    p.add_argument("--config", type=str, default=None,
                   help="api/main.py config json for --spawn")
    p.add_argument("--base-port", type=int, default=8100,
                   help="first spawned replica's port (default 8100)")
    p.add_argument("--phases", type=str, default=None,
                   help="comma list of per-replica serving phases for "
                        "--spawn (prefill|decode|both, e.g. "
                        "'prefill,decode,decode'); omitted replicas "
                        "default to 'both' (docs/disaggregation.md)")
    p.add_argument("--host", type=str, default="0.0.0.0")
    p.add_argument("--port", type=int, default=8080,
                   help="the router's own port (default 8080)")
    p.add_argument("--task", type=str, default="text_generation",
                   help="the proxied /api/<task> route")
    p.add_argument("--poll-interval", type=float, default=0.5)
    p.add_argument("--request-timeout", type=float, default=120.0)
    p.add_argument("--max-retries", type=int, default=2)
    p.add_argument("--breaker-threshold", type=int, default=3)
    p.add_argument("--breaker-cooldown", type=float, default=5.0)
    p.add_argument("--recovery-probes", type=int, default=2)
    p.add_argument("--drain-timeout", type=float, default=30.0)
    p.add_argument("--dump-dir", type=str, default=None,
                   help="flight-recorder dir: post-mortem bundles "
                        "(incl. traces.json, the last-N distributed "
                        "traces) land here on drain")
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if bool(args.replicas) == bool(args.spawn):
        build_parser().error(
            "exactly one of --replicas or --spawn is required")
    if args.phases and not args.spawn:
        build_parser().error("--phases needs --spawn (already-running "
                             "replicas advertise their own phase)")
    procs = []
    if args.spawn:
        if not args.config:
            build_parser().error("--spawn needs --config")
        from fengshen_tpu.fleet.launcher import (spawn_replicas,
                                                 terminate_replicas)
        phases = [] if not args.phases else \
            [p.strip() for p in args.phases.split(",") if p.strip()]
        targets, procs = spawn_replicas(args.config, args.spawn,
                                        args.base_port, phases=phases)
        print(f"[fleet] spawned {len(procs)} replica(s): "
              f"{', '.join(targets)}", flush=True)
    else:
        targets = [t.strip() for t in args.replicas.split(",")
                   if t.strip()]

    from fengshen_tpu.fleet.router import FleetConfig, FleetRouter
    from fengshen_tpu.fleet.server import serve
    recorder = None
    if args.dump_dir:
        # router-side flight recorder: the event ring plus a
        # traces.json provider (the last-N distributed traces) in
        # every post-mortem bundle (docs/observability.md)
        from fengshen_tpu.observability import FlightRecorder
        recorder = FlightRecorder(dump_dir=args.dump_dir)
    router = FleetRouter(FleetConfig(
        replicas=targets, task=args.task,
        request_timeout_s=args.request_timeout,
        poll_interval_s=args.poll_interval,
        max_retries=args.max_retries,
        breaker_threshold=args.breaker_threshold,
        breaker_cooldown_s=args.breaker_cooldown,
        recovery_probes=args.recovery_probes),
        recorder=recorder)

    def on_drained():
        if recorder is not None:
            try:
                recorder.dump(reason="router_drain")
            except Exception:  # noqa: BLE001 — a failed dump must not
                pass           # block replica teardown on the way out
        if procs:
            from fengshen_tpu.fleet.launcher import terminate_replicas
            terminate_replicas(procs)

    try:
        serve(router, args.host, args.port,
              drain_timeout_s=args.drain_timeout,
              on_drained=on_drained)
    finally:
        if procs:
            from fengshen_tpu.fleet.launcher import terminate_replicas
            terminate_replicas(procs, timeout_s=5.0)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
