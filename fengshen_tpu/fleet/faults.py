"""Deterministic fault injection for fleet-router tests.

The trainer's resilience doctrine (`resilience/faults.py`): chaos that
only fires on a real pod is untestable chaos — every failure mode the
router must survive is injected at exact, deterministic coordinates.
Here the coordinate system is **generate-request indices**: the plan
wraps the router's HTTP transport and counts `POST /api/*` attempts in
dispatch order (0-based; health/stats polls are never counted), so
"kill replica r1 just before the 5th generate request" means exactly
that, every run, regardless of wall clock.

Fault kinds (each keyed `{request_index: replica_name}`):

- ``kill_at``: from the moment attempt `index` is dispatched, the
  replica is DEAD — every request and poll to it raises
  `TransportError(sent=False)` (connect refused: the process is gone).
  If attempt `index` itself targets the replica, it fails too.
- ``wedge_at``: like ``kill_at`` but the process is WEDGED, not gone:
  requests raise `TransportError(sent=True)` (hang-until-timeout — the
  replica may still be executing), the dangerous failure mode that
  exercises the idempotent-safe retry rule. A wedged GENERATE attempt
  is actually DELIVERED to the replica first (its response is then
  discarded): the replica really does execute work whose answer the
  router never sees — which is exactly what "may still be executing"
  means, and what gives the killed-request's trace a real waterfall on
  the wedged replica. Polls are not delivered (a wedged healthz just
  times out).
- ``error_503_at``: that ONE attempt, if it targets the replica,
  answers `503 {"error": "injected 503"}` — a transient warming/
  draining window.
- ``slow_at``: that one attempt is delayed by ``slow_s`` (through the
  injectable sleep) before proceeding normally — tail-latency, not
  failure.
- ``preempt_at``: just BEFORE attempt `index` is dispatched, the named
  replica receives its preemption notice — the callback registered via
  ``preempt_with(name, fn)`` runs exactly once (delivering SIGTERM to a
  real process, or calling `begin_drain` on an in-process engine), so
  evacuation tests pin "the drain began at request k" as a coordinate,
  not a sleep. The callback fires OUTSIDE the plan lock and before the
  attempt is forwarded: a preempted replica answers that very attempt
  503 draining and the router re-places it deterministically. A
  coordinate with no registered callback still lands in ``fired``
  (exactly-once bookkeeping) and is otherwise a no-op.

KV-handoff faults (docs/disaggregation.md) use their OWN coordinate
axis — **KV-push indices**, counting `PUT /kv/*` attempts in dispatch
order through whichever transport the plan wraps (the prefill-side
coordinator's push seam). Each is keyed `{push_index: replica_name}`
where the name is the DECODE replica being pushed to, and each is
one-shot (the push either fails or it doesn't; the source's fallback
to local decode is the behavior under test, not a sticky outage):

- ``kv_kill_at``: the push raises `TransportError(sent=False)` — the
  payload provably never arrived, the source falls back locally with
  no twin to clean up.
- ``kv_wedge_at``: the push is DELIVERED (the decode replica really
  adopts the lane) and then raises `TransportError(sent=True)` — the
  dangerous mode: the source must DELETE the adopted twin before
  falling back, or one request decodes twice.
- ``kv_decline_at``: the push answers `409 {"adopted": false}` without
  being delivered — an adopt-decline (capacity, version skew) as the
  decode replica would phrase it.

``fired`` records every (kind, index, replica) that actually triggered,
so tests can pin that the injected fault count matches the router's
`fstpu_fleet_retries_total` exactly. ``revive(replica)`` clears a
sticky kill/wedge — the restarted-process move — without re-arming the
already-fired coordinate, so post-mortem reads (trace assembly, debug
endpoints) can reach the replica again deterministically.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from fengshen_tpu.fleet.router import TransportError


class FleetFaultPlan:
    """WHEN faults fire, in deterministic request coordinates."""

    def __init__(self, kill_at: Optional[Dict[int, str]] = None,
                 wedge_at: Optional[Dict[int, str]] = None,
                 error_503_at: Optional[Dict[int, str]] = None,
                 slow_at: Optional[Dict[int, str]] = None,
                 slow_s: float = 0.05,
                 kv_kill_at: Optional[Dict[int, str]] = None,
                 kv_wedge_at: Optional[Dict[int, str]] = None,
                 kv_decline_at: Optional[Dict[int, str]] = None,
                 preempt_at: Optional[Dict[int, str]] = None):
        self.kill_at = {int(k): str(v)
                        for k, v in (kill_at or {}).items()}
        self.wedge_at = {int(k): str(v)
                         for k, v in (wedge_at or {}).items()}
        self.error_503_at = {int(k): str(v)
                             for k, v in (error_503_at or {}).items()}
        self.slow_at = {int(k): str(v)
                        for k, v in (slow_at or {}).items()}
        self.slow_s = slow_s
        self.kv_kill_at = {int(k): str(v)
                           for k, v in (kv_kill_at or {}).items()}
        self.kv_wedge_at = {int(k): str(v)
                            for k, v in (kv_wedge_at or {}).items()}
        self.kv_decline_at = {int(k): str(v)
                              for k, v in (kv_decline_at or {}).items()}
        self.preempt_at = {int(k): str(v)
                           for k, v in (preempt_at or {}).items()}
        self.fired: List[Tuple[str, int, str]] = []
        self._lock = threading.Lock()
        self._index = 0
        self._kv_index = 0
        self._dead: Dict[str, str] = {}    # name -> "kill" | "wedge"
        self._armed: set = set()           # (at, name) already applied
        self._preempt_fn: Dict[str, Callable[[], None]] = {}
        #: preemption callbacks armed under the lock but DELIVERED by
        #: the wrapper outside it — a callback that drains an
        #: in-process engine must not run under the plan lock
        self._preempt_pending: List[str] = []

    @property
    def fault_count(self) -> int:
        """Faults that actually fired (the retries-must-match pin)."""
        return len(self.fired)

    def revive(self, replica: str) -> None:
        """Clear a sticky kill/wedge for `replica` (the process was
        restarted/unstuck). The coordinate that armed it stays
        consumed, so the fault does NOT re-fire on the next attempt."""
        with self._lock:
            self._dead.pop(replica, None)

    def preempt_with(self, replica: str,
                     fn: Callable[[], None]) -> None:
        """Register the preemption-notice delivery for `replica` —
        what actually happens when its ``preempt_at`` coordinate is
        reached (send SIGTERM to the subprocess, call `begin_drain`
        on the in-process engine, ...)."""
        with self._lock:
            self._preempt_fn[str(replica)] = fn

    def wrap(self, transport, sleep: Callable[[float], None] = time.sleep
             ) -> "FaultInjectingTransport":
        return FaultInjectingTransport(transport, self, sleep)

    # -- internals (called by the wrapper under self._lock) -----------
    def _advance_locked(self, replica: str) -> Optional[str]:
        """Account one generate attempt targeting `replica`; returns
        the one-shot fault to apply to THIS attempt (or None)."""
        idx = self._index
        self._index += 1
        for at, name in self.kill_at.items():
            if at <= idx and (at, name) not in self._armed:
                self._armed.add((at, name))
                self._dead.setdefault(name, "kill")
        for at, name in self.wedge_at.items():
            if at <= idx and (at, name) not in self._armed:
                self._armed.add((at, name))
                self._dead.setdefault(name, "wedge")
        for at, name in self.preempt_at.items():
            # exactly-once: the ("preempt", at, name) ledger key keeps
            # a late-armed coordinate (at < idx after a quiet stretch)
            # from re-firing on every subsequent attempt
            if at <= idx and ("preempt", at, name) not in self._armed:
                self._armed.add(("preempt", at, name))
                self.fired.append(("preempt", at, name))
                self._preempt_pending.append(name)
        if self.error_503_at.get(idx) == replica:
            self.fired.append(("error_503", idx, replica))
            return "error_503"
        if self.slow_at.get(idx) == replica:
            self.fired.append(("slow", idx, replica))
            return "slow"
        return None

    def _advance_kv_locked(self, replica: str) -> Optional[str]:
        """Account one KV push targeting `replica` (its own index
        axis); returns the one-shot fault to apply (or None)."""
        idx = self._kv_index
        self._kv_index += 1
        for kind, table in (("kv_kill", self.kv_kill_at),
                            ("kv_wedge", self.kv_wedge_at),
                            ("kv_decline", self.kv_decline_at)):
            if table.get(idx) == replica:
                self.fired.append((kind, idx, replica))
                return kind
        return None

    def _dead_mode_locked(self, replica: str,
                          idx: Optional[int]) -> Optional[str]:
        mode = self._dead.get(replica)
        if mode is not None and idx is not None:
            self.fired.append((mode, idx, replica))
        return mode


class FaultInjectingTransport:
    """Transport wrapper applying a `FleetFaultPlan`. Generate attempts
    (`POST` to an `/api/` path) advance the request index; polls only
    observe the dead-set (a killed replica stops answering /healthz
    too, which is exactly how the router's sweep notices it)."""

    def __init__(self, inner, plan: FleetFaultPlan,
                 sleep: Callable[[float], None] = time.sleep):
        self.inner = inner
        self.plan = plan
        self._sleep = sleep
        self._names: Dict[str, str] = {}   # base_url -> replica name

    def bind(self, router) -> "FaultInjectingTransport":
        """Learn base_url -> name from the router's replica set (names
        default to host:port, matching the plan's coordinates)."""
        for rep in router.replicas:
            self._names[rep.base_url.rstrip("/")] = rep.name
        return self

    def _name(self, base_url: str) -> str:
        key = base_url.rstrip("/")
        return self._names.get(key, key.split("://", 1)[-1])

    def request(self, base_url, method, path, body, timeout_s):
        name = self._name(base_url)
        is_generate = method.upper() == "POST" and \
            path.startswith("/api/")
        is_kv_push = method.upper() == "PUT" and \
            path.startswith("/kv/")
        with self.plan._lock:
            if is_generate:
                one_shot = self.plan._advance_locked(name)
                idx = self.plan._index - 1
                mode = self.plan._dead_mode_locked(name, idx)
            elif is_kv_push:
                one_shot = self.plan._advance_kv_locked(name)
                mode = self.plan._dead_mode_locked(name, None)
            else:
                one_shot = None
                mode = self.plan._dead_mode_locked(name, None)
            preempts = [self.plan._preempt_fn.get(n)
                        for n in self.plan._preempt_pending]
            self.plan._preempt_pending.clear()
        for fn in preempts:
            # the preemption notice lands BEFORE this attempt is
            # forwarded, outside the plan lock (the callback may drain
            # an in-process engine or signal a subprocess); a
            # coordinate with no registered callback is a no-op
            if fn is not None:
                try:
                    fn()
                except Exception:  # noqa: BLE001 — a broken delivery
                    pass           # must not fail the routed request
        if mode == "kill":
            raise TransportError(
                f"injected kill: connect to {name} refused", sent=False)
        if mode == "wedge":
            if is_generate:
                # sent=True for real: deliver the request, lose the
                # response — the replica executes work the router
                # never hears about (the danger the idempotent-safe
                # retry rule exists for, and the reason the wedged
                # replica HAS a waterfall when the trace is assembled)
                try:
                    self.inner.request(base_url, method, path, body,
                                       timeout_s)
                except Exception:  # noqa: BLE001 — the response is
                    pass           # discarded either way
            raise TransportError(
                f"injected wedge: request to {name} timed out",
                sent=True)
        if one_shot == "error_503":
            return 503, {"error": "injected 503", "reason": "injected"}
        if one_shot == "slow":
            self._sleep(self.plan.slow_s)
        if one_shot == "kv_kill":
            raise TransportError(
                f"injected kv kill: connect to {name} refused",
                sent=False)
        if one_shot == "kv_wedge":
            # deliver for real — the decode replica ADOPTS the lane —
            # then lose the ack, so the source must twin-delete before
            # its local fallback (the one-request-decodes-twice hazard)
            try:
                self.inner.request(base_url, method, path, body,
                                   timeout_s)
            except Exception:  # noqa: BLE001 — the ack is discarded
                pass           # either way
            raise TransportError(
                f"injected kv wedge: push to {name} timed out",
                sent=True)
        if one_shot == "kv_decline":
            return 409, {"adopted": False, "reason": "injected"}
        return self.inner.request(base_url, method, path, body,
                                  timeout_s)
