"""Fleet router: health-gated multi-replica serving (docs/fleet.md).

Composes N `fengshen_tpu/api` replicas into one fault-tolerant
endpoint: least-occupancy load balancing from polled `/stats`, health
gating with eased recovery, bounded retries with jittered backoff on a
different replica, per-replica circuit breaking with half-open probes,
and graceful drain on SIGTERM. Pure stdlib — the router runs on hosts
with no accelerator runtime.

    python -m fengshen_tpu.fleet --replicas host:port,host:port
    make serve-fleet CONFIG=api.json
"""

from fengshen_tpu.fleet.faults import (FaultInjectingTransport,
                                       FleetFaultPlan)
from fengshen_tpu.fleet.router import (BROKEN, DRAINING, HEALTHY,
                                       FleetConfig, FleetRouter,
                                       Replica, TransportError,
                                       UrllibTransport)
from fengshen_tpu.fleet.server import (build_fleet_server,
                                       healthz_payload,
                                       install_router_sigterm)

__all__ = [
    "BROKEN", "DRAINING", "HEALTHY", "FaultInjectingTransport",
    "FleetConfig", "FleetFaultPlan", "FleetRouter", "Replica",
    "TransportError", "UrllibTransport", "build_fleet_server",
    "healthz_payload", "install_router_sigterm",
]
