"""Fleet router core: health-gated least-occupancy load balancing over
N api replicas, with retries, circuit breaking, and graceful drain.

One serving replica (`fengshen_tpu/api/main.py` + the continuous
engine) is a single point of failure: a wedged tick, a restart, or a
warmup window is a full outage. The router composes replicas so the
fleet survives every single-replica failure mode (docs/fleet.md):

- **placement**: generate requests go to the IN-rotation replica with
  the least slot occupancy, computed from each replica's polled
  `/stats` (`slots_active + queue_depth` over `num_slots`) plus the
  router's own not-yet-visible in-flight count; ties break by replica
  index, so placement is deterministic under a deterministic clock;
- **phase-aware disaggregation** (docs/disaggregation.md): replicas
  advertise a serving `phase` in /stats; when the healthy rotation
  holds dedicated `prefill` AND `decode` tiers, admissions prime on
  the least-occupied prefill replica, its coordinator pushes the KV
  lane to the chosen decode replica, and the router collects the
  decode tail via `GET /kv/<id>` — every handoff failure degrades to
  local decode on the prefill replica, never a client error;
- **health gating**: a background poll hits every replica's
  `/healthz`; a replica is OUT while it answers anything but 200
  (warming, draining, unreachable) and is eased back in only after
  `recovery_probes` consecutive healthy polls — a replica that flaps
  must not immediately re-absorb traffic;
- **retries**: a connect failure or a 5xx answer costs one bounded
  retry on a DIFFERENT replica after a jittered exponential backoff.
  A failure that happened after the request may have reached the
  replica (timeout, reset mid-response) is only retried because the
  routed surface is idempotent-safe: never-streamed greedy generation
  carrying a router-assigned `request_id` that the replica dedupes or
  rejects (`DuplicateRequest` → 409, see serving/engine.py). With
  `retry_maybe_executed=False` such failures return 502 instead;
- **circuit breaker**: `breaker_threshold` consecutive failures open
  a per-replica breaker for `breaker_cooldown_s`; afterwards exactly
  one half-open probe request (or `recovery_probes` healthy polls)
  may close it — a black-holed replica costs one failed attempt per
  cooldown window, not one per request;
- **graceful drain**: `drain()` stops admission (`route_generate` and
  the server's `/healthz` answer 503 `{"reason": "draining"}`) while
  in-flight requests finish against their replica;
- **loud degradation**: only when ZERO replicas are in rotation does
  the fleet answer 503, with a structured reason JSON naming every
  replica's state and last error — never a bare empty 503.

Everything here is pure stdlib (no jax): the router must start on a
host that has no accelerator runtime at all. Clock, sleep, and the
HTTP transport are injectable, and the backoff jitter comes from a
seeded `random.Random`, so every behavior above is exercisable by
deterministic tests (`fleet/faults.py` injects kills/wedges/503s/slow
responses at exact request indices through the same transport seam).

Router-side telemetry lives in its OWN registry (rendered by the
server's `/metrics`): `fstpu_fleet_replicas{state}`,
`fstpu_fleet_retries_total{reason}`,
`fstpu_fleet_request_seconds{outcome}`, a per-attempt
`fstpu_fleet_attempt_seconds{outcome}` histogram, plus
requests/breaker-open and `fstpu_trace_*` counters. `fleet_state()`
is the `/fleet` debug JSON — deterministic (sorted, rounded) given a
deterministic clock.

Distributed tracing (docs/observability.md "Distributed tracing"):
every routed request mints (or joins) a trace; the router's
`SpanLedger` records enqueue / placement / per-attempt / total spans,
each attempt propagates `traceparent` to its replica (header + body
field), and `assemble()` stitches the ledger with the involved
replicas' `/debug/requests/<id>` waterfalls into the ONE
cross-process timeline `GET /debug/traces/<trace_id>` serves — clock
skew reported per replica, never hidden.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import random
import threading
import time
import urllib.error
import urllib.request
import uuid
from typing import (Any, Callable, Dict, Iterator, List, Optional,
                    Sequence, Tuple)

from fengshen_tpu.disagg import policy as disagg_policy
from fengshen_tpu.observability import (MetricsRegistry, SpanLedger,
                                        TraceContext, TraceIds,
                                        assemble_trace,
                                        parse_traceparent)
# streaming/ is stdlib-only (no jax), so the no-accelerator-host
# contract above holds
from fengshen_tpu.streaming import format_event, iter_sse

# replica rotation states (the fstpu_fleet_replicas{state} label set):
# "draining" covers every out-by-healthz condition — warming, an
# orderly drain, or unreachable-before-the-breaker-opens — the
# per-replica `reason` in /fleet tells them apart
HEALTHY, DRAINING, BROKEN = "healthy", "draining", "broken"

#: request-seconds outcome labels
OUTCOME_OK = "ok"                      # 2xx from a replica
OUTCOME_CLIENT_ERROR = "client_error"  # 4xx passed through
OUTCOME_ERROR = "error"                # retries exhausted on failures
OUTCOME_UNAVAILABLE = "unavailable"    # zero replicas in rotation
OUTCOME_DRAINING = "draining"          # router refused: drain started


class TransportError(Exception):
    """A request that produced no HTTP status at all (connect refused,
    DNS failure, timeout, connection reset). `sent` is False only when
    the transport can PROVE the request never reached the replica
    (e.g. connect refused) — retrying such a request is always safe.
    `sent=True` (the conservative default) means the replica may still
    be executing it, so a retry is only safe for idempotent requests.
    """

    def __init__(self, message: str, sent: bool = True):
        super().__init__(message)
        self.sent = sent


class UrllibTransport:
    """Default HTTP transport (stdlib urllib). Returns (status, body
    dict) for ANY HTTP status — an HTTP error response is a routing
    signal, not an exception — and raises TransportError when no
    status came back."""

    def request(self, base_url: str, method: str, path: str,
                body: Optional[dict], timeout_s: float
                ) -> Tuple[int, dict]:
        url = base_url.rstrip("/") + path
        data = None if body is None else json.dumps(body).encode()
        headers = {"Content-Type": "application/json"}
        if body is not None and body.get("traceparent"):
            # the trace context crosses the wire BOTH ways: as the
            # standard header (for anything W3C-aware in between) and
            # as the body field already in `data` (survives proxies
            # that strip unknown headers) — the replica prefers the
            # body form and they are identical here
            headers["traceparent"] = str(body["traceparent"])
        req = urllib.request.Request(
            url, data=data, method=method, headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as r:
                return r.status, _parse_json(r.read())
        except urllib.error.HTTPError as e:
            return e.code, _parse_json(e.read())
        except urllib.error.URLError as e:
            reason = getattr(e, "reason", e)
            # connect refused = the kernel rejected the SYN: the
            # request provably never reached a server process
            sent = not isinstance(reason, ConnectionRefusedError)
            raise TransportError(str(e), sent=sent) from e
        except (TimeoutError, ConnectionError, OSError) as e:
            sent = not isinstance(e, ConnectionRefusedError)
            raise TransportError(str(e), sent=sent) from e

    def stream(self, base_url: str, method: str, path: str,
               body: Optional[dict], timeout_s: float
               ) -> Iterator[dict]:
        """Open an SSE response and yield parsed event dicts
        ({"event", "id", "data"}) as frames arrive. An HTTP error
        status yields ONE synthetic {"event": "http_error",
        "status": code, "data": body} frame and ends — like
        `request`, a status IS a routing signal, not an exception.
        Connection-level failures (connect refused, timeout, a reset
        or truncated read MID-stream — the SIGKILL case) raise
        TransportError; `sent` follows the same proof rule as
        `request`, and is always True once bytes have streamed."""
        url = base_url.rstrip("/") + path
        data = None if body is None else json.dumps(body).encode()
        headers = {"Content-Type": "application/json"}
        if body is not None and body.get("traceparent"):
            headers["traceparent"] = str(body["traceparent"])
        req = urllib.request.Request(
            url, data=data, method=method, headers=headers)
        try:
            r = urllib.request.urlopen(req, timeout=timeout_s)
        except urllib.error.HTTPError as e:
            yield {"event": "http_error", "id": None,
                   "status": e.code, "data": _parse_json(e.read())}
            return
        except urllib.error.URLError as e:
            reason = getattr(e, "reason", e)
            sent = not isinstance(reason, ConnectionRefusedError)
            raise TransportError(str(e), sent=sent) from e
        except (TimeoutError, ConnectionError, OSError) as e:
            sent = not isinstance(e, ConnectionRefusedError)
            raise TransportError(str(e), sent=sent) from e
        try:
            with r:
                for ev in iter_sse(r):
                    yield ev
        except (TimeoutError, ConnectionError, OSError,
                http.client.HTTPException) as e:
            # IncompleteRead / reset after frames already flowed:
            # the replica definitely saw the request
            raise TransportError(str(e), sent=True) from e


def _parse_json(raw: bytes) -> dict:
    try:
        out = json.loads(raw)
        return out if isinstance(out, dict) else {}
    except (ValueError, UnicodeDecodeError):
        return {}


@dataclasses.dataclass
class FleetConfig:
    """Router tuning knobs (docs/fleet.md has sizing guidance)."""

    replicas: Sequence[str] = ()        # "host:port" or full base URLs
    task: str = "text_generation"       # the proxied /api/<task> route
    request_timeout_s: float = 120.0    # per-attempt timeout
    poll_interval_s: float = 0.5        # health/stats sweep period
    poll_timeout_s: float = 2.0         # per-poll-request timeout
    max_retries: int = 2                # extra attempts after the first
    backoff_base_s: float = 0.05        # first retry's nominal delay
    backoff_max_s: float = 2.0          # exponential backoff ceiling
    breaker_threshold: int = 3          # consecutive failures to open
    breaker_cooldown_s: float = 5.0     # open time before half-open
    recovery_probes: int = 2            # healthy polls to re-enter
    retry_maybe_executed: bool = True   # see module docstring: the
    #   routed surface is idempotent-safe (greedy, never streamed,
    #   request-id deduped), so maybe-executed failures retry too
    resume_from_journal: bool = True    # before a maybe-executed retry
    #   (or after a failed disagg collect), mine the fleet's commit
    #   journals (`GET /partial/<id>`) and resubmit with
    #   `resume_tokens` so the retry decodes only the remainder
    #   (docs/fault_tolerance.md "Preemption runbook")
    seed: int = 0                       # backoff-jitter rng seed
    trace_ring: int = 128               # traces the span ledger keeps
    trace_seed: Optional[int] = None    # trace-id seed — tests ONLY:
    #   None (the default) draws ids from OS entropy; a fixed seed
    #   would make every router with the same config mint the SAME
    #   id stream, colliding across restarts and sibling routers

    def __post_init__(self):
        if not self.replicas:
            raise ValueError("FleetConfig needs at least one replica")
        if self.max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        if self.breaker_threshold < 1:
            raise ValueError("breaker_threshold must be >= 1")
        if self.recovery_probes < 1:
            raise ValueError("recovery_probes must be >= 1")


class Replica:
    """Per-replica rotation state. All mutation happens under the
    router's lock; reads for /fleet snapshot under the same lock."""

    def __init__(self, index: int, target: str):
        self.index = index
        self.name = target if "://" not in target else \
            target.split("://", 1)[1].rstrip("/")
        self.base_url = target if "://" in target \
            else f"http://{target}"
        # out of rotation until the first healthy poll: routing to an
        # unprobed replica would race its warmup window
        self.state = DRAINING
        self.reason: Optional[str] = "unprobed"
        self.consecutive_failures = 0
        self.healthy_streak = 0
        self.breaker_open_until: Optional[float] = None
        self.half_open_inflight = False
        self.last_error: Optional[dict] = None   # {"detail", "at"}
        #: when the health sweep last COMPLETED a poll of this replica
        #: (any outcome incl. unreachable); None until the first one —
        #: /fleet renders it as last_poll_age_s so a stuck poll loop
        #: is visible without reading logs
        self.last_poll_at: Optional[float] = None
        self.in_flight = 0
        self.slots_active = 0
        self.num_slots = 0
        self.queue_depth = 0
        self.draining_reported = False
        #: the replica's advertised serving phase (`prefill` | `decode`
        #: | `both`, from its polled /stats — docs/disaggregation.md);
        #: "both" until the first stats poll, so an unprobed fleet
        #: routes homogeneously
        self.phase = "both"

    def occupancy(self) -> float:
        """Polled load plus the router's own not-yet-visible dispatches
        (each charged as one slot's worth of work)."""
        denom = max(self.num_slots, 1)
        return (self.slots_active + self.queue_depth
                + self.in_flight) / denom


class FleetRouter:
    """The routing core. HTTP-free by itself: `fleet/server.py` wraps
    it in the router process's own stdlib server, tests drive
    `route_generate()` / `poll_once()` directly."""

    def __init__(self, config: FleetConfig,
                 transport: Any = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 log: Optional[Callable[[dict], None]] = None,
                 wall: Callable[[], float] = time.time,
                 recorder: Any = None):
        self.config = config
        self.transport = transport if transport is not None \
            else UrllibTransport()
        self._clock = clock
        self._sleep = sleep
        self._log = log or (lambda entry: None)
        self._lock = threading.Lock()
        self._rng = random.Random(config.seed)
        # the distributed-tracing tier (docs/observability.md): every
        # routed request gets (or joins) a trace; the ledger records
        # the router's own spans — admit, placement, each attempt,
        # total — on the request thread, host-side only. Ids draw OS
        # entropy unless config.trace_seed pins them (deterministic
        # tests); `wall` is the epoch anchor clock the assembler's
        # skew math rests on (injectable, like everything else here).
        self.tracer = SpanLedger("router", clock=clock, wall=wall,
                                 max_traces=config.trace_ring,
                                 ids=TraceIds(config.trace_seed))
        self._recorder = recorder
        if recorder is not None:
            # router events enter the post-mortem ring, and bundles
            # carry the last-N traces as traces.json
            self._log = recorder.wrap_sink(self._log)
            recorder.attach("traces", self.tracer.provider)
        self.replicas: List[Replica] = [
            Replica(i, t) for i, t in enumerate(config.replicas)]
        if len({r.base_url for r in self.replicas}) != len(self.replicas):
            raise ValueError("duplicate replica targets in FleetConfig")
        self._draining = False
        self._seq = 0
        # per-process token in assigned request ids: a restarted router
        # must never reuse a previous router's id while a replica still
        # holds it live (the dedupe would 409 a brand-new request)
        self._id_token = uuid.uuid4().hex[:8]
        self._t0 = clock()
        self._poll_thread: Optional[threading.Thread] = None
        self._poll_stop = threading.Event()

        r = self.registry = MetricsRegistry()
        self._g_replicas = r.gauge(
            "fstpu_fleet_replicas",
            "replicas per rotation state and serving phase",
            labelnames=("state", "phase"))
        # (state, phase) combos ever exported — a replica that switches
        # phase must leave a 0 behind, not a frozen stale sample
        self._gauge_combos: set = set()
        self._c_retries = r.counter(
            "fstpu_fleet_retries_total",
            "generate retries by cause of the failed attempt",
            labelnames=("reason",))
        self._h_request = r.histogram(
            "fstpu_fleet_request_seconds",
            "fleet-level generate wall seconds by outcome",
            labelnames=("outcome",))
        self._c_requests = r.counter(
            "fstpu_fleet_requests_total",
            "generate requests admitted by the router")
        self._c_breaker = r.counter(
            "fstpu_fleet_breaker_opens_total",
            "circuit-breaker open transitions", labelnames=("replica",))
        self._c_polls = r.counter(
            "fstpu_fleet_polls_total", "health/stats poll sweeps")
        self._h_attempt = r.histogram(
            "fstpu_fleet_attempt_seconds",
            "per-attempt wall seconds by attempt outcome",
            labelnames=("outcome",))
        self._c_resume = r.counter(
            "fstpu_resume_total",
            "commit-journal consultations before a maybe-executed "
            "retry (resumed / recovered / miss)",
            labelnames=("outcome",))
        self._c_resume_tokens = r.counter(
            "fstpu_resume_tokens_total",
            "committed tokens replayed via resume_tokens instead of "
            "regenerated from token 0")
        self._c_traces = r.counter(
            "fstpu_trace_started_total",
            "traces minted or joined by the router")
        self._c_trace_assembled = r.counter(
            "fstpu_trace_assembled_total",
            "cross-process trace assemblies served")
        self._c_trace_fetch_errors = r.counter(
            "fstpu_trace_fetch_errors_total",
            "replica waterfall fetches that failed during assembly")
        self._update_state_gauge_locked()

    # ---- health polling ---------------------------------------------

    def start_polling(self) -> None:
        """Background health/stats sweeps every poll_interval_s."""
        if self._poll_thread is not None:
            return
        self._poll_stop.clear()

        def loop():
            while not self._poll_stop.is_set():
                try:
                    self.poll_once()
                except Exception as e:  # noqa: BLE001 — a poll bug must
                    # not kill the sweeper and silently freeze rotation
                    # state; log and keep sweeping
                    self._log({"event": "fleet_poll_error",
                               "error": str(e)[:200]})
                self._poll_stop.wait(self.config.poll_interval_s)

        self._poll_thread = threading.Thread(
            target=loop, daemon=True, name="fstpu-fleet-poll")
        self._poll_thread.start()

    def stop(self) -> None:
        self._poll_stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=5.0)
            self._poll_thread = None

    def poll_once(self) -> None:
        """One sweep: /healthz (rotation gating) then, for in-rotation
        replicas, /stats (occupancy). Replicas are polled on PARALLEL
        threads joined before returning — a black-holed replica costs
        one poll_timeout_s, not poll_timeout_s x dead_replicas of
        staleness for the healthy ones. Per-replica outcomes are
        deterministic given a deterministic transport (each replica's
        state is touched only by its own poll), which is what the
        fault-plan tests rely on when calling this directly."""
        self._c_polls.inc()
        if len(self.replicas) == 1:
            self._poll_replica(self.replicas[0])
            return
        threads = [threading.Thread(target=self._poll_replica,
                                    args=(rep,), daemon=True)
                   for rep in self.replicas]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    def _poll_replica(self, rep: Replica) -> None:
        try:
            code, body = self.transport.request(
                rep.base_url, "GET", "/healthz", None,
                self.config.poll_timeout_s)
        except TransportError as e:
            self._note_poll_down(rep, "unreachable", str(e))
            return
        except Exception as e:  # noqa: BLE001 — a transport bug on one
            # replica's poll thread must not skip the rest of the sweep
            self._log({"event": "fleet_poll_error",
                       "replica": rep.name, "error": str(e)[:200]})
            return
        if code != 200:
            reason = str(body.get("reason") or body.get("status")
                         or f"http_{code}")
            self._note_poll_down(rep, reason, f"healthz {code}",
                                 orderly=reason in ("draining",
                                                    "warmup",
                                                    "warming"))
            return
        # healthz is 200 — refresh load numbers BEFORE deciding the
        # state: engine.begin_drain() without the API-layer event flips
        # /stats `draining` first, and the router must route around the
        # replica on that signal alone (serving/engine.py begin_drain)
        fresh_draining = False
        try:
            scode, stats = self.transport.request(
                rep.base_url, "GET", "/stats", None,
                self.config.poll_timeout_s)
        except Exception:  # noqa: BLE001 — healthz just answered;
            scode = None   # keep the stale load numbers
        if scode == 200:
            with self._lock:
                rep.slots_active = int(
                    stats.get("slots_active") or 0)
                rep.num_slots = int(stats.get("num_slots") or 0)
                rep.queue_depth = int(
                    stats.get("queue_depth") or 0)
                rep.draining_reported = fresh_draining = bool(
                    stats.get("draining") or False)
                phase = str(stats.get("phase") or "both")
                if phase != rep.phase:
                    rep.phase = phase
                    self._update_state_gauge_locked()
        if fresh_draining:
            self._note_poll_down(rep, "draining", "stats draining",
                                 orderly=True)
        else:
            self._note_poll_healthy(rep)

    def _note_poll_healthy(self, rep: Replica) -> None:
        with self._lock:
            now = self._clock()
            rep.last_poll_at = now
            if rep.state == BROKEN:
                # healthy polls past the cooldown count as half-open
                # probes: recovery_probes of them close the breaker
                # without risking a real request
                if (rep.breaker_open_until is not None
                        and now < rep.breaker_open_until):
                    return
                rep.healthy_streak += 1
                if rep.healthy_streak >= self.config.recovery_probes:
                    self._close_breaker_locked(rep)
                return
            if rep.state == HEALTHY:
                rep.healthy_streak = 0
                return
            # DRAINING → eased re-entry
            rep.healthy_streak += 1
            if rep.healthy_streak >= self.config.recovery_probes:
                rep.state = HEALTHY
                rep.reason = None
                rep.healthy_streak = 0
                self._log({"event": "fleet_replica_in",
                           "replica": rep.name})
                self._update_state_gauge_locked()

    def _note_poll_down(self, rep: Replica, reason: str, detail: str,
                        orderly: bool = False) -> None:
        with self._lock:
            rep.healthy_streak = 0
            rep.last_poll_at = self._clock()
            rep.last_error = {"detail": detail[:200],
                              "at": self._clock()}
            if rep.state == BROKEN:
                return          # the breaker already holds it out
            if not orderly:
                # an unreachable replica found by polling counts toward
                # the breaker exactly like a failed request — a dead
                # process must not need real traffic to trip it
                self._count_failure_locked(rep, f"poll_{reason}")
                if rep.state == BROKEN:
                    return
            if rep.state != DRAINING or rep.reason != reason:
                self._log({"event": "fleet_replica_out",
                           "replica": rep.name, "reason": reason})
            rep.state = DRAINING
            rep.reason = reason
            self._update_state_gauge_locked()

    # ---- breaker ----------------------------------------------------

    def _count_failure_locked(self, rep: Replica, reason: str) -> None:
        rep.consecutive_failures += 1
        if (rep.state != BROKEN and rep.consecutive_failures
                >= self.config.breaker_threshold):
            rep.state = BROKEN
            rep.reason = "breaker_open"
            rep.breaker_open_until = (self._clock()
                                      + self.config.breaker_cooldown_s)
            rep.healthy_streak = 0
            rep.half_open_inflight = False
            self._c_breaker.labels(rep.name).inc()
            self._log({"event": "fleet_breaker_open",
                       "replica": rep.name, "reason": reason,
                       "consecutive_failures":
                           rep.consecutive_failures})
            self._update_state_gauge_locked()

    def _close_breaker_locked(self, rep: Replica) -> None:
        rep.state = HEALTHY
        rep.reason = None
        rep.consecutive_failures = 0
        rep.breaker_open_until = None
        rep.half_open_inflight = False
        rep.healthy_streak = 0
        self._log({"event": "fleet_breaker_close", "replica": rep.name})
        self._update_state_gauge_locked()

    def _update_state_gauge_locked(self) -> None:
        counts: Dict[Tuple[str, str], int] = {}
        for phase in {rep.phase for rep in self.replicas}:
            for state in (HEALTHY, DRAINING, BROKEN):
                counts[(state, phase)] = 0
        for rep in self.replicas:
            counts[(rep.state, rep.phase)] += 1
        for (state, phase), n in counts.items():
            self._g_replicas.labels(state, phase).set(n)
        for combo in self._gauge_combos - set(counts):
            self._g_replicas.labels(*combo).set(0)
        self._gauge_combos |= set(counts)

    # ---- placement --------------------------------------------------

    def _pick_locked(self, exclude: Sequence[Replica]
                     ) -> Optional[Replica]:
        now = self._clock()
        best: Optional[Replica] = None
        for rep in self.replicas:
            if rep in exclude:
                continue
            if rep.state == HEALTHY:
                if best is None or rep.occupancy() < best.occupancy():
                    best = rep
        if best is not None:
            return best
        # no healthy candidate: offer ONE half-open probe to a broken
        # replica whose cooldown expired (lowest index — deterministic)
        for rep in self.replicas:
            if (rep not in exclude and rep.state == BROKEN
                    and not rep.half_open_inflight
                    and rep.breaker_open_until is not None
                    and now >= rep.breaker_open_until):
                rep.half_open_inflight = True
                return rep
        return None

    def _plan_disagg_locked(self, exclude: Sequence[Replica]
                            ) -> Optional[disagg_policy.HandoffPlan]:
        """Phase-aware placement (docs/disaggregation.md): when the
        healthy rotation holds BOTH a dedicated prefill and a dedicated
        decode tier, plan a handoff — prime the lane on the
        least-occupied prefill replica and push its KV to the
        least-occupied decode replica. Every other topology (all-both,
        one tier missing or fully out of rotation) returns None and
        placement falls through to plain `_pick_locked` least-occupancy
        — disaggregation is an optimization, never a new way to 503."""
        candidates = [r for r in self.replicas
                      if r not in exclude and r.state == HEALTHY]
        return disagg_policy.plan_handoff(candidates)

    def healthy_count(self) -> int:
        with self._lock:
            return sum(1 for r in self.replicas if r.state == HEALTHY)

    def in_flight_total(self) -> int:
        with self._lock:
            return sum(r.in_flight for r in self.replicas)

    # ---- drain ------------------------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining

    def drain(self) -> None:
        """Stop admitting; in-flight requests keep their replica."""
        self._draining = True
        self._log({"event": "fleet_drain",
                   "in_flight": self.in_flight_total()})

    def wait_drained(self, timeout_s: float = 30.0,
                     poll_s: float = 0.05) -> bool:
        """True once every in-flight request finished (or immediately
        if none); False on timeout."""
        deadline = self._clock() + timeout_s
        while self.in_flight_total() > 0:
            if self._clock() >= deadline:
                return False
            self._sleep(poll_s)
        return True

    # ---- the request path -------------------------------------------

    def _backoff_s(self, attempt: int) -> float:
        """Jittered exponential backoff before retry `attempt` (1-based):
        nominal base*2^(attempt-1) capped at backoff_max_s, scaled by a
        seeded-uniform 0.5..1.0 jitter so synchronized clients spread
        out while tests stay deterministic."""
        nominal = min(self.config.backoff_base_s * (2 ** (attempt - 1)),
                      self.config.backoff_max_s)
        with self._lock:
            jitter = 0.5 + self._rng.random() / 2.0
        return nominal * jitter

    def _finish_attempt(self, rep: Replica, ok: bool,
                        reason: Optional[str] = None,
                        detail: str = "") -> None:
        with self._lock:
            rep.in_flight = max(rep.in_flight - 1, 0)
            if ok:
                rep.consecutive_failures = 0
                if rep.state == BROKEN:
                    # the half-open probe came back clean
                    self._close_breaker_locked(rep)
                return
            rep.half_open_inflight = False
            rep.last_error = {"detail": detail[:200],
                              "at": self._clock()}
            if rep.state == BROKEN:
                # a failed half-open probe re-opens the window
                rep.breaker_open_until = (
                    self._clock() + self.config.breaker_cooldown_s)
                return
            self._count_failure_locked(rep, reason or "request")

    def _mark_out_locked(self, rep: Replica, reason: str) -> None:
        if rep.state == HEALTHY:
            rep.state = DRAINING
            rep.reason = reason
            rep.healthy_streak = 0
            self._log({"event": "fleet_replica_out",
                       "replica": rep.name, "reason": reason})
            self._update_state_gauge_locked()

    def _no_replicas_payload(self) -> dict:
        """The loud structured degradation body: the fleet only ever
        503s with a reason naming every replica's state."""
        with self._lock:
            now = self._clock()
            states = {}
            for rep in self.replicas:
                err = None
                if rep.last_error is not None:
                    err = {"detail": rep.last_error["detail"],
                           "age_s": round(now - rep.last_error["at"], 3)}
                states[rep.name] = {"state": rep.state,
                                    "reason": rep.reason,
                                    "last_error": err}
        return {"error": "no healthy replicas",
                "reason": "no_healthy_replicas",
                "replicas": states}

    def route_generate(self, body: dict) -> Tuple[int, dict]:
        """Proxy one generate request: pick → attempt → (on connect/5xx
        failure) retry on a different replica with jittered backoff.
        Returns (status, response body) — the server layer writes them
        verbatim. Never raises.

        Every admitted request gets a distributed trace
        (docs/observability.md "Distributed tracing"): a fresh trace id
        is minted (or an incoming `traceparent` joined), the router's
        span ledger records admit / placement / every attempt (with
        replica, outcome, and the backoff that followed) / total, and
        each attempt propagates `traceparent` to its replica — parented
        to THAT attempt's span, so retries show as siblings under one
        trace. The response body carries `trace_id` for later
        `GET /debug/traces/<trace_id>` assembly. All of it is host-side
        dict work on this thread — zero per-token overhead."""
        t0 = time.perf_counter()
        if self._draining:
            self._h_request.labels(OUTCOME_DRAINING).observe(
                time.perf_counter() - t0)
            return 503, {"error": "router draining",
                         "reason": "draining"}
        incoming = parse_traceparent(body.get("traceparent"))
        with self._lock:
            rid = body.get("request_id")
            if not rid:
                rid = f"fleet-{self._id_token}-{self._seq}"
            self._seq += 1
        body = dict(body, request_id=str(rid))
        ctx = self.tracer.start_trace(
            "fleet/request",
            trace_id=None if incoming is None else incoming.trace_id,
            parent_span_id=None if incoming is None
            else incoming.span_id,
            request_id=body["request_id"], task=self.config.task)
        tid, root = ctx.trace_id, ctx.span_id
        self._c_traces.inc()
        s_admit = self.tracer.start_span(tid, "router/enqueue", root)
        self.tracer.end_span(tid, s_admit,
                             healthy=self.healthy_count())
        self._c_requests.inc()

        attempts = self.config.max_retries + 1
        tried: List[Replica] = []
        last: Optional[Tuple[int, dict]] = None
        for attempt in range(attempts):
            s_place = self.tracer.start_span(
                tid, "router/placement", root, attempt=attempt + 1)
            push_to: Optional[str] = None
            decode_name: Optional[str] = None
            with self._lock:
                plan = self._plan_disagg_locked(tried)
                if plan is not None:
                    rep = plan.prefill
                    push_to = plan.decode.base_url
                    decode_name = plan.decode.name
                else:
                    rep = self._pick_locked(tried)
                if rep is not None:
                    rep.in_flight += 1
            self.tracer.end_span(
                tid, s_place,
                replica=None if rep is None else rep.name,
                **({} if decode_name is None
                   else {"decode": decode_name}))
            if rep is None:
                break
            tried.append(rep)
            path = f"/api/{self.config.task}"
            # the attempt span carries its OWN request_id: a joined
            # trace (one caller traceparent over many requests) must
            # let assemble() fetch each replica's actual request, not
            # the first request the trace ever saw
            s_att = self.tracer.start_span(
                tid, "router/attempt", root, attempt=attempt + 1,
                replica=rep.name, request_id=body["request_id"])
            send_body = body
            if push_to is not None:
                # the prefill replica's coordinator primes the lane
                # and pushes it here (docs/disaggregation.md); any
                # failure degrades to local decode on that replica
                send_body = dict(send_body, disagg_push_to=push_to)
            if s_att is not None:
                # the replica's timeline parents to THIS attempt's
                # span — a retried request's two executions hang off
                # two sibling spans of one trace
                send_body = dict(
                    send_body,
                    traceparent=TraceContext(tid, s_att)
                    .to_traceparent())
            t_att = time.perf_counter()
            try:
                status, resp = self.transport.request(
                    rep.base_url, "POST", path, send_body,
                    self.config.request_timeout_s)
            except TransportError as e:
                reason = "connect" if not e.sent else "timeout"
                self._h_attempt.labels(reason).observe(
                    time.perf_counter() - t_att)
                # charge the breaker but leave rotation state to it
                # (and to the health poll): one flaky connect must not
                # empty the rotation below breaker_threshold
                self._finish_attempt(rep, ok=False, reason=reason,
                                     detail=str(e))
                last = (502, {"error": f"replica {rep.name}: {e}",
                              "reason": reason,
                              "request_id": body["request_id"]})
                if e.sent and not self.config.retry_maybe_executed:
                    # the replica may still be executing and the
                    # deployment opted out of idempotent-safe retries
                    self.tracer.end_span(tid, s_att, outcome=reason,
                                         error=str(e)[:200],
                                         retried=False)
                    self._log({"event": "fleet_request_error",
                               "replica": rep.name, "reason": reason,
                               "retried": False})
                    break
                backoff = self._maybe_retry(attempt, attempts, reason,
                                            rep)
                answer = None
                if (backoff is not None and e.sent
                        and self.config.resume_from_journal):
                    # the attempt may have executed: mine the fleet's
                    # commit journals so the retry resumes from token
                    # k instead of regenerating from token 0
                    answer, body = self._resume_from_journal(body, rep)
                self.tracer.end_span(
                    tid, s_att, outcome=reason, error=str(e)[:200],
                    **({} if backoff is None
                       else {"backoff_s": backoff}))
                if answer is not None:
                    status, resp = answer
                    self.tracer.end_span(tid, root, outcome=OUTCOME_OK,
                                         status=status,
                                         attempts=attempt + 1)
                    self._h_request.labels(OUTCOME_OK).observe(
                        time.perf_counter() - t0)
                    self._log({"event": "fleet_request_recovered",
                               "request_id": body["request_id"],
                               "attempts": attempt + 1,
                               "replica": rep.name, "trace_id": tid})
                    return status, dict(resp, trace_id=tid)
                if backoff is not None:
                    self._sleep(backoff)
                continue
            if status >= 500:
                reason = f"http_{status}"
                self._h_attempt.labels("http_5xx").observe(
                    time.perf_counter() - t_att)
                # 503 is the replica saying "not me right now"
                # (draining / warming) — orderly: it leaves rotation
                # immediately WITHOUT charging the breaker; other 5xx
                # are real failures that count toward it (rotation is
                # then the breaker's + the health poll's concern)
                self._finish_attempt(rep, ok=(status == 503),
                                     reason=reason,
                                     detail=f"HTTP {status}")
                if status == 503:
                    with self._lock:
                        self._mark_out_locked(
                            rep, str(resp.get("reason") or reason))
                last = (status, resp)
                backoff = self._maybe_retry(attempt, attempts, reason,
                                            rep)
                self.tracer.end_span(
                    tid, s_att, outcome=reason, status=status,
                    **({} if backoff is None
                       else {"backoff_s": backoff}))
                if backoff is not None:
                    self._sleep(backoff)
                continue
            # 2xx/3xx/4xx: final — 4xx is the client's to handle
            self._finish_attempt(rep, ok=True)
            outcome = OUTCOME_OK if status < 400 else \
                OUTCOME_CLIENT_ERROR
            self._h_attempt.labels(outcome).observe(
                time.perf_counter() - t_att)
            self.tracer.end_span(tid, s_att, outcome=outcome,
                                 status=status)
            if status == 200 and resp.get("disagg_redirect"):
                # the replica handed the lane to a peer (phase-aware
                # placement, or a drain-time live evacuation): collect
                # the final generation from the adopter
                target_rep = next(
                    (r for r in self.replicas
                     if r.base_url == str(resp.get("target") or "")),
                    None)
                status, resp = self._collect_redirect(tid, root, resp)
                if status >= 500:
                    outcome = OUTCOME_ERROR
                    # the adopter died mid-decode (hard preemption):
                    # before giving up, mine the fleet's commit
                    # journals — the evacuating source journaled the
                    # prefix — and re-place the request as a
                    # resume-from-token-k retry
                    backoff = self._maybe_retry(
                        attempt, attempts, "collect_failed", rep) \
                        if self.config.resume_from_journal else None
                    if backoff is not None:
                        answer, body = self._resume_from_journal(
                            body, target_rep)
                        if answer is not None:
                            status, resp = answer
                            outcome = OUTCOME_OK
                        else:
                            if (target_rep is not None
                                    and target_rep not in tried):
                                tried.append(target_rep)
                            last = (status, resp)
                            self._sleep(backoff)
                            continue
                elif status >= 400:
                    outcome = OUTCOME_CLIENT_ERROR
            self.tracer.end_span(tid, root, outcome=outcome,
                                 status=status, attempts=attempt + 1)
            self._h_request.labels(outcome).observe(
                time.perf_counter() - t0)
            if attempt > 0:
                self._log({"event": "fleet_request_recovered",
                           "request_id": body["request_id"],
                           "attempts": attempt + 1,
                           "replica": rep.name,
                           "trace_id": tid})
            return status, dict(resp, trace_id=tid)

        dt = time.perf_counter() - t0
        if last is None:
            self.tracer.end_span(tid, root,
                                 outcome=OUTCOME_UNAVAILABLE,
                                 attempts=len(tried))
            self._h_request.labels(OUTCOME_UNAVAILABLE).observe(dt)
            return 503, dict(self._no_replicas_payload(),
                             trace_id=tid)
        self._h_request.labels(OUTCOME_ERROR).observe(dt)
        status, resp = last
        self.tracer.end_span(tid, root, outcome=OUTCOME_ERROR,
                             status=status, attempts=len(tried))
        self._log({"event": "fleet_request_failed",
                   "request_id": body["request_id"],
                   "attempts": len(tried), "status": status,
                   "trace_id": tid})
        return status, dict(resp, trace_id=tid)

    def route_generate_stream(self, body: dict
                              ) -> Tuple[int, Optional[dict],
                                         Optional[Iterator[bytes]]]:
        """Proxy one STREAMING generate request (docs/streaming.md
        "Through the fleet"): same pick → attempt → retry ladder as
        `route_generate`, but the 200 answer is a live SSE frame
        iterator instead of a JSON body. Returns `(status, payload,
        frames)` — refusals answer as plain JSON before any stream
        byte (frames None); otherwise `(200, None, frames)` and the
        server layer writes the chunks verbatim.

        The router guarantees the CONCATENATED client stream is
        gapless and token-identical across replica failures: a dedupe
        cursor (`next_idx`) drops replayed token events, an
        `evacuated` terminal event is followed transparently to the
        adopter (`last_event_id` reconnect — the client never sees the
        move), and a mid-stream transport failure consults the fleet's
        commit journals exactly like `route_generate`: journaled
        committed tokens past the cursor are emitted immediately, then
        the retry resubmits with `resume_tokens`. Replayed prefixes on
        the replacement replica are token-identical even for sampled
        requests because the engine derives the per-lane RNG key from
        the pinned `request_id` (or the client's explicit `seed`) —
        never from placement."""
        if self._draining:
            return 503, {"error": "router draining",
                         "reason": "draining"}, None
        with self._lock:
            rid = body.get("request_id")
            if not rid:
                rid = f"fleet-{self._id_token}-{self._seq}"
            self._seq += 1
        body = dict(body, request_id=str(rid))
        return 200, None, self._stream_frames(body)

    def _find_replica(self, target: str) -> Optional[Replica]:
        t = str(target or "").rstrip("/")
        for r in self.replicas:
            if r.base_url.rstrip("/") == t or r.name == t:
                return r
        return None

    def _stream_frames(self, body: dict) -> Iterator[bytes]:
        """The frame generator behind `route_generate_stream` — runs
        on the server layer's writer thread, one attempt ladder per
        client connection. No disagg planning here: a streamed lane
        decodes where it prefilled, and the `evacuated` follow path
        covers every mid-generation move."""
        t0 = time.perf_counter()
        rid = body["request_id"]
        path = f"/api/{self.config.task}/stream"
        incoming = parse_traceparent(body.get("traceparent"))
        ctx = self.tracer.start_trace(
            "fleet/stream",
            trace_id=None if incoming is None else incoming.trace_id,
            parent_span_id=None if incoming is None
            else incoming.span_id,
            request_id=rid, task=self.config.task)
        tid, root = ctx.trace_id, ctx.span_id
        self._c_traces.inc()
        self._c_requests.inc()

        next_idx = 0  # dedupe cursor: next token index still owed
        attempts = self.config.max_retries + 1
        tried: List[Replica] = []
        follow: Optional[Replica] = None       # evacuation adopter
        follow_body: Optional[dict] = None     # its reconnect body
        last_err: dict = {"error": "stream retries exhausted",
                          "reason": "exhausted"}

        def finish(outcome: str, n_att: int, **attrs) -> None:
            self.tracer.end_span(tid, root, outcome=outcome,
                                 attempts=n_att, **attrs)
            self._h_request.labels(outcome).observe(
                time.perf_counter() - t0)

        for attempt in range(attempts):
            if follow is not None:
                # the previous replica evacuated the lane: pin the
                # adopter and reconnect from the cursor — the adopter
                # journals adopted lanes, so `attach_stream` replays
                # any tokens it committed while we were switching
                rep, follow = follow, None
                send, follow_body = follow_body, None
                with self._lock:
                    rep.in_flight += 1
            else:
                with self._lock:
                    rep = self._pick_locked(tried)
                    if rep is not None:
                        rep.in_flight += 1
                if rep is None:
                    break
                send = body
            if rep not in tried:
                tried.append(rep)
            s_att = self.tracer.start_span(
                tid, "router/attempt", root, attempt=attempt + 1,
                replica=rep.name, request_id=rid, stream=True)
            if s_att is not None:
                send = dict(send, traceparent=TraceContext(tid, s_att)
                            .to_traceparent())
            t_att = time.perf_counter()
            terminal: Optional[str] = None  # set => frames() returns
            failure: Optional[TransportError] = None
            http_err: Optional[Tuple[int, dict]] = None
            try:
                for ev in self.transport.stream(
                        rep.base_url, "POST", path, send,
                        self.config.request_timeout_s):
                    kind = ev.get("event")
                    if kind == "token":
                        idx = ev.get("id")
                        if idx is None or int(idx) >= next_idx:
                            i = next_idx if idx is None else int(idx)
                            yield format_event("token", ev["data"],
                                               event_id=i)
                            next_idx = i + 1
                        continue
                    if kind == "evacuated":
                        target = self._find_replica(
                            str(ev["data"].get("target") or ""))
                        if target is not None:
                            follow = target
                            follow_body = {
                                "request_id": rid,
                                "last_event_id": next_idx - 1}
                        # unknown adopter: fall through to the journal
                        # consult below, exactly like a dead replica
                        failure = TransportError(
                            "evacuated to unknown target", sent=True) \
                            if target is None else None
                        break
                    if kind in ("done", "timeout"):
                        yield format_event(
                            kind, ev["data"], event_id=ev.get("id"))
                        terminal = kind
                        break
                    if kind == "http_error":
                        http_err = (int(ev["status"]), ev["data"])
                        break
                    # ignore keep-alives / unknown event types
            except TransportError as e:
                failure = e
            if terminal is not None:
                self._finish_attempt(rep, ok=True)
                outcome = OUTCOME_OK if terminal == "done" \
                    else OUTCOME_ERROR
                self._h_attempt.labels(outcome).observe(
                    time.perf_counter() - t_att)
                self.tracer.end_span(tid, s_att, outcome=outcome,
                                     tokens=next_idx)
                finish(outcome, attempt + 1)
                if attempt > 0 or terminal == "done":
                    self._log({"event": "fleet_stream_done",
                               "request_id": rid, "reason": terminal,
                               "attempts": attempt + 1,
                               "tokens": next_idx, "trace_id": tid})
                return
            if follow is not None:
                # an orderly evacuation is a SUCCESS for the source
                self._finish_attempt(rep, ok=True)
                self._h_attempt.labels(OUTCOME_OK).observe(
                    time.perf_counter() - t_att)
                self.tracer.end_span(tid, s_att, outcome="evacuated",
                                     target=follow.name)
                self._log({"event": "fleet_stream_follow",
                           "request_id": rid, "target": follow.name,
                           "from_token": next_idx})
                continue
            if http_err is not None:
                status, resp = http_err
                reason = f"http_{status}"
                if status >= 500:
                    self._h_attempt.labels("http_5xx").observe(
                        time.perf_counter() - t_att)
                    self._finish_attempt(rep, ok=(status == 503),
                                         reason=reason,
                                         detail=f"HTTP {status}")
                    if status == 503:
                        with self._lock:
                            self._mark_out_locked(
                                rep,
                                str(resp.get("reason") or reason))
                    last_err = dict(resp, status=status)
                    backoff = self._maybe_retry(attempt, attempts,
                                                reason, rep)
                    self.tracer.end_span(
                        tid, s_att, outcome=reason, status=status,
                        **({} if backoff is None
                           else {"backoff_s": backoff}))
                    if backoff is not None:
                        self._sleep(backoff)
                    continue
                # 4xx before any stream byte: the client's to handle
                self._finish_attempt(rep, ok=True)
                self._h_attempt.labels(OUTCOME_CLIENT_ERROR).observe(
                    time.perf_counter() - t_att)
                self.tracer.end_span(tid, s_att,
                                     outcome=OUTCOME_CLIENT_ERROR,
                                     status=status)
                finish(OUTCOME_CLIENT_ERROR, attempt + 1,
                       status=status)
                yield format_event(
                    "error", dict(resp, status=status,
                                  request_id=rid, trace_id=tid))
                return
            # transport-level failure, an unknown evacuation target,
            # or a connection that closed without a terminal event (a
            # clean FIN from a dying replica) — all maybe-executed
            if failure is None:
                failure = TransportError(
                    "stream ended without a terminal event", sent=True)
            reason = "connect" if not failure.sent else "timeout"
            self._h_attempt.labels(reason).observe(
                time.perf_counter() - t_att)
            self._finish_attempt(rep, ok=False, reason=reason,
                                 detail=str(failure))
            last_err = {"error": f"replica {rep.name}: {failure}",
                        "reason": reason}
            if failure.sent and not self.config.retry_maybe_executed:
                self.tracer.end_span(tid, s_att, outcome=reason,
                                     error=str(failure)[:200],
                                     retried=False)
                break
            backoff = self._maybe_retry(attempt, attempts, reason,
                                        rep)
            self.tracer.end_span(
                tid, s_att, outcome=reason,
                error=str(failure)[:200],
                **({} if backoff is None else {"backoff_s": backoff}))
            if backoff is None:
                break
            if failure.sent and self.config.resume_from_journal:
                found = self._consult_journal(rid, rep)
                if found is None:
                    self._c_resume.labels("miss").inc()
                    # resubmit from scratch: the dedupe cursor plus
                    # the request-id-derived lane seed keep the
                    # replayed stream token-identical
                elif found[0] == "final":
                    # some replica already finished it: stream the
                    # journaled remainder, answer, done — no retry
                    _, payload, name = found
                    toks = [int(t)
                            for t in (payload.get("tokens") or [])]
                    for i in range(next_idx, len(toks)):
                        yield format_event("token",
                                           {"token": toks[i]},
                                           event_id=i)
                    next_idx = max(next_idx, len(toks))
                    yield format_event(
                        "done",
                        {"request_id": rid,
                         "finish_reason": payload.get("finish_reason"),
                         "result": payload.get("result")},
                        event_id=next_idx)
                    self._c_resume.labels("recovered").inc()
                    finish(OUTCOME_OK, attempt + 1)
                    self._log({"event": "fleet_stream_recovered",
                               "request_id": rid, "source": name,
                               "attempts": attempt + 1,
                               "trace_id": tid})
                    return
                else:
                    # journaled committed prefix: every token in it is
                    # safe to deliver NOW (commit-time publication),
                    # and the retry prefills prompt+prefix instead of
                    # regenerating from token 0
                    _, tokens, name = found
                    for i in range(next_idx, len(tokens)):
                        yield format_event("token",
                                           {"token": tokens[i]},
                                           event_id=i)
                    next_idx = max(next_idx, len(tokens))
                    body = dict(body, resume_tokens=tokens,
                                resume_source=name)
                    self._c_resume.labels("resumed").inc()
                    self._c_resume_tokens.inc(len(tokens))
                    self._log({"event": "fleet_stream_resume",
                               "request_id": rid, "source": name,
                               "tokens": len(tokens)})
            self._sleep(backoff)

        # exhausted (or nothing in rotation): one terminal error event
        n_att = len(tried)
        if not tried:
            last_err = self._no_replicas_payload()
            finish(OUTCOME_UNAVAILABLE, n_att)
        else:
            finish(OUTCOME_ERROR, n_att)
        self._log({"event": "fleet_stream_failed",
                   "request_id": rid, "attempts": n_att,
                   "delivered": next_idx, "trace_id": tid})
        yield format_event(
            "error", dict(last_err, request_id=rid, trace_id=tid,
                          delivered=next_idx))

    def _collect_redirect(self, tid: str, root: Optional[str],
                          resp: dict) -> Tuple[int, dict]:
        """A generate answered with `disagg_redirect`: the prefill
        replica primed the lane and pushed it to `target`, which now
        owns the decode tail. Long-poll the target's `GET /kv/<rid>`
        for the final generation-shaped body. The collect runs inside
        spans NAMED "router/attempt" (with replica + request_id attrs)
        on purpose: `assemble()` keys its waterfall fetches off that
        span name, so the decode replica's timeline joins the
        assembled trace with zero assembler changes. The GET is
        idempotent, so transport failures and 504 still-decoding
        answers retry in place (reason "collect"); any other failure
        is final — the request DID execute, so a silent re-route
        would risk decoding it twice."""
        rid = str(resp.get("request_id") or "")
        target = str(resp.get("target") or "")
        by_url = {r.base_url: r for r in self.replicas}
        rep = by_url.get(target)
        name = rep.name if rep is not None else target
        attempts = self.config.max_retries + 1
        last_err = "no collect attempt made"
        for attempt in range(attempts):
            s_col = self.tracer.start_span(
                tid, "router/attempt", root, replica=name,
                request_id=rid, kind="disagg_collect",
                attempt=attempt + 1)
            t_col = time.perf_counter()
            try:
                status, out = self.transport.request(
                    target, "GET", f"/kv/{rid}", None,
                    self.config.request_timeout_s)
            except TransportError as e:
                self._h_attempt.labels("collect_error").observe(
                    time.perf_counter() - t_col)
                self.tracer.end_span(tid, s_col,
                                     outcome="collect_error",
                                     error=str(e)[:200])
                last_err = str(e)
                if attempt + 1 < attempts:
                    self._c_retries.labels("collect").inc()
                continue
            if status == 200:
                self._h_attempt.labels(OUTCOME_OK).observe(
                    time.perf_counter() - t_col)
                self.tracer.end_span(tid, s_col, outcome=OUTCOME_OK,
                                     status=status)
                return 200, dict(out)
            self._h_attempt.labels("collect_error").observe(
                time.perf_counter() - t_col)
            self.tracer.end_span(tid, s_col, outcome="collect_error",
                                 status=status)
            last_err = f"HTTP {status}"
            if status != 504:
                break
            if attempt + 1 < attempts:
                self._c_retries.labels("collect").inc()
        self._log({"event": "fleet_collect_failed",
                   "request_id": rid, "replica": name,
                   "detail": last_err[:200]})
        return 502, {"error": f"disagg collect from {name}: "
                              f"{last_err[:200]}",
                     "reason": "collect_failed",
                     "request_id": rid}

    # ---- resume-from-token-k (docs/fault_tolerance.md) --------------

    def _consult_journal(self, rid: str, first: Optional[Replica]
                         ) -> Optional[Tuple[str, Any, str]]:
        """Ask the fleet for request `rid`'s commit journal
        (`GET /partial/<rid>`). The failed replica is asked FIRST — a
        replica that timed out (or evacuated the lane before dying)
        often still serves its journal — then every other replica (the
        adopter of an evacuated lane journals it too, so a hard-killed
        source leaves the prefix readable on its peer). Returns
        ("final", payload, name) when some replica already FINISHED
        the request (answer it without any resubmit), ("resume",
        tokens, name) for a journaled prefix of >= 1 committed token,
        or None — nothing journaled anywhere, regenerate from 0."""
        order = ([first] if first is not None else []) + \
            [r for r in self.replicas if r is not first]
        for rep in order:
            try:
                code, out = self.transport.request(
                    rep.base_url, "GET", f"/partial/{rid}", None,
                    self.config.poll_timeout_s)
            except TransportError:
                continue
            except Exception:  # noqa: BLE001 — a journal probe bug
                # must degrade to regenerate-from-0, never fail the
                # retry that is about to recover the request
                continue
            if code != 200:
                continue
            if out.get("state") == "finished" and "result" in out:
                return ("final", out, rep.name)
            tokens = [int(t) for t in (out.get("tokens") or [])]
            if tokens:
                return ("resume", tokens, rep.name)
        return None

    def _resume_from_journal(self, body: dict, failed: Optional[Replica]
                             ) -> Tuple[Optional[Tuple[int, dict]],
                                        dict]:
        """A maybe-executed attempt failed on `failed`: mine the
        fleet's commit journals before the retry. Returns
        (final_answer, body): a non-None final_answer short-circuits
        the retry entirely (some replica already finished the request
        — e.g. the evacuated lane's adopter completed it); otherwise
        the returned body carries `resume_tokens`/`resume_source` when
        a journaled prefix was found, so the retry prefills
        prompt+prefix and decodes only the remainder instead of
        regenerating from token 0."""
        found = self._consult_journal(body["request_id"], failed)
        if found is None:
            self._c_resume.labels("miss").inc()
            return None, body
        kind, payload, name = found
        if kind == "final":
            self._c_resume.labels("recovered").inc()
            self._log({"event": "fleet_resume_recovered",
                       "request_id": body["request_id"],
                       "source": name})
            return (200, {"result": payload.get("result"),
                          "request_id": body["request_id"],
                          "ttft_s": payload.get("ttft_s"),
                          "finish_reason":
                              payload.get("finish_reason")}), body
        self._c_resume.labels("resumed").inc()
        self._c_resume_tokens.inc(len(payload))
        self._log({"event": "fleet_resume",
                   "request_id": body["request_id"], "source": name,
                   "tokens": len(payload)})
        return None, dict(body, resume_tokens=payload,
                          resume_source=name)

    def _maybe_retry(self, attempt: int, attempts: int, reason: str,
                     rep: Replica) -> Optional[float]:
        """Count + compute backoff for the retry that will follow this
        failed attempt (only when one WILL follow — an exhausted
        request is a failure, not a retry). Returns the backoff to
        sleep, or None when no retry follows. The caller sleeps AFTER
        ending the attempt span: the span measures the attempt, and
        the wait rides along as its ``backoff_s`` attr — otherwise the
        span's duration and the attempt histogram would disagree about
        the same attempt."""
        if attempt + 1 >= attempts:
            return None
        self._c_retries.labels(reason).inc()
        self._log({"event": "fleet_retry", "reason": reason,
                   "replica": rep.name, "attempt": attempt + 1})
        return self._backoff_s(attempt + 1)

    # ---- trace assembly (docs/observability.md) ---------------------

    def assemble(self, trace_id: str) -> Optional[dict]:
        """`GET /debug/traces/<trace_id>`: stitch the router's span
        ledger with the involved replicas' `/debug/requests/<id>`
        waterfalls into ONE cross-process trace. Replicas are the ones
        the attempt spans name; each attachment carries the clock
        anchoring (`offset_in_trace_s`, `clock_skew_s` — skew reported,
        never hidden) and a fetch failure degrades to an `error` entry:
        a dead replica must not make its trace unreadable. None when
        the trace id is unknown (never minted, or aged out of the
        ledger ring)."""
        trace = self.tracer.get_trace(trace_id)
        if trace is None:
            return None
        request_id = None
        involved: List[str] = []
        rids: Dict[str, List[str]] = {}
        for span in trace["spans"]:
            attrs = span.get("attrs", {})
            if request_id is None and "request_id" in attrs:
                request_id = attrs["request_id"]
            if span["name"] == "router/attempt":
                name = attrs.get("replica")
                if name and name not in involved:
                    involved.append(name)
                rid = attrs.get("request_id")
                if name and rid is not None and \
                        rid not in rids.setdefault(name, []):
                    rids[name].append(rid)
        by_name = {r.name: r for r in self.replicas}
        fetches: Dict[str, dict] = {}
        for name in involved:
            rep = by_name.get(name)
            # prefer the request id the attempt span itself recorded
            # (a joined trace can span several requests); fall back to
            # the trace-level first for ledgers predating the attr
            seen = rids.get(name, [])
            rid = seen[0] if seen else request_id
            if rep is None or rid is None:
                fetches[name] = {"error": "unknown_replica"}
                self._c_trace_fetch_errors.inc()
                continue
            try:
                code, payload = self.transport.request(
                    rep.base_url, "GET",
                    f"/debug/requests/{rid}", None,
                    self.config.poll_timeout_s)
            except TransportError as e:
                fetches[name] = {
                    "error": f"unreachable: {str(e)[:200]}"}
                self._c_trace_fetch_errors.inc()
                continue
            except Exception as e:  # noqa: BLE001 — assembly is a
                # debug read; a transport bug must degrade to an error
                # entry, never 500 the whole trace
                fetches[name] = {
                    "error": f"{type(e).__name__}: {str(e)[:200]}"}
                self._c_trace_fetch_errors.inc()
                continue
            if code == 200:
                fetches[name] = {"waterfall": payload}
            else:
                # 404: the replica never executed it (a connect-level
                # failure) or its debug ring aged the entry out
                fetches[name] = {"error": f"http_{code}"}
                self._c_trace_fetch_errors.inc()
        for name, seen in rids.items():
            # one attachment per replica: when a joined trace routed
            # SEVERAL requests to the same replica, the later ones are
            # named rather than silently invisible
            if len(seen) > 1 and name in fetches:
                fetches[name]["other_request_ids"] = seen[1:]
        self._c_trace_assembled.inc()
        return assemble_trace(trace, fetches)

    # ---- introspection ----------------------------------------------

    def fleet_state(self) -> dict:
        """The `/fleet` debug JSON: per-replica rotation + breaker +
        occupancy + last error. Deterministic (sorted keys downstream,
        rounded floats) given a deterministic clock."""
        with self._lock:
            now = self._clock()
            reps = []
            counts = {HEALTHY: 0, DRAINING: 0, BROKEN: 0}
            for rep in self.replicas:
                counts[rep.state] += 1
                err = None
                if rep.last_error is not None:
                    err = {"detail": rep.last_error["detail"],
                           "age_s": round(now - rep.last_error["at"],
                                          3)}
                cooldown = None
                if rep.breaker_open_until is not None:
                    cooldown = round(
                        max(rep.breaker_open_until - now, 0.0), 3)
                poll_age = None
                if rep.last_poll_at is not None:
                    poll_age = round(max(now - rep.last_poll_at, 0.0),
                                     3)
                reps.append({
                    "name": rep.name,
                    "url": rep.base_url,
                    "state": rep.state,
                    "phase": rep.phase,
                    "reason": rep.reason,
                    # a stuck poll loop reads as a growing age here
                    # (None = never completed a poll), and the failure
                    # streak is visible without opening the breaker
                    # sub-dict
                    "last_poll_age_s": poll_age,
                    "consecutive_failures": rep.consecutive_failures,
                    "breaker": {
                        "consecutive_failures":
                            rep.consecutive_failures,
                        "open": rep.state == BROKEN,
                        "cooldown_remaining_s": cooldown,
                        "half_open_inflight": rep.half_open_inflight,
                    },
                    "occupancy": {
                        "slots_active": rep.slots_active,
                        "num_slots": rep.num_slots,
                        "queue_depth": rep.queue_depth,
                        "in_flight": rep.in_flight,
                        "draining_reported": rep.draining_reported,
                    },
                    "last_error": err,
                })
            return {
                "replicas": reps,
                "healthy": counts[HEALTHY],
                "draining": counts[DRAINING],
                "broken": counts[BROKEN],
                "topology": disagg_policy.topology(
                    [r.phase for r in self.replicas]),
                "router_draining": self._draining,
                "requests_total": int(self._c_requests.value()),
                "retries_total": self.retries_total(),
                "uptime_s": round(now - self._t0, 3),
            }

    def retries_total(self) -> Dict[str, int]:
        """{reason: count} over fstpu_fleet_retries_total (sorted)."""
        return {values[0]: int(child.value)
                for values, child in self._c_retries.children()}
