"""Fleet microbench: aggregate tokens/s at N replicas vs one, plus the
kill-one-replica-mid-run robustness rung.

    make serve-bench-fleet
    FLEET_BENCH_REPLICAS=3 python -m fengshen_tpu.fleet.bench

Spawns N **real replica subprocesses** (`--replica`: a random-init
llama in the weight-memory-bound serve-bench shape behind the stdlib
api server + continuous engine), fronts them with a `FleetRouter`, and
drives the same request set three ways:

1. one replica only → `tokens_per_sec_1` (the baseline);
2. all N replicas → `value` (the ≥2x acceptance bar of ISSUE 10 —
   each replica is slot-capacity-bound, so the fleet's win is real
   batched-decode capacity, not timer noise);
3. all N replicas with replica #1 SIGKILLed after `KILL_AFTER`
   responses: every request must still answer 200 (the router retries
   connect/reset failures on a different replica; requests are
   idempotent-safe greedy with router-assigned ids), `failed` must be
   0, and the kill-run outputs must be token-identical to run 2's.

One BENCH-schema JSON line ({"metric", "value", "unit",
"vs_baseline", ...}) with the **replica count in the row**
(`"replicas": N`): benchdiff treats rows at different N as
incomparable, like offload placements (docs/observability.md).

`FLEET_BENCH_FAKE=1` swaps the replicas for in-process fake servers
(pure stdlib, no jax: deterministic token function + a per-token sleep
emulating decode) so the fast-lane smoke test
(`tests/test_fleet_bench_smoke.py`) exercises the whole harness —
schema, phases, the kill rung — in a couple of seconds without a
model. Env knobs (FLEET_BENCH_*): REPLICAS, REQUESTS, NEW_TOKENS,
SLOTS (per replica), KILL (0 disables rung 3), KILL_AFTER, FAKE,
FAKE_TOKEN_S, BASE_PORT, and the serve-bench model shape knobs VOCAB /
HIDDEN / INTER / LAYERS / HEADS / BUCKETS / SEED.
"""

from __future__ import annotations

import argparse
import http.server
import json
import os
import signal
import subprocess
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Optional, Tuple

from fengshen_tpu.fleet.router import FleetConfig, FleetRouter


def _env(name: str, default: int) -> int:
    return int(os.environ.get(f"FLEET_BENCH_{name}", default))


def _buckets() -> Tuple[int, ...]:
    return tuple(int(b) for b in os.environ.get(
        "FLEET_BENCH_BUCKETS", "32,64").split(","))


def _emit(row: dict) -> None:
    from fengshen_tpu.observability import JsonlSink
    if os.environ.get("BENCH_DEGRADED", "0") == "1":
        row["degraded"] = True
    JsonlSink(stream=sys.stdout, only_process_zero=False)(row)


class _IntTokenizer:
    """Whitespace-int tokenizer ('5 7 9' <-> [5, 7, 9]) — the bench's
    prompts are synthetic, a real vocab would only add weight."""

    eos_token_id = None
    pad_token_id = 0

    def encode(self, text):
        return [int(t) for t in text.split()]

    def decode(self, ids):
        return " ".join(str(int(t)) for t in ids)


# ---- fake replicas (FLEET_BENCH_FAKE=1: the harness-smoke path) -----

def _fake_result(ids: List[int], n: int, vocab: int = 97) -> str:
    """Deterministic stand-in for greedy decode: the same prompt gives
    the same tokens on EVERY replica, so retry/kill runs can assert
    token identity without a model."""
    s = sum(ids)
    return " ".join(str((s + i) % vocab) for i in range(n))


def start_fake_replica(num_slots: int, token_s: float,
                       default_new_tokens: int,
                       host: str = "127.0.0.1", port: int = 0):
    """In-process fake api replica: /healthz, /stats, and a generate
    route whose latency is num-tokens x token_s gated by a
    num_slots-wide semaphore (decode capacity). Returns (server,
    thread); kill it with `server.shutdown(); server.server_close()`
    (new connects then refuse — the fake analog of a dead process)."""
    sem = threading.BoundedSemaphore(num_slots)
    lock = threading.Lock()
    active = [0]

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _send(self, code, payload):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                self._send(200, {"status": "ok", "ready": True})
            elif self.path == "/stats":
                with lock:
                    a = active[0]
                self._send(200, {"slots_active": min(a, num_slots),
                                 "queue_depth": max(a - num_slots, 0),
                                 "num_slots": num_slots,
                                 "draining": False})
            else:
                self._send(404, {"error": "not found"})

        def do_POST(self):
            if not self.path.startswith("/api/"):
                self._send(404, {"error": "not found"})
                return
            length = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(length) or b"{}")
            ids = [int(t) for t in req["input_text"].split()]
            n = int(req.get("max_new_tokens") or default_new_tokens)
            with lock:
                active[0] += 1
            try:
                with sem:
                    time.sleep(n * token_s)
            finally:
                with lock:
                    active[0] -= 1
            self._send(200, {"result": _fake_result(ids, n),
                             "request_id": req.get("request_id"),
                             "ttft_s": 0.0,
                             "finish_reason": "length"})

    server = http.server.ThreadingHTTPServer((host, port), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server, thread


# ---- real replica subprocess (`--replica`) --------------------------

def replica_main(port: int) -> None:
    """Subprocess entry: random-init llama (serve-bench's default
    weight-memory-bound shape) + continuous engine + stdlib api server
    with warmup gating and SIGTERM drain — a faithful single replica."""
    import jax
    import jax.numpy as jnp

    from fengshen_tpu.api.main import (PipelineConfig, ServerConfig,
                                       _start_warmup_thread,
                                       build_stdlib_server,
                                       create_continuous_engine,
                                       install_drain_handler)
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.pipelines.text_generation import Pipeline

    buckets = _buckets()
    new_tokens = _env("NEW_TOKENS", 48)
    config = LlamaConfig(
        vocab_size=_env("VOCAB", 4096),
        hidden_size=_env("HIDDEN", 1024),
        intermediate_size=_env("INTER", 2816),
        num_hidden_layers=_env("LAYERS", 4),
        num_attention_heads=_env("HEADS", 8),
        max_position_embeddings=buckets[-1] + new_tokens,
        dtype="float32")
    model = LlamaForCausalLM(config)
    params = jax.jit(lambda r: model.init(
        r, jnp.zeros((1, 8), jnp.int32))["params"])(
        jax.random.PRNGKey(_env("SEED", 0)))
    pipe = Pipeline(module=model, params=params,
                    tokenizer=_IntTokenizer(),
                    max_new_tokens=new_tokens, eos_token_id=None,
                    pad_token_id=0)
    engine = create_continuous_engine(
        pipe, {"num_slots": _env("SLOTS", 2), "buckets": buckets,
               "max_new_tokens": new_tokens, "max_queue": 512})
    server_cfg = ServerConfig(host="127.0.0.1", port=port,
                              engine="continuous")
    pipeline_cfg = PipelineConfig(task="text_generation")
    ready = _start_warmup_thread(server_cfg, pipeline_cfg, pipe, engine)
    draining = threading.Event()
    server = build_stdlib_server(server_cfg, pipeline_cfg,
                                 pipeline=pipe, engine=engine,
                                 ready=ready, draining=draining)
    install_drain_handler(server, draining, engine=engine)
    print(f"[fleet-bench] replica on 127.0.0.1:{port}", flush=True)
    server.serve_forever()


def _spawn_real_replicas(n: int, base_port: int
                         ) -> Tuple[List[str], list]:
    procs, targets = [], []
    for i in range(n):
        port = base_port + i
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "fengshen_tpu.fleet.bench",
             "--replica", "--port", str(port)]))
        targets.append(f"127.0.0.1:{port}")
    return targets, procs


# ---- the driver -----------------------------------------------------

def _make_router(targets, timeout_s: float = 180.0,
                 poll_interval_s: float = 0.2) -> FleetRouter:
    """Router over `targets`, polled until every replica is healthy
    (replica warmup bounds the wait)."""
    router = FleetRouter(FleetConfig(
        replicas=targets, max_retries=3, breaker_threshold=2,
        breaker_cooldown_s=2.0, recovery_probes=1,
        poll_interval_s=poll_interval_s, request_timeout_s=300.0))
    deadline = time.monotonic() + timeout_s
    while router.healthy_count() < len(targets):
        if time.monotonic() > deadline:
            raise RuntimeError(
                f"replicas not healthy after {timeout_s}s: "
                f"{router.fleet_state()}")
        router.poll_once()
        time.sleep(0.2)
    router.start_polling()
    return router


def _drive(router: FleetRouter, prompts: List[str], new_tokens: int,
           width: int,
           kill: Optional[Tuple[int, Callable[[], None]]] = None
           ) -> dict:
    """Push every prompt through the router from a `width`-wide pool;
    with `kill=(after, fn)`, fn fires once `after` responses landed."""
    results: List[Optional[str]] = [None] * len(prompts)
    failed: List[Tuple[int, int, dict]] = []
    lock = threading.Lock()
    done = [0]
    killed = [False]

    def one(i: int) -> None:
        status, body = router.route_generate(
            {"input_text": prompts[i], "max_new_tokens": new_tokens})
        with lock:
            done[0] += 1
            fire = (kill is not None and not killed[0]
                    and done[0] >= kill[0])
            if fire:
                killed[0] = True
        if fire:
            kill[1]()
        if status == 200:
            results[i] = body["result"]
        else:
            with lock:
                failed.append((i, status, body))

    t0 = time.perf_counter()
    with ThreadPoolExecutor(max_workers=width) as pool:
        list(pool.map(one, range(len(prompts))))
    dt = time.perf_counter() - t0
    tokens = sum(len(r.split()) for r in results if r)
    return {"seconds": dt, "tokens": tokens,
            "tokens_per_sec": tokens / dt if dt > 0 else 0.0,
            "results": results, "failed": failed}


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(
        prog="python -m fengshen_tpu.fleet.bench")
    parser.add_argument("--replica", action="store_true",
                        help="run as a bench replica subprocess")
    parser.add_argument("--port", type=int, default=0)
    args = parser.parse_args(argv)
    if args.replica:
        replica_main(args.port)
        return

    n = _env("REPLICAS", 3)
    slots = _env("SLOTS", 2)
    new_tokens = _env("NEW_TOKENS", 48)
    n_req = max(_env("REQUESTS", 6 * n * slots), 2)
    fake = _env("FAKE", 0) == 1
    kill_enabled = _env("KILL", 1) == 1 and n > 1
    kill_after = _env("KILL_AFTER", max(n_req // 4, 1))
    buckets = _buckets()
    width = max(2 * n * slots, 4)

    import random as _random
    rng = _random.Random(_env("SEED", 0))
    prompt_len = max(buckets[0] // 2, 1)
    prompts = [" ".join(str(rng.randint(3, 95))
                        for _ in range(prompt_len))
               for _ in range(n_req)]

    procs: list = []
    fake_servers: list = []
    if fake:
        token_s = float(os.environ.get("FLEET_BENCH_FAKE_TOKEN_S",
                                       "0.002"))
        targets = []
        for _ in range(n):
            server, _t = start_fake_replica(slots, token_s, new_tokens)
            fake_servers.append(server)
            targets.append("127.0.0.1:%d" % server.server_address[1])
    else:
        targets, procs = _spawn_real_replicas(
            n, _env("BASE_PORT", 8190))

    try:
        # 1. baseline: the fleet reduced to ONE replica
        r1 = _make_router(targets[:1])
        single = _drive(r1, prompts, new_tokens, width=max(2 * slots,
                                                           2))
        r1.stop()
        # 2. the fleet: same requests, N replicas
        rn = _make_router(targets)
        full = _drive(rn, prompts, new_tokens, width=width)
        rn.stop()
        # 3. kill rung: replica #1 dies mid-run; zero failures allowed
        kill_section = {"enabled": False}
        if kill_enabled:
            # poll slower than the rung lasts: the router must discover
            # the death through a FAILED REQUEST (breaker + retry), not
            # through a lucky health poll — otherwise `retries >= 1` is
            # a race against the poll thread
            rk = _make_router(targets, poll_interval_s=60.0)

            def kill_victim():
                if fake:
                    fake_servers[1].shutdown()
                    fake_servers[1].server_close()
                else:
                    procs[1].kill()     # SIGKILL: the harsh path — no
                    #   drain, in-flight requests die with it
                print(f"[fleet-bench] killed replica {targets[1]}",
                      flush=True)

            killrun = _drive(rk, prompts, new_tokens, width=width,
                             kill=(kill_after, kill_victim))
            retries = sum(rk.retries_total().values())
            rk.stop()
            kill_section = {
                "enabled": True,
                "killed": targets[1],
                "after_responses": kill_after,
                "failed": len(killrun["failed"]),
                "completed": sum(1 for r in killrun["results"]
                                 if r is not None),
                "retries": retries,
                "token_identical":
                    killrun["results"] == full["results"],
            }

        tps1 = single["tokens_per_sec"]
        tpsn = full["tokens_per_sec"]
        if fake:
            backend = "fake"
        else:
            import jax
            backend = jax.default_backend()
        _emit({
            "metric": "fleet_router_tokens_per_sec",
            "value": round(tpsn, 1),
            "unit": "tokens/s",
            "vs_baseline": round(tpsn / tps1, 3) if tps1 > 0 else 0.0,
            "mode": "fleet",
            # the comparison identity: benchdiff never compares fleet
            # rows across different replica counts
            "replicas": n,
            "num_slots": slots,
            "requests": n_req,
            "new_tokens": new_tokens,
            "tokens_per_sec_1": round(tps1, 1),
            "failed": len(single["failed"]) + len(full["failed"]),
            "token_identical_n_vs_1":
                full["results"] == single["results"],
            "kill": kill_section,
            "fake": fake,
            "backend": backend,
        })
    finally:
        for server in fake_servers:
            try:
                server.shutdown()
                server.server_close()
            except OSError:
                pass
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()


if __name__ == "__main__":
    main()
