"""The router process's own HTTP surface (pure stdlib).

Mirrors the replica server's conventions (`api/main.py`):

- ``POST /api/<task>``: proxied through `FleetRouter.route_generate`
  (the router adds a `request_id` the replica dedupes — see
  docs/fleet.md "Retries and idempotency");
- ``POST /api/<task>/stream``: the SSE proxy
  (`FleetRouter.route_generate_stream`, docs/streaming.md "Through
  the fleet") — token events relayed as they arrive, replica failures
  retried/resumed mid-stream so the client sees one gapless stream;
- ``GET /healthz``: 200 `{"ready": true}` iff the router is not
  draining AND at least one replica is in rotation; otherwise 503 with
  `{"ready": false, "reason": "draining" | "no_healthy_replicas"}` —
  the same body contract the replicas answer, so an outer balancer can
  stack routers;
- ``GET /metrics``: Prometheus text over the router's own registry
  (`fstpu_fleet_*`, `fstpu_trace_*`) plus the process-global one;
- ``GET /fleet``: the per-replica debug JSON (`fleet_state()`);
- ``GET /debug/traces/<trace_id>``: the assembled cross-process trace
  (`FleetRouter.assemble` — the router's span ledger stitched with the
  involved replicas' waterfalls, docs/observability.md "Distributed
  tracing"), deterministic sorted JSON like `/fleet`.

Every endpoint times itself into the same
`fstpu_http_request_seconds{route}` histogram (+ per-route/status
counter) the replica servers feed, so router-side latency and
replica-side latency read on one dashboard.

`install_router_sigterm` wires graceful drain: SIGTERM stops admission
(healthz flips to draining-503, new generates answer 503), in-flight
requests finish against their replica, then the server shuts down.
"""

from __future__ import annotations

import http.server
import json
import signal
import threading
import time
from typing import Optional

from fengshen_tpu.fleet.router import FleetRouter


def _observe_http(route: str, code: int, seconds: float) -> None:
    """The replica servers' request telemetry, fed from the router's
    own endpoints too — the shared families in
    `observability.httpmetrics`, so router/replica latency read on one
    dashboard."""
    from fengshen_tpu.observability.httpmetrics import (
        http_request_seconds, http_requests_total)
    http_requests_total().labels(route, code).inc()
    http_request_seconds().labels(route).observe(seconds)


def _classify_route(path: str, api_route: str) -> str:
    """Bounded label cardinality: a trace id must not become one label
    value per request."""
    if path.startswith("/debug/traces/"):
        return "/debug/traces/<id>"
    return path if path in (api_route, f"{api_route}/stream",
                            "/healthz", "/fleet",
                            "/metrics") else "other"


def healthz_payload(router: FleetRouter) -> tuple:
    """(code, body) for the router's /healthz."""
    if router.draining:
        return 503, {"ready": False, "reason": "draining",
                     "healthy_replicas": router.healthy_count()}
    n = router.healthy_count()
    if n < 1:
        body = {"ready": False, "reason": "no_healthy_replicas"}
        # the loud part: name every replica's state, not a bare 503
        body["replicas"] = {
            r["name"]: {"state": r["state"], "reason": r["reason"]}
            for r in router.fleet_state()["replicas"]}
        return 503, body
    return 200, {"ready": True, "healthy_replicas": n}


def build_fleet_server(router: FleetRouter, host: str = "0.0.0.0",
                       port: int = 8080):
    """ThreadingHTTPServer over the router; `serve_forever()` to run.
    The returned server carries `.router` and an in-flight counter the
    drain handler consults."""
    route_prefix = "/api/"
    api_route = f"/api/{router.config.task}"

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _send(self, code: int, payload, content_type: str =
                  "application/json") -> None:
            body = payload if isinstance(payload, bytes) else \
                json.dumps(payload, ensure_ascii=False,
                           sort_keys=True).encode()
            # the router's own endpoints time themselves like the
            # replica servers' do (same histogram + counter families)
            t0 = getattr(self, "_t_start", None)
            if t0 is not None:
                _observe_http(_classify_route(self.path, api_route),
                              code, time.perf_counter() - t0)
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            self._t_start = time.perf_counter()
            if self.path == "/healthz":
                code, body = healthz_payload(router)
                self._send(code, body)
            elif self.path == "/fleet":
                self._send(200, router.fleet_state())
            elif self.path.startswith("/debug/traces/"):
                trace_id = self.path[len("/debug/traces/"):]
                assembled = router.assemble(trace_id)
                if assembled is None:
                    self._send(404, {"error":
                                     f"unknown trace_id {trace_id!r}"})
                else:
                    self._send(200, assembled)
            elif self.path == "/metrics":
                from fengshen_tpu.observability import (
                    CONTENT_TYPE_LATEST, get_registry,
                    render_prometheus)
                text = render_prometheus(get_registry(),
                                         router.registry)
                self._send(200, text.encode(), CONTENT_TYPE_LATEST)
            else:
                self._send(404, {"error": "not found"})

        def _send_stream(self, frames) -> None:
            """Write an SSE response chunk-by-chunk (the streaming
            route's 200 path). Mirrors the replica server's writer:
            headers first, then each frame flushed as it arrives; a
            client that hangs up mid-stream just ends the generator."""
            t0 = getattr(self, "_t_start", None)
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Connection", "close")
            self.end_headers()
            try:
                for chunk in frames:
                    self.wfile.write(chunk)
                    self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError):
                pass        # client went away; the generator cleans up
            finally:
                frames.close()
            if t0 is not None:
                _observe_http(_classify_route(self.path, api_route),
                              200, time.perf_counter() - t0)

        def do_POST(self):
            self._t_start = time.perf_counter()
            if not self.path.startswith(route_prefix):
                self._send(404, {"error": "not found"})
                return
            stream = self.path == f"{api_route}/stream"
            length = int(self.headers.get("Content-Length", 0))
            try:
                req = json.loads(self.rfile.read(length) or b"{}")
            except json.JSONDecodeError as e:
                self._send(422, {"error": f"invalid json: {e}"})
                return
            if "input_text" not in req and not stream:
                self._send(422, {"error": "input_text required"})
                return
            tp = self.headers.get("traceparent")
            if tp and not req.get("traceparent"):
                # an upstream caller's trace context arrives header-
                # first here too; the router JOINS it instead of
                # minting a fresh trace
                req["traceparent"] = tp
            if stream:
                lei = self.headers.get("Last-Event-ID")
                if lei is not None and req.get("last_event_id") is None:
                    try:
                        req["last_event_id"] = int(lei)
                    except ValueError:
                        pass
                reconnect = (req.get("request_id") is not None
                             and req.get("last_event_id") is not None)
                if "input_text" not in req and not reconnect:
                    self._send(422, {"error": "input_text required"})
                    return
                code, body, frames = router.route_generate_stream(req)
                if frames is None:
                    self._send(code, body)
                else:
                    self._send_stream(frames)
                return
            code, body = router.route_generate(req)
            self._send(code, body)

    server = http.server.ThreadingHTTPServer((host, port), Handler)
    server.router = router
    return server


def install_router_sigterm(router: FleetRouter, server,
                           drain_timeout_s: float = 30.0,
                           on_drained=None) -> bool:
    """SIGTERM → drain → (in-flight finish) → server shutdown.
    Deliberately REPLACES (does not chain) any prior SIGTERM handler,
    exactly like the replica side's `install_drain_handler`: the
    repo's flight-recorder handler re-delivers the default disposition
    after dumping — immediate death — which is what a drain must
    prevent. A second SIGTERM while a drain is underway is a no-op.
    Returns False off the main thread."""
    if threading.current_thread() is not threading.main_thread():
        return False

    def handler(signum, frame):
        if router.draining:
            return      # second SIGTERM: drain already underway
        router.drain()

        def waiter():
            router.wait_drained(timeout_s=drain_timeout_s)
            router.stop()
            if on_drained is not None:
                try:
                    on_drained()
                except Exception:  # noqa: BLE001 — the shutdown path
                    # must reach server.shutdown() regardless
                    pass
            server.shutdown()

        threading.Thread(target=waiter, daemon=True,
                         name="fstpu-fleet-drain").start()

    signal.signal(signal.SIGTERM, handler)
    return True


def serve(router: FleetRouter, host: str, port: int,
          drain_timeout_s: float = 30.0,
          on_drained=None) -> None:
    """Blocking entry: poll, install drain, serve until shutdown."""
    server = build_fleet_server(router, host, port)
    router.start_polling()
    install_router_sigterm(router, server,
                           drain_timeout_s=drain_timeout_s,
                           on_drained=on_drained)
    bound = server.server_address
    print(f"[fleet] router on {bound[0]}:{bound[1]} over "
          f"{len(router.replicas)} replica(s): "
          f"{', '.join(r.name for r in router.replicas)}", flush=True)
    try:
        server.serve_forever()
    finally:
        server.server_close()
        router.stop()
