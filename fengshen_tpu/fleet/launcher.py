"""Local fleet launcher: spawn N stdlib api replicas from one config.

`python -m fengshen_tpu.fleet --spawn N --config api.json` (the
`make serve-fleet` path) takes the SAME config file a single replica
runs with (`api/main.py`), writes N derived copies whose `SERVER.port`
is `base_port + i`, and starts each as a
`python -m fengshen_tpu.api.main --config <derived>` subprocess. The
router then fronts them; its health gating keeps traffic off each
replica until its warmup 503 window closes, and its drain handler
SIGTERMs the children (each drains gracefully, docs/fleet.md "Drain
runbook") once the router itself has drained.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
from typing import List, Sequence, Tuple

from fengshen_tpu.disagg.policy import validate_phase


def spawn_replicas(config_path: str, n: int, base_port: int,
                   host: str = "127.0.0.1",
                   workdir: str = None,
                   phases: Sequence[str] = ()
                   ) -> Tuple[List[str], list]:
    """Write derived configs and start N replica subprocesses. Returns
    (targets, processes) where targets are "host:port" strings for
    `FleetConfig.replicas`. Replicas inherit this process's env (so
    `JAX_PLATFORMS` etc. flow through) plus `FSTPU_API_SERVER=stdlib`:
    only the stdlib server path has the SIGTERM graceful drain the
    fleet's rolling restarts depend on — a uvicorn replica would die
    with its in-flight requests instead of draining.

    `phases` assigns replica i the serving phase `phases[i]`
    (`prefill` | `decode` | `both`, docs/disaggregation.md) via its
    derived config's `SERVER.phase`; replicas past the end of the list
    stay homogeneous (`both`)."""
    if n < 1:
        raise ValueError("need at least one replica")
    phases = [validate_phase(p) for p in phases]
    if len(phases) > n:
        raise ValueError(f"{len(phases)} phases for {n} replicas")
    with open(config_path) as f:
        raw = json.load(f)
    workdir = workdir or tempfile.mkdtemp(prefix="fstpu_fleet_")
    targets, procs = [], []
    for i in range(n):
        cfg = json.loads(json.dumps(raw))    # deep copy
        server = cfg.setdefault("SERVER", {})
        port = base_port + i
        server["host"] = host
        server["port"] = port
        if i < len(phases):
            server["phase"] = phases[i]
        # per-replica dump dirs: two replicas sharing one flight-
        # recorder directory would interleave their bundle sequences
        server["dump_dir"] = os.path.join(
            workdir, f"replica{i}_dumps")
        path = os.path.join(workdir, f"replica{i}.json")
        with open(path, "w") as f:
            json.dump(cfg, f, indent=1, sort_keys=True)
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "fengshen_tpu.api.main",
             "--config", path],
            env={**os.environ, "FSTPU_API_SERVER": "stdlib"}))
        targets.append(f"{host}:{port}")
    return targets, procs


def terminate_replicas(procs, timeout_s: float = 30.0) -> None:
    """SIGTERM every replica (graceful drain), then wait; SIGKILL any
    that outlive the timeout."""
    for p in procs:
        if p.poll() is None:
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                pass
    for p in procs:
        try:
            p.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
