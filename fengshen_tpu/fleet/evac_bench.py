"""Preemption-tolerance microbench: SIGTERM-mid-decode (live lane
evacuation) and SIGKILL-mid-decode (resume-from-token-k) drills.

    make serve-bench-evac
    FLEET_BENCH_FAKE=1 python -m fengshen_tpu.fleet.evac_bench

Three rungs over ONE request set against a 3-replica fleet — the
router fronts replicas A and C while B stands by as A's configured
evacuation peer (docs/fault_tolerance.md "Preemption runbook"):

1. **baseline**: undisturbed run → reference outputs + tokens/s;
2. **sigterm drill**: replica A receives its preemption notice after
   `PREEMPT_AFTER` responses — it drains, EVACUATES its in-flight
   lanes to B (KV push + commit-journal cursors), and the blocked
   POSTs answer disagg-style redirects the router re-collects from B.
   Every request must answer 200, token-identical to rung 1, with at
   least one lane adopted and zero locally-regenerated retries;
3. **sigkill drill**: the same preemption, then B (the adopter) is
   hard-killed after `GRACE_S`. The router's collect fails, it mines
   the fleet's commit journals (`GET /partial/<id>` — A, still
   draining, serves the evacuated prefix) and re-places each request
   on C with `resume_tokens`, which prefills prompt+prefix and
   decodes only the remainder. Every request must answer 200,
   token-identical, with `resumed >= 1` and ZERO journal misses (no
   request regenerated from token 0); the row carries the recovered
   request overhead vs regenerate-from-zero
   (`1 - resumed_tokens / (resumed * new_tokens)` saved).

One BENCH-schema JSON line with ``"drill": "preempt"`` in the row:
benchdiff folds the drill into the comparison identity, so evacuation
rounds never diff against undisturbed fleet rounds.

`FLEET_BENCH_FAKE=1` (or `EVAC_BENCH_FAKE=1`) swaps the replicas for
in-process fakes (pure stdlib, no jax) that speak the full surface —
api + /stats draining + `PUT/GET /kv/<id>` + `GET /partial/<id>` —
with a deterministic token function, so the REAL router's redirect /
collect / journal-consult / resume path is exercised end to end in
seconds (`tests/test_evac_bench_smoke.py`). The adopter B decodes
slower than A/C (`FAKE_ADOPTER_FACTOR`) so the sigkill drill reliably
catches evacuated lanes mid-decode.

Env knobs (EVAC_BENCH_*, falling back to FLEET_BENCH_*): REQUESTS,
NEW_TOKENS, SLOTS, PROMPT_LEN, PREEMPT_AFTER, GRACE_S, FAKE,
FAKE_TOKEN_S, FAKE_ADOPTER_FACTOR, BASE_PORT, SEED, plus fleet.bench's
model-shape knobs for the real-replica path.
"""

from __future__ import annotations

import argparse
import http.server
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request
from typing import List, Optional

from fengshen_tpu.fleet.bench import (_buckets, _drive, _emit,
                                      _IntTokenizer, _make_router)


def _env(name: str, default: int) -> int:
    v = os.environ.get(f"EVAC_BENCH_{name}",
                       os.environ.get(f"FLEET_BENCH_{name}"))
    return default if v is None else int(v)


def _fenv(name: str, default: float) -> float:
    v = os.environ.get(f"EVAC_BENCH_{name}",
                       os.environ.get(f"FLEET_BENCH_{name}"))
    return default if v is None else float(v)


def _resume_totals(router) -> dict:
    """{outcome: count} over the router's fstpu_resume_total."""
    return {values[0]: int(child.value)
            for values, child in router._c_resume.children()}


# ---- fake evac replicas (the harness-smoke fast lane) ---------------

def _fake_tok(s: int, i: int, vocab: int = 97) -> int:
    """Position-deterministic token: matches fleet.bench._fake_result,
    so a resumed tail is token-identical to the undisturbed run by
    construction — exactly the greedy-decode property the real resume
    path guarantees."""
    return (s + i) % vocab


def start_fake_evac_replica(num_slots: int, token_s: float,
                            default_new_tokens: int,
                            host: str = "127.0.0.1", port: int = 0
                            ) -> dict:
    """In-process fake replica speaking the full evacuation surface:
    generate + /stats (with the draining flag) + adopt (`PUT /kv`) +
    collect (`GET /kv`) + commit journal (`GET /partial`). Returns a
    control dict: url/target/server/counters plus `drain(peer_urls)` —
    the preemption notice: flips draining, pushes every in-flight lane
    with >= 1 committed token to the first adopting peer (the rest
    finish locally, never an error)."""
    sem = threading.BoundedSemaphore(num_slots)
    lock = threading.Lock()
    active = [0]
    draining = [False]
    journal: dict = {}   # rid -> {"ids","n","tokens","state","result"}
    lanes: dict = {}     # rid -> {"cut": adopter url or None}
    adopted: dict = {}   # rid -> {"event", "result"}
    killed = [False]     # SIGKILL: sever in-flight responses too
    counters = {"adopted": 0, "evacuated": 0, "local_finish": 0,
                "resumed": 0}

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _send(self, code, payload):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _read(self):
            length = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(length) or b"{}")

        def do_GET(self):
            if self.path == "/healthz":
                if draining[0]:
                    self._send(503, {"ready": False,
                                     "reason": "draining"})
                else:
                    self._send(200, {"status": "ok", "ready": True})
            elif self.path == "/stats":
                with lock:
                    a = active[0]
                self._send(200, {"slots_active": min(a, num_slots),
                                 "queue_depth": max(a - num_slots, 0),
                                 "num_slots": num_slots,
                                 "draining": draining[0],
                                 "phase": "both"})
            elif self.path.startswith("/partial/"):
                rid = self.path[len("/partial/"):]
                with lock:
                    entry = journal.get(rid)
                    entry = None if entry is None else dict(
                        entry, tokens=list(entry["tokens"]))
                if entry is None:
                    self._send(404, {"error": "unknown"})
                    return
                out = {"request_id": rid, "state": entry["state"],
                       "generated_tokens": len(entry["tokens"]),
                       "tokens": entry["tokens"],
                       "max_new_tokens": entry["n"]}
                if entry["state"] == "finished":
                    out["result"] = entry["result"]
                    out["finish_reason"] = "length"
                    out["ttft_s"] = 0.0
                self._send(200, out)
            elif self.path.startswith("/kv/"):
                rid = self.path[len("/kv/"):]
                with lock:
                    entry = adopted.get(rid)
                if entry is None:
                    self._send(404, {"error": "unknown"})
                    return
                deadline = time.monotonic() + 30.0
                while not entry["event"].wait(timeout=0.02):
                    if killed[0]:
                        # a real SIGKILL severs the long-poll
                        # mid-flight; the router must see a reset,
                        # not a clean response
                        self.close_connection = True
                        try:
                            self.connection.shutdown(socket.SHUT_RDWR)
                        except OSError:
                            pass
                        return
                    if time.monotonic() >= deadline:
                        self._send(504, {"error": "still decoding"})
                        return
                with lock:
                    adopted.pop(rid, None)
                self._send(200, {"result": entry["result"],
                                 "request_id": rid, "ttft_s": 0.0,
                                 "finish_reason": "length"})
            else:
                self._send(404, {"error": "not found"})

        def do_POST(self):
            if not self.path.startswith("/api/"):
                self._send(404, {"error": "not found"})
                return
            req = self._read()
            if draining[0]:
                self._send(503, {"error": "replica draining",
                                 "reason": "draining"})
                return
            ids = [int(t) for t in req["input_text"].split()]
            n = int(req.get("max_new_tokens") or default_new_tokens)
            rid = str(req.get("request_id"))
            resume = [int(t) for t in (req.get("resume_tokens") or [])]
            committed = list(resume)
            lane = {"cut": None}
            s = sum(ids)
            with lock:
                active[0] += 1
                lanes[rid] = lane
                journal[rid] = {"ids": ids, "n": n,
                                "tokens": list(committed),
                                "state": "running", "result": None}
                if resume:
                    counters["resumed"] += 1
            try:
                target = None
                with sem:
                    for i in range(len(committed), n):
                        time.sleep(token_s)
                        with lock:
                            target = lane["cut"]
                            if target is not None:
                                break
                            committed.append(_fake_tok(s, i))
                            journal[rid]["tokens"] = list(committed)
                if target is not None:
                    self._send(200, {"disagg_redirect": True,
                                     "request_id": rid,
                                     "target": target,
                                     "evacuated": True})
                    return
                result = " ".join(str(t) for t in committed)
                with lock:
                    journal[rid].update(state="finished",
                                        result=result)
                    if draining[0]:
                        counters["local_finish"] += 1
                self._send(200, {"result": result, "request_id": rid,
                                 "ttft_s": 0.0,
                                 "finish_reason": "length"})
            finally:
                with lock:
                    active[0] -= 1
                    lanes.pop(rid, None)

        def do_PUT(self):
            if not self.path.startswith("/kv/"):
                self._send(404, {"error": "not found"})
                return
            rid = self.path[len("/kv/"):]
            payload = self._read()
            if draining[0]:
                self._send(409, {"adopted": False,
                                 "reason": "draining"})
                return
            ids = [int(t) for t in payload["ids"]]
            n = int(payload["n"])
            committed = [int(t) for t in payload["committed"]]
            entry = {"event": threading.Event(), "result": None}
            with lock:
                adopted[rid] = entry
                counters["adopted"] += 1
                # the adopter journals the lane too: a hard-killed
                # source leaves the prefix readable here
                journal[rid] = {"ids": ids, "n": n,
                                "tokens": list(committed),
                                "state": "running", "result": None}
            s = sum(ids)

            def run():
                with sem:
                    for i in range(len(committed), n):
                        time.sleep(token_s)
                        if killed[0]:
                            # SIGKILL: the adopted lane dies
                            # uncommitted — only the source's journal
                            # prefix survives
                            return
                        committed.append(_fake_tok(s, i))
                        with lock:
                            journal[rid]["tokens"] = list(committed)
                entry["result"] = " ".join(str(t) for t in committed)
                with lock:
                    journal[rid].update(state="finished",
                                        result=entry["result"])
                entry["event"].set()

            threading.Thread(target=run, daemon=True).start()
            self._send(200, {"adopted": True, "request_id": rid})

    server = http.server.ThreadingHTTPServer((host, port), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    url = "http://127.0.0.1:%d" % server.server_address[1]

    def drain(peer_urls: List[str]) -> None:
        draining[0] = True
        snapshot: list = []
        # a just-admitted lane has no committed token yet and cannot
        # be resumed, and a nearly-finished one wins the race against
        # its own cut; evacuate lanes with real work remaining and
        # give the decode loop a few ticks to surface one
        for _ in range(5):
            with lock:
                snapshot = [
                    (rid, dict(journal[rid],
                               tokens=list(journal[rid]["tokens"])))
                    for rid in list(lanes)
                    if 0 < len(journal.get(rid, {}).get("tokens", ()))
                    <= journal[rid]["n"] - 4]
            if snapshot:
                break
            time.sleep(2 * token_s)
        for rid, entry in snapshot:
            for peer in peer_urls:
                body = json.dumps(
                    {"request_id": rid, "ids": entry["ids"],
                     "n": entry["n"],
                     "committed": entry["tokens"]}).encode()
                req = urllib.request.Request(
                    peer.rstrip("/") + f"/kv/{rid}", data=body,
                    method="PUT",
                    headers={"Content-Type": "application/json"})
                try:
                    with urllib.request.urlopen(req, timeout=10.0) as r:
                        ok = bool(json.loads(r.read()).get("adopted"))
                except Exception:  # noqa: BLE001 — push failure =
                    ok = False     # try the next peer / local finish
                if ok:
                    with lock:
                        lane = lanes.get(rid)
                        if lane is not None:
                            lane["cut"] = peer
                        journal[rid].update(
                            state="evacuated",
                            tokens=list(entry["tokens"]))
                        counters["evacuated"] += 1
                    break

    def kill() -> None:
        """Fake SIGKILL: refuse new connects AND sever in-flight
        long-polls, so the router sees resets, never clean answers."""
        killed[0] = True
        server.shutdown()
        server.server_close()

    return {"url": url,
            "target": "127.0.0.1:%d" % server.server_address[1],
            "server": server, "counters": counters, "drain": drain,
            "kill": kill}


def _stop_fake(*ctls) -> None:
    for ctl in ctls:
        try:
            ctl["server"].shutdown()
            ctl["server"].server_close()
        except OSError:
            pass


# ---- real replica subprocess (`--replica --peers ...`) --------------

def replica_main(port: int, peers: List[str]) -> None:
    """Subprocess entry: the fleet bench's random-init llama replica
    with a `DisaggCoordinator` and the drain handler wired for live
    evacuation — SIGTERM makes it push its in-flight lanes to
    `peers` before the idle wait."""
    import jax
    import jax.numpy as jnp

    from fengshen_tpu.api.main import (PipelineConfig, ServerConfig,
                                       _start_warmup_thread,
                                       build_stdlib_server,
                                       create_continuous_engine,
                                       install_drain_handler)
    from fengshen_tpu.disagg.coordinator import DisaggCoordinator
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.pipelines.text_generation import Pipeline

    buckets = _buckets()
    new_tokens = _env("NEW_TOKENS", 16)
    config = LlamaConfig(
        vocab_size=_env("VOCAB", 4096),
        hidden_size=_env("HIDDEN", 1024),
        intermediate_size=_env("INTER", 2816),
        num_hidden_layers=_env("LAYERS", 4),
        num_attention_heads=_env("HEADS", 8),
        max_position_embeddings=buckets[-1] + new_tokens,
        dtype="float32")
    model = LlamaForCausalLM(config)
    params = jax.jit(lambda r: model.init(
        r, jnp.zeros((1, 8), jnp.int32))["params"])(
        jax.random.PRNGKey(_env("SEED", 0)))
    pipe = Pipeline(module=model, params=params,
                    tokenizer=_IntTokenizer(),
                    max_new_tokens=new_tokens, eos_token_id=None,
                    pad_token_id=0)
    engine = create_continuous_engine(
        pipe, {"num_slots": _env("SLOTS", 2), "buckets": buckets,
               "max_new_tokens": new_tokens, "max_queue": 512})
    disagg = DisaggCoordinator(engine, pipe)
    server_cfg = ServerConfig(host="127.0.0.1", port=port,
                              engine="continuous",
                              peers=tuple(peers))
    pipeline_cfg = PipelineConfig(task="text_generation")
    ready = _start_warmup_thread(server_cfg, pipeline_cfg, pipe, engine)
    draining = threading.Event()
    server = build_stdlib_server(server_cfg, pipeline_cfg,
                                 pipeline=pipe, engine=engine,
                                 ready=ready, draining=draining,
                                 disagg=disagg)
    install_drain_handler(server, draining, engine=engine,
                          disagg=disagg, peers=server_cfg.peers)
    print(f"[evac-bench] replica on 127.0.0.1:{port} "
          f"(peers={list(peers)})", flush=True)
    server.serve_forever()


def _spawn_fleet(base_port: int) -> tuple:
    """A, B, C subprocess replicas; A evacuates to B on drain."""
    ports = [base_port, base_port + 1, base_port + 2]
    peers = [f"http://127.0.0.1:{ports[1]}", "", ""]
    procs = []
    for port, peer in zip(ports, peers):
        cmd = [sys.executable, "-m", "fengshen_tpu.fleet.evac_bench",
               "--replica", "--port", str(port)]
        if peer:
            cmd += ["--peers", peer]
        procs.append(subprocess.Popen(cmd))
    targets = [f"127.0.0.1:{p}" for p in ports]
    return targets, procs


def _wait_healthy(target: str, timeout_s: float = 180.0) -> None:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(
                    f"http://{target}/healthz", timeout=2.0) as r:
                if r.status == 200:
                    return
        except Exception:  # noqa: BLE001 — still warming
            pass
        time.sleep(0.2)
    raise RuntimeError(f"replica {target} not healthy in {timeout_s}s")


def _reap(procs) -> None:
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    for p in procs:
        try:
            p.wait(timeout=15)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()


# ---- the driver -----------------------------------------------------

def main(argv=None) -> None:
    parser = argparse.ArgumentParser(
        prog="python -m fengshen_tpu.fleet.evac_bench")
    parser.add_argument("--replica", action="store_true",
                        help="run as a bench replica subprocess")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--peers", type=str, default="")
    args = parser.parse_args(argv)
    if args.replica:
        replica_main(args.port,
                     [p for p in args.peers.split(",") if p])
        return

    slots = _env("SLOTS", 2)
    new_tokens = _env("NEW_TOKENS", 16)
    prompt_len = _env("PROMPT_LEN", 8)
    n_req = max(_env("REQUESTS", 24), 2)
    preempt_after = _env("PREEMPT_AFTER", max(n_req // 4, 1))
    grace_s = _fenv("GRACE_S", 0.05)
    fake = _env("FAKE", 0) == 1
    # slow enough that in-flight lanes are reliably mid-decode when
    # the preemption notice lands (the whole point of the drill)
    token_s = _fenv("FAKE_TOKEN_S", 0.02)
    adopter_factor = _fenv("FAKE_ADOPTER_FACTOR", 5.0)
    width = max(4 * slots, 8)

    import random as _random
    rng = _random.Random(_env("SEED", 0))
    prompts = [" ".join(str(rng.randint(3, 95))
                        for _ in range(prompt_len))
               for _ in range(n_req)]

    def fresh_fleet(rung):
        """(router_targets, drain_a, kill_b, counters, cleanup)."""
        if fake:
            # sigterm rung: B decodes adopted lanes slowly but
            # finishes them (collect succeeds). sigkill rung: B is
            # effectively frozen, so every evacuated lane is still
            # mid-decode at the kill and MUST come back through
            # resume-from-token-k — the drill is deterministic
            adopter_s = (30.0 if rung == "sigkill"
                         else token_s * adopter_factor)
            a = start_fake_evac_replica(slots, token_s, new_tokens)
            b = start_fake_evac_replica(slots, adopter_s, new_tokens)
            c = start_fake_evac_replica(slots, token_s, new_tokens)

            return ([a["target"], c["target"]],
                    lambda: a["drain"]([b["url"]]), b["kill"],
                    {"adopted": b["counters"],
                     "source": a["counters"]},
                    lambda: _stop_fake(a, b, c))
        targets, procs = _spawn_fleet(_env("BASE_PORT", 8470))
        for t in targets:
            _wait_healthy(t)
        return ([targets[0], targets[2]],
                lambda: procs[0].send_signal(signal.SIGTERM),
                lambda: procs[1].kill(), None, lambda: _reap(procs))

    sections = {}
    results = {}
    for rung in ("baseline", "sigterm", "sigkill"):
        targets, drain_a, kill_b, counters, cleanup = fresh_fleet(rung)
        try:
            # slow poll on the drill rungs: the router must learn of
            # the drain through 503-draining answers, deterministically
            router = _make_router(
                targets,
                poll_interval_s=0.2 if rung == "baseline" else 60.0)
            if rung == "baseline":
                trigger = None
            elif rung == "sigterm":
                trigger = drain_a
            else:
                def trigger():
                    drain_a()

                    def later():
                        time.sleep(grace_s)
                        kill_b()
                    threading.Thread(target=later,
                                     daemon=True).start()
            run = _drive(router, prompts, new_tokens, width=width,
                         kill=None if trigger is None
                         else (preempt_after, trigger))
            resume = _resume_totals(router)
            resume_tokens = int(router._c_resume_tokens.value())
            router.stop()
            results[rung] = run
            sections[rung] = {
                "failed": len(run["failed"]),
                "completed": sum(1 for r in run["results"]
                                 if r is not None),
                "tokens_per_sec": round(run["tokens_per_sec"], 1),
                "resume": resume,
                "resume_tokens": resume_tokens,
            }
            if counters is not None:
                sections[rung]["adopted"] = \
                    counters["adopted"]["adopted"]
                sections[rung]["evacuated"] = \
                    counters["source"]["evacuated"]
                sections[rung]["local_finish"] = \
                    counters["source"]["local_finish"]
        finally:
            cleanup()

    base, term, hard = (results["baseline"], results["sigterm"],
                        results["sigkill"])
    hard_resume = sections["sigkill"]["resume"]
    resumed = int(hard_resume.get("resumed", 0))
    resumed_tokens = int(sections["sigkill"]["resume_tokens"])
    if fake:
        backend = "fake"
    else:
        import jax
        backend = jax.default_backend()
    # recovered-request overhead vs regenerate-from-zero: the share of
    # a recovered request's tokens that had to be decoded AGAIN — 1.0
    # would mean the journal saved nothing, < 1.0 is the win
    overhead = (round(1.0 - resumed_tokens / (resumed * new_tokens), 3)
                if resumed else None)
    tps_b = base["tokens_per_sec"]
    tps_t = term["tokens_per_sec"]
    _emit({
        "metric": "evac_tokens_per_sec",
        "value": round(tps_t, 1),
        "unit": "tokens/s",
        "vs_baseline": round(tps_t / tps_b, 3) if tps_b > 0 else 0.0,
        "mode": "evac",
        # the comparison identity: a preemption drill is never diffed
        # against an undisturbed fleet round
        "drill": "preempt",
        "replicas": 3,
        "num_slots": slots,
        "requests": n_req,
        "new_tokens": new_tokens,
        "preempt_after": preempt_after,
        "tokens_per_sec_baseline": round(tps_b, 1),
        "failed": (len(base["failed"]) + len(term["failed"])
                   + len(hard["failed"])),
        "token_identical_sigterm": term["results"] == base["results"],
        "token_identical_sigkill": hard["results"] == base["results"],
        "resumed": resumed,
        "zero_regenerated": int(hard_resume.get("miss", 0)) == 0,
        "recovered_overhead_vs_regenerate": overhead,
        "sigterm": sections["sigterm"],
        "sigkill": sections["sigkill"],
        "fake": fake,
        "backend": backend,
    })


if __name__ == "__main__":
    main()
