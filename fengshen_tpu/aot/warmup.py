"""Warmup manifests: record every shape a process compiles, replay
them all at the next startup.

The executable cache (`fengshen_tpu.aot.cache`) removes the XLA cost of
a compile the process has ALREADY asked for — but a freshly restarted
server only asks as traffic arrives. The manifest closes that gap:

- **record** (`record=True` on `WarmupManifest` / the AOT config
  block): every (fn name, argument avals, mesh axes) that reaches
  `CachedFunction` for the first time is appended to a JSON file,
  deduplicated, committed by atomic rename;
- **replay** (`replay()`): at startup, every manifest entry whose fn
  name the caller registers is pre-compiled — or, with a warm cache,
  deserialized — on a thread pool (XLA compilation releases the GIL,
  so buckets compile in parallel), BEFORE the first request arrives.

The serving engine replays `serving/prefill` (every bucket),
`serving/assign`, and `serving/decode` inside `warmup()`; the `python
-m fengshen_tpu.aot warm` CLI replays in CI/deploy images so the
shipped cache is pre-baked (docs/aot_cache.md).

Avals are stored structurally (nested dict/list/tuple tags with
shape+dtype leaves), so a manifest is valid across processes but NOT
across model-shape changes — a stale entry simply compiles an
executable nobody calls, it cannot corrupt anything. A corrupt manifest
file logs and starts empty (same never-break-a-job stance as the
cache).
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Mapping, Optional

import numpy as np

from fengshen_tpu.aot.cache import (DEFAULT_MAX_BYTES, CachedFunction,
                                    ExecutableCache)

MANIFEST_VERSION = 1


# ---- aval (de)serialization ---------------------------------------------

def encode_avals(obj: Any) -> Any:
    """Positional args → JSON-safe nested structure. Leaves keep only
    shape+dtype (exactly what `.lower()` needs); containers keep their
    type so the pytree structure round-trips.

    Raises ValueError on anything it cannot represent faithfully —
    custom pytree nodes like the trainer's TrainState would otherwise
    collapse to a 0-d object leaf, recording manifest entries that can
    never replay (the caller skips such entries; the executable cache
    itself is unaffected)."""
    if obj is None:
        return {"t": "none"}
    if isinstance(obj, Mapping):
        return {"t": "dict",
                "v": {str(k): encode_avals(v)
                      for k, v in sorted(obj.items())}}
    if isinstance(obj, (list, tuple)):
        return {"t": "list" if isinstance(obj, list) else "tuple",
                "v": [encode_avals(v) for v in obj]}
    dtype = getattr(obj, "dtype", None)
    if dtype is None:
        dtype = np.asarray(obj).dtype
    if np.dtype(dtype) == object:
        raise ValueError(
            f"cannot encode avals for {type(obj).__name__} — only "
            "arrays and dict/list/tuple containers round-trip through "
            "a manifest")
    return {"t": "aval", "shape": [int(d) for d in np.shape(obj)],
            "dtype": str(np.dtype(dtype))}


def decode_avals(enc: Any) -> Any:
    import jax
    t = enc["t"]
    if t == "none":
        return None
    if t == "dict":
        return {k: decode_avals(v) for k, v in enc["v"].items()}
    if t == "list":
        return [decode_avals(v) for v in enc["v"]]
    if t == "tuple":
        return tuple(decode_avals(v) for v in enc["v"])
    if t == "aval":
        return jax.ShapeDtypeStruct(tuple(enc["shape"]),
                                    np.dtype(enc["dtype"]))
    raise ValueError(f"unknown aval tag {t!r}")


def _encode_mesh(mesh: Any) -> Optional[list]:
    if mesh is None:
        return None
    return sorted([str(k), int(v)] for k, v in dict(mesh.shape).items())


# ---- the manifest --------------------------------------------------------

class WarmupManifest:
    """JSON file of every (name, avals, mesh) worth pre-compiling."""

    def __init__(self, path: str, record: bool = False,
                 log: Optional[Callable[[dict], None]] = None):
        self.path = path
        self.record_mode = record
        self._log = log or (lambda entry: None)
        self._lock = threading.Lock()
        self._entries: Dict[str, dict] = {}   # dedup key -> entry
        if os.path.exists(path):
            try:
                with open(path) as f:
                    raw = json.load(f)
                if raw.get("version") != MANIFEST_VERSION:
                    raise ValueError(
                        f"manifest version {raw.get('version')!r}")
                for entry in raw.get("entries", []):
                    self._entries[self._dedup_key(entry)] = entry
            except Exception as e:  # noqa: BLE001 — a corrupt manifest
                # starts empty (and gets rewritten on the next record),
                # it never blocks startup
                self._log({"event": "aot_manifest_error", "path": path,
                           "error": str(e)[:500]})
                self._entries = {}

    @staticmethod
    def _dedup_key(entry: dict) -> str:
        return json.dumps([entry.get("name"), entry.get("avals"),
                           entry.get("mesh")], sort_keys=True)

    def __len__(self) -> int:
        return len(self._entries)

    def entries(self, name: Optional[str] = None) -> List[dict]:
        out = [e for e in self._entries.values()
               if name is None or e.get("name") == name]
        return sorted(out, key=self._dedup_key)

    def record(self, name: str, args: tuple, mesh: Any = None,
               key: Optional[str] = None,
               fingerprint: Optional[str] = None) -> bool:
        """Append one compile site (dedup'd by name+avals+mesh; a
        re-record with a new cache key/fingerprint — code or config
        drift — overwrites newest-wins); True when the manifest
        changed. No-op unless opened with record=True.

        `key`/`fingerprint` enable TRUSTED replay (docs/aot_cache.md):
        the cache key the compile landed under, and the code+env+config
        fingerprint under which that key may be adopted without
        re-lowering."""
        if not self.record_mode:
            return False
        try:
            avals = encode_avals(tuple(args))
        except (ValueError, TypeError) as e:
            # un-roundtrippable args (custom pytree nodes — the
            # trainer's TrainState): the executable cache still works
            # by content address, only manifest replay is unavailable
            self._log({"event": "aot_manifest_skip", "fn": name,
                       "reason": str(e)[:200]})
            return False
        entry = {"name": name, "avals": avals,
                 "mesh": _encode_mesh(mesh), "key": key,
                 "fingerprint": fingerprint}
        dk = self._dedup_key(entry)
        with self._lock:
            if self._entries.get(dk) == entry:
                return False
            self._entries[dk] = entry
            self._save_locked()
        return True

    def _save_locked(self) -> None:
        doc = {"version": MANIFEST_VERSION,
               "entries": self.entries()}
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".manifest-tmp-")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
            os.replace(tmp, self.path)
        except OSError as e:
            self._log({"event": "aot_manifest_error", "path": self.path,
                       "error": str(e)[:500]})
            try:
                os.remove(tmp)
            except OSError:
                pass

    def replay(self, functions: Dict[str, CachedFunction],
               workers: int = 4, trusted: bool = True) -> dict:
        """Pre-compile/deserialize every entry whose name is in
        `functions`, thread-parallel. Returns a summary dict; per-entry
        failures are logged and skipped (a stale manifest must not
        block startup).

        With `trusted` (the near-zero-cold-start path), an entry whose
        recorded fingerprint matches the function's current
        code+env+config fingerprint is ADOPTED straight from the cache
        by its recorded key — no tracing, no lowering; everything else
        (fingerprint drift, missing blob, trusted=False) goes through
        `warm()`: lower, hash, load-or-compile."""
        todo = [e for e in self.entries() if e["name"] in functions]
        skipped = len(self._entries) - len(todo)
        t0 = time.perf_counter()
        failed = 0
        adopted = 0

        def _one(entry: dict) -> Optional[bool]:
            try:
                fn = functions[entry["name"]]
                avals = decode_avals(entry["avals"])
                if trusted and entry.get("key") and \
                        entry.get("fingerprint") == \
                        fn.trusted_fingerprint() and \
                        fn.adopt(avals, entry["key"]):
                    return None    # adopted: no lower, no compile
                fn.warm(*avals)
                return True
            except Exception as e:  # noqa: BLE001 — stale/foreign
                # entries are logged and skipped, never fatal
                self._log({"event": "aot_manifest_replay_error",
                           "fn": entry.get("name"),
                           "error": str(e)[:500]})
                return False

        if todo:
            with ThreadPoolExecutor(
                    max_workers=max(1, int(workers))) as pool:
                results = list(pool.map(_one, todo))
            failed = sum(1 for r in results if r is False)
            adopted = sum(1 for r in results if r is None)
        summary = {"replayed": len(todo) - failed, "failed": failed,
                   "adopted": adopted, "skipped": skipped,
                   "seconds": round(time.perf_counter() - t0, 3)}
        self._log({"event": "aot_manifest_replay", **summary})
        return summary


# ---- config + bundle -----------------------------------------------------

@dataclasses.dataclass
class AotConfig:
    """The `AOT` server-config block / trainer flags, as a dataclass.

    `cache_dir` is the only required field. `manifest` defaults to
    `<cache_dir>/warmup_manifest.json`; set it to "" to disable the
    manifest entirely. Recording is on by default (appending a line of
    JSON per new shape is free next to an XLA compile).
    `trusted_replay` allows replay to adopt executables by recorded key
    when the code+env+config fingerprint matches, skipping tracing
    entirely — set False to force the verified lower-and-hash path on
    every entry (docs/aot_cache.md)."""

    cache_dir: str
    manifest: Optional[str] = None
    record: bool = True
    replay: bool = True
    trusted_replay: bool = True
    max_bytes: int = DEFAULT_MAX_BYTES
    workers: int = 4

    def manifest_path(self) -> Optional[str]:
        if self.manifest == "":
            return None
        if self.manifest is None:
            return os.path.join(self.cache_dir, "warmup_manifest.json")
        return self.manifest


class AotSetup:
    """One process's AOT wiring: the executable cache + the manifest,
    with `wrap()` handing out `CachedFunction`s that record into both.
    The serving engine takes one of these via its `aot=` argument; the
    trainer builds one from `--aot_cache_dir`."""

    def __init__(self, config: AotConfig, mesh: Any = None,
                 registry: Any = None,
                 log: Optional[Callable[[dict], None]] = None):
        self.config = config
        self.mesh = mesh
        self._registry = registry
        self._log = log or (lambda entry: None)
        self.cache = ExecutableCache(
            config.cache_dir, max_bytes=config.max_bytes,
            registry=registry, log=self._log)
        path = config.manifest_path()
        self.manifest = WarmupManifest(
            path, record=config.record, log=self._log) \
            if path is not None else None

    def wrap(self, fn: Any, name: str, donate_argnums=(),
             fingerprint_extra: str = "",
             key_extra: str = "") -> CachedFunction:
        """`fingerprint_extra` must capture every static value the
        caller bakes into the traced program that avals don't (model
        config, engine config reprs) — it gates trusted replay.
        `key_extra` additionally enters the cache key itself (the
        trainer's offload placement, docs/offload.md): entries under
        different `key_extra` values can never cross-hit, even within
        one process."""
        return CachedFunction(
            fn, name, cache=self.cache, donate_argnums=donate_argnums,
            mesh=self.mesh, manifest=self.manifest,
            fingerprint_extra=fingerprint_extra, key_extra=key_extra,
            registry=self._registry, log=self._log)

    def replay(self, functions: Dict[str, CachedFunction]
               ) -> Optional[dict]:
        """Manifest replay over the caller's functions (None when no
        manifest exists or replay is disabled)."""
        if self.manifest is None or not self.config.replay or \
                len(self.manifest) == 0:
            return None
        return self.manifest.replay(functions,
                                    workers=self.config.workers,
                                    trusted=self.config.trusted_replay)
