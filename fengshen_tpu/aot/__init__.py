"""AOT compile-cache subsystem (docs/aot_cache.md): persistent
executable cache + warmup manifests for near-zero cold start.

`cached_compile` / `CachedFunction` split jit into lower (cheap, keys
the cache) and compile (expensive, skipped when a serialized executable
for the same environment + StableHLO already exists on disk);
`WarmupManifest` records every shape a process compiles and replays
them thread-parallel at the next startup. Wired into the serving
engine (`ContinuousBatchingEngine(aot=...)`), the trainer
(`--aot_cache_dir`), the api server (the `AOT` config block), and the
`python -m fengshen_tpu.aot {warm,ls,purge}` CLI.
"""

from fengshen_tpu.aot.cache import (BLOB_SUFFIX, BLOB_VERSION,
                                    DEFAULT_MAX_BYTES, ERRORS_METRIC,
                                    HITS_METRIC, MISSES_METRIC,
                                    CachedFunction, CacheEntry,
                                    ExecutableCache, cache_key,
                                    cached_compile,
                                    package_source_digest,
                                    trusted_fingerprint)
from fengshen_tpu.aot.warmup import (AotConfig, AotSetup,
                                     WarmupManifest, decode_avals,
                                     encode_avals)

__all__ = [
    "AotConfig", "AotSetup", "BLOB_SUFFIX", "BLOB_VERSION",
    "CacheEntry", "CachedFunction", "DEFAULT_MAX_BYTES",
    "ERRORS_METRIC", "ExecutableCache", "HITS_METRIC", "MISSES_METRIC",
    "WarmupManifest", "cache_key", "cached_compile", "decode_avals",
    "encode_avals", "package_source_digest", "trusted_fingerprint",
]
