"""AOT cache CLI: pre-bake, inspect, and prune executable caches.

    python -m fengshen_tpu.aot warm  --config server.json
    python -m fengshen_tpu.aot ls    --cache-dir /var/cache/fstpu [--json]
    python -m fengshen_tpu.aot purge --cache-dir /var/cache/fstpu \
        [--all | --older-than SECONDS | --max-bytes N]

`warm` takes the SAME JSON config file the api server runs from
(PIPELINE + AOT blocks, docs/aot_cache.md): it builds the pipeline and
the continuous engine exactly as the server would, runs the engine
warmup (manifest replay + every prefill bucket + decode), and exits —
leaving the cache dir fully populated. CI/deploy images run it once at
build time so every replica boots warm; the warmup must be executed on
the SAME accelerator topology the replica will see (the cache key pins
backend/device kind/count).

Exit codes: 0 ok; 2 usage error (bad config, missing AOT block).
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}GiB"


def cmd_ls(args) -> int:
    from fengshen_tpu.aot import ExecutableCache
    cache = ExecutableCache(args.cache_dir)
    entries = cache.entries()
    now = time.time()
    if args.json:
        print(json.dumps({
            "cache_dir": args.cache_dir,
            "total_bytes": sum(e.size_bytes for e in entries),
            "entries": [{"name": e.name, "key": e.key,
                         "bytes": e.size_bytes,
                         "idle_s": round(now - e.mtime, 1)}
                        for e in entries]}, indent=1, sort_keys=True))
        return 0
    if not entries:
        print(f"{args.cache_dir}: empty")
        return 0
    for e in entries:
        print(f"{e.name:<24} {e.key[:16]}  "
              f"{_fmt_bytes(e.size_bytes):>10}  "
              f"idle {now - e.mtime:8.1f}s")
    print(f"total: {len(entries)} executables, "
          f"{_fmt_bytes(sum(e.size_bytes for e in entries))}")
    return 0


def cmd_purge(args) -> int:
    from fengshen_tpu.aot import ExecutableCache
    if not (args.all or args.older_than is not None
            or args.max_bytes is not None):
        print("purge: pass --all, --older-than SECONDS, or "
              "--max-bytes N", file=sys.stderr)
        return 2
    cache = ExecutableCache(args.cache_dir)
    removed = cache.purge(max_bytes=args.max_bytes,
                          older_than_s=args.older_than,
                          drop_all=args.all)
    print(f"purged {len(removed)} executables "
          f"({_fmt_bytes(sum(e.size_bytes for e in removed))}); "
          f"{_fmt_bytes(cache.total_bytes())} remain")
    return 0


def cmd_warm(args) -> int:
    from fengshen_tpu.api.main import (create_continuous_engine,
                                       load_config)
    from fengshen_tpu.observability import record_build_info
    try:
        server_cfg, pipeline_cfg = load_config(args.config)
    except (OSError, ValueError, KeyError, TypeError) as e:
        print(f"warm: cannot load config {args.config!r}: {e}",
              file=sys.stderr)
        return 2
    aot_args = dict(server_cfg.aot_args)
    if args.cache_dir:
        aot_args["cache_dir"] = args.cache_dir
    if not aot_args.get("cache_dir"):
        print("warm: the config has no AOT block (and no --cache-dir "
              "override) — nothing to pre-bake", file=sys.stderr)
        return 2
    record_build_info()
    from fengshen_tpu.api.main import _resolve_pipeline
    pipeline = _resolve_pipeline(pipeline_cfg)
    engine = create_continuous_engine(
        pipeline, server_cfg.engine_args, aot_args=aot_args,
        log=lambda entry: print(json.dumps(entry), flush=True))
    dt = engine.warmup()
    cache = engine._aot.cache
    print(f"warmed {pipeline_cfg.task} in {dt:.1f}s — cache "
          f"{aot_args['cache_dir']}: {len(cache.entries())} "
          f"executables, {_fmt_bytes(cache.total_bytes())}")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m fengshen_tpu.aot",
        description="AOT executable cache tools (docs/aot_cache.md)")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_warm = sub.add_parser(
        "warm", help="pre-bake a cache from a server config (CI/deploy)")
    p_warm.add_argument("--config", required=True, type=str,
                        help="api server JSON config (PIPELINE + AOT)")
    p_warm.add_argument("--cache-dir", default=None, type=str,
                        help="override the AOT block's cache_dir")
    p_warm.set_defaults(fn=cmd_warm)

    p_ls = sub.add_parser("ls", help="list cached executables")
    p_ls.add_argument("--cache-dir", required=True, type=str)
    p_ls.add_argument("--json", action="store_true")
    p_ls.set_defaults(fn=cmd_ls)

    p_purge = sub.add_parser("purge", help="evict cached executables")
    p_purge.add_argument("--cache-dir", required=True, type=str)
    p_purge.add_argument("--all", action="store_true",
                         help="drop every entry")
    p_purge.add_argument("--older-than", default=None, type=float,
                         metavar="SECONDS",
                         help="drop entries idle longer than this")
    p_purge.add_argument("--max-bytes", default=None, type=int,
                         help="drop least-recently-used entries until "
                              "the dir fits")
    p_purge.set_defaults(fn=cmd_purge)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
