"""AOT cold-start microbench: cold-process vs warm-process engine
warmup (`make aot-bench`).

Two CHILD processes run the identical startup sequence — build a
llama-shaped model, build the continuous-batching engine with an AOT
cache attached, run `engine.warmup()` (manifest replay + every prefill
bucket + the jitted decode), then greedy-generate a fixed prompt:

- the COLD child starts against an empty cache dir and pays full XLA
  compilation (populating the cache + warmup manifest as it goes);
- the WARM child starts against the now-populated dir and
  deserializes.

Separate processes, not two engines in one process: jax's in-memory
jit caches would otherwise hand the second engine its executables for
free and measure nothing. The parent emits ONE JSON line in the BENCH
schema ({"metric", "value", "unit", "vs_baseline"} — value =
cold/warm warmup speedup) with `aot_cold_s`, `aot_warm_s`, and
`token_identical` (the warm child's greedy tokens must equal the cold
child's: the acceptance bar couples the speedup to decode parity).

    make aot-bench
    AOT_BENCH_LAYERS=8 python -m fengshen_tpu.aot.bench

Env knobs (AOT_BENCH_*): VOCAB, HIDDEN, INTER, LAYERS, HEADS, SLOTS,
BUCKETS (comma list), NEW_TOKENS, SEED, WORKERS.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time


def _env(name: str, default: int) -> int:
    return int(os.environ.get(f"AOT_BENCH_{name}", default))


def _child(cache_dir: str) -> None:
    """One measured process startup; prints a single JSON line."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fengshen_tpu.aot import AotConfig, AotSetup
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.serving import (ContinuousBatchingEngine,
                                      EngineConfig)

    # default shape: deep enough that XLA compile dominates the cold
    # start (cold cost grows with layer count and bucket count; a warm
    # start adopts executables by manifest key and pays neither tracing
    # nor compile, so it stays flat — the same asymmetry real pods see,
    # where compile is minutes and deserialize is milliseconds)
    buckets = tuple(int(b) for b in os.environ.get(
        "AOT_BENCH_BUCKETS", "32,64,128").split(","))
    new_tokens = _env("NEW_TOKENS", 8)
    config = LlamaConfig(
        vocab_size=_env("VOCAB", 2048),
        hidden_size=_env("HIDDEN", 512),
        intermediate_size=_env("INTER", 1024),
        num_hidden_layers=_env("LAYERS", 8),
        num_attention_heads=_env("HEADS", 8),
        max_position_embeddings=buckets[-1] + new_tokens,
        dtype="float32")
    model = LlamaForCausalLM(config)
    params = jax.jit(lambda r: model.init(
        r, jnp.zeros((1, 8), jnp.int32))["params"])(
        jax.random.PRNGKey(_env("SEED", 0)))

    aot = AotSetup(AotConfig(cache_dir=cache_dir,
                             workers=_env("WORKERS", 4)))
    engine = ContinuousBatchingEngine(
        model, params,
        EngineConfig(num_slots=_env("SLOTS", 4), buckets=buckets,
                     max_new_tokens=new_tokens, max_queue=8,
                     eos_token_id=None, pad_token_id=0),
        aot=aot)
    warmup_s = engine.warmup()
    # greedy decode through the (possibly deserialized) executables —
    # the parent pins cold-vs-warm token identity
    rng = np.random.RandomState(_env("SEED", 0))
    prompt = rng.randint(3, config.vocab_size - 1,
                         max(buckets[0] - 3, 1)).astype(np.int32)
    tokens = engine.generate_all([prompt])[0]
    print(json.dumps({"warmup_s": round(warmup_s, 3),
                      "tokens": [int(t) for t in tokens],
                      "backend": jax.default_backend(),
                      "cache_files": sum(
                          1 for f in os.listdir(cache_dir)
                          if f.endswith(".aotx"))}), flush=True)


def _run_child(cache_dir: str) -> dict:
    proc = subprocess.run(
        [sys.executable, "-m", "fengshen_tpu.aot.bench", "--child",
         cache_dir],
        capture_output=True, text=True, timeout=1800)
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    if proc.returncode != 0 or not lines:
        raise RuntimeError(
            f"aot bench child failed (rc={proc.returncode}):\n"
            f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}")
    return json.loads(lines[-1])


def main() -> None:
    # parent stays jax-free: the children own the measured startups
    from fengshen_tpu.observability import JsonlSink

    with tempfile.TemporaryDirectory(prefix="fstpu-aot-bench-") as d:
        t0 = time.perf_counter()
        cold = _run_child(d)
        warm = _run_child(d)
        total_s = time.perf_counter() - t0
    speedup = cold["warmup_s"] / max(warm["warmup_s"], 1e-9)
    row = {
        "metric": "aot_warm_warmup_speedup",
        "value": round(speedup, 2),
        "unit": "x",
        "vs_baseline": round(speedup, 2),
        "aot_cold_s": cold["warmup_s"],
        "aot_warm_s": warm["warmup_s"],
        "token_identical": cold["tokens"] == warm["tokens"],
        "cache_files": warm["cache_files"],
        "bench_wall_s": round(total_s, 1),
        "backend": warm["backend"],
    }
    if os.environ.get("BENCH_DEGRADED", "0") == "1":
        row["degraded"] = True
    JsonlSink(stream=sys.stdout, only_process_zero=False)(row)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        _child(sys.argv[2])
    else:
        main()
