"""Persistent AOT executable cache: lower once anywhere, compile once
EVER (per environment).

XLA compilation of the train step and of every serving prefill bucket
costs seconds-to-minutes on real pods, and a restart/redeploy/rewind/
autoscale event re-pays all of it. `cached_compile` splits jit into its
two halves — lower (cheap tracing, always runs, and produces the cache
key) and compile (the expensive XLA invocation, skipped on a hit) — and
persists the compiled executable with
`jax.experimental.serialize_executable`.

Cache key anatomy (docs/aot_cache.md): sha256 over

- the jax version,
- backend platform + device kind + device count,
- mesh axis names/sizes (when a mesh is in play — the same program
  lowered under a different mesh is a different executable),
- compiler options,
- the sha256 of the canonical StableHLO text of the lowered module
  (which already embeds input shapes/dtypes/shardings and donation).

Failure semantics — THE invariant: the cache can never break a job.
Every load failure (truncated blob, unpicklable payload, jax version
drift inside the blob header, deserialize error) logs an event, bumps
`fstpu_aot_cache_errors_total{fn}`, removes the bad file, and falls
back to a fresh compile whose result overwrites the entry newest-wins
via atomic `os.replace`. Stores are also best-effort: a full disk or
read-only cache dir degrades to compile-every-time, not a crash.

Host-side only: everything here (file I/O, pickling, metric bumps) runs
strictly OUTSIDE traced code — `cached_compile` is called between jit
boundaries, never inside one (the `metrics-in-traced-code` /
`blocking-transfer` fslint rules gate this; see
tests/analysis_fixtures/aot_cache_clean.py).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import pickle
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax

from fengshen_tpu.observability import MetricsRegistry, get_registry, span

#: bump when the on-disk blob layout changes — older blobs become load
#: errors (counted + recompiled), never crashes
BLOB_VERSION = 1

#: file suffix for cache entries ("<name>__<key>.aotx")
BLOB_SUFFIX = ".aotx"

#: default LRU size cap (bytes); generous because blobs are per-shape
DEFAULT_MAX_BYTES = 4 << 30

HITS_METRIC = "fstpu_aot_cache_hits_total"
MISSES_METRIC = "fstpu_aot_cache_misses_total"
ERRORS_METRIC = "fstpu_aot_cache_errors_total"

_METRIC_HELP = {
    HITS_METRIC: "AOT cache loads served from a deserialized executable",
    MISSES_METRIC: "AOT cache lookups that fell through to XLA compile",
    ERRORS_METRIC: "AOT cache load/store failures (fell back to compile)",
}


def _counter(name: str, registry: Optional[MetricsRegistry] = None):
    reg = registry if registry is not None else get_registry()
    return reg.counter(name, _METRIC_HELP[name], labelnames=("fn",))


def _sanitize(name: str) -> str:
    """Function names are span-style ("serving/prefill") — keep them
    readable on disk without path separators."""
    return "".join(c if c.isalnum() or c in "._-" else "-" for c in name)


def _mesh_ident(mesh: Any) -> Optional[list]:
    if mesh is None:
        return None
    return sorted((str(k), int(v)) for k, v in dict(mesh.shape).items())


def cache_key(name: str, lowered: Any, mesh: Any = None,
              compiler_options: Optional[dict] = None,
              extra: str = "") -> str:
    """The content address of one compiled executable (see module
    docstring for the anatomy). `lowered` is a `jax.stages.Lowered`.

    `extra` carries static context that changes the program's runtime
    choreography without necessarily changing its StableHLO — the
    trainer passes the resolved offload placement
    (`OffloadPolicy.fingerprint()`, docs/offload.md) so two placements
    can never share an entry. Empty `extra` keeps the pre-existing key
    derivation (no silent cache invalidation for everyone else)."""
    devices = jax.devices()
    ident = {
        "name": name,
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "device_kind": devices[0].device_kind,
        "device_count": len(devices),
        "mesh": _mesh_ident(mesh),
        "compiler_options": sorted(
            (str(k), str(v))
            for k, v in (compiler_options or {}).items()),
        "stablehlo_sha256": hashlib.sha256(
            lowered.as_text().encode()).hexdigest(),
    }
    if extra:
        ident["extra"] = extra
    return hashlib.sha256(
        json.dumps(ident, sort_keys=True).encode()).hexdigest()


_SOURCE_DIGEST: Optional[str] = None


def package_source_digest() -> str:
    """sha256 over every .py file of the installed fengshen_tpu package
    (path + content, sorted walk) — the code half of the trusted-replay
    fingerprint. Computed once per process (~a few MiB of reads)."""
    global _SOURCE_DIGEST
    if _SOURCE_DIGEST is None:
        import fengshen_tpu
        root = os.path.dirname(os.path.abspath(fengshen_tpu.__file__))
        h = hashlib.sha256()
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames.sort()
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                h.update(os.path.relpath(path, root).encode())
                try:
                    with open(path, "rb") as f:
                        h.update(f.read())
                except OSError:
                    h.update(b"<unreadable>")
        _SOURCE_DIGEST = h.hexdigest()
    return _SOURCE_DIGEST


def trusted_fingerprint(extra: str = "", mesh: Any = None) -> str:
    """The precondition for adopting a cached executable WITHOUT
    re-lowering (docs/aot_cache.md "trusted replay"): lowering is
    deterministic, so identical package source + library versions +
    accelerator topology + static config (`extra` — e.g. the model and
    engine config reprs, which bake constants into the program) imply
    an identical StableHLO module for identical avals. Any drift in any
    component changes this fingerprint and demotes replay to the
    verified lower-and-hash path."""
    try:
        import flax
        flax_version = flax.__version__
    except Exception:  # noqa: BLE001 — fingerprint must not require flax
        flax_version = "none"
    import numpy as np
    devices = jax.devices()
    ident = {
        "jax": jax.__version__,
        "flax": flax_version,
        "numpy": np.__version__,
        "backend": jax.default_backend(),
        "device_kind": devices[0].device_kind,
        "device_count": len(devices),
        "mesh": _mesh_ident(mesh),
        "source": package_source_digest(),
        "extra": extra,
        "blob_version": BLOB_VERSION,
    }
    return hashlib.sha256(
        json.dumps(ident, sort_keys=True).encode()).hexdigest()


class _FlatCall:
    """Adapter for blobs stored in the FLAT calling convention.

    `serialize_executable` must pickle the program's in/out treedefs,
    and some perfectly cacheable programs have unpicklable ones — the
    trainer's TrainState carries its optax transform (a closure) as
    static pytree metadata. Such executables are stored against
    surrogate flat-tuple treedefs instead; this wrapper re-flattens the
    live call args and restores the REAL out tree (supplied by the
    caller's `Lowered` at load time, so flat blobs are only loadable on
    the verified lower-and-hash path — `adopt()` declines them).
    """

    __slots__ = ("_exe", "_out_tree")

    def __init__(self, exe, out_tree):
        self._exe = exe
        self._out_tree = out_tree

    def __call__(self, *args):
        leaves = jax.tree_util.tree_leaves(args)
        outs = self._exe(*leaves)
        return jax.tree_util.tree_unflatten(self._out_tree, outs)


def _flat_treedefs(n_in: int, n_out: int):
    """Surrogate (in, out) treedefs for the flat calling convention:
    positionally identical leaves, trivially picklable."""
    in_tree = jax.tree_util.tree_structure((tuple(range(n_in)), {}))
    out_tree = jax.tree_util.tree_structure(tuple(range(n_out)))
    return in_tree, out_tree


@dataclasses.dataclass(frozen=True)
class CacheEntry:
    """One on-disk executable blob (ls/purge surface)."""

    path: str
    name: str
    key: str
    size_bytes: int
    mtime: float


class ExecutableCache:
    """Directory of serialized executables, LRU-capped by mtime.

    mtime doubles as the recency clock: `load` touches the file on a
    hit, so `purge` (triggered after every store once the dir exceeds
    `max_bytes`) evicts the least-recently-USED blob, not merely the
    oldest-written one.
    """

    def __init__(self, cache_dir: str, max_bytes: int = DEFAULT_MAX_BYTES,
                 registry: Optional[MetricsRegistry] = None,
                 log: Optional[Callable[[dict], None]] = None):
        self.cache_dir = cache_dir
        self.max_bytes = int(max_bytes)
        self._registry = registry
        self._log = log or (lambda entry: None)
        self._lock = threading.Lock()
        os.makedirs(cache_dir, exist_ok=True)

    # ---- paths ------------------------------------------------------

    def path_for(self, name: str, key: str) -> str:
        return os.path.join(self.cache_dir,
                            f"{_sanitize(name)}__{key}{BLOB_SUFFIX}")

    def entries(self) -> List[CacheEntry]:
        """All blobs, newest (most recently used) first."""
        out = []
        try:
            filenames = os.listdir(self.cache_dir)
        except OSError:
            return []
        for fn in filenames:
            if not fn.endswith(BLOB_SUFFIX):
                continue
            path = os.path.join(self.cache_dir, fn)
            stem = fn[:-len(BLOB_SUFFIX)]
            name, _, key = stem.rpartition("__")
            try:
                st = os.stat(path)
            except OSError:
                continue  # racing purge
            out.append(CacheEntry(path=path, name=name or stem, key=key,
                                  size_bytes=st.st_size,
                                  mtime=st.st_mtime))
        out.sort(key=lambda e: (-e.mtime, e.path))
        return out

    def total_bytes(self) -> int:
        return sum(e.size_bytes for e in self.entries())

    # ---- load / store ----------------------------------------------

    def load(self, name: str, key: str, out_tree: Any = None):
        """Deserialize the executable for (name, key); None on miss OR
        on any failure (counted in errors_total, bad file removed).

        `out_tree` (from the caller's `Lowered`) is required to load a
        flat-convention blob — without it such a blob is a plain miss
        (not an error): the trusted-adopt path has no Lowered and falls
        back to the verified path, which passes one."""
        path = self.path_for(name, key)
        if not os.path.exists(path):
            return None
        try:
            with span("aot/deserialize"):
                with open(path, "rb") as f:
                    blob = pickle.load(f)
                if blob.get("version") != BLOB_VERSION:
                    raise ValueError(
                        f"blob version {blob.get('version')!r} != "
                        f"{BLOB_VERSION}")
                if blob.get("jax") != jax.__version__:
                    raise ValueError(
                        f"blob compiled under jax {blob.get('jax')!r}, "
                        f"running {jax.__version__}")
                from jax.experimental.serialize_executable import \
                    deserialize_and_load
                if blob.get("tree_mode") == "flat":
                    if out_tree is None:
                        return None
                    in_surr, out_surr = _flat_treedefs(blob["n_in"],
                                                       blob["n_out"])
                    exe = _FlatCall(
                        deserialize_and_load(blob["payload"], in_surr,
                                             out_surr), out_tree)
                else:
                    exe = deserialize_and_load(
                        blob["payload"], blob["in_tree"],
                        blob["out_tree"])
            # touch: LRU recency for the size-cap purge
            try:
                os.utime(path, None)
            except OSError:
                pass
            return exe
        except Exception as e:  # noqa: BLE001 — THE invariant: a
            # corrupt/mismatched blob silently recompiles, it never
            # fails the job
            _counter(ERRORS_METRIC, self._registry).labels(name).inc()
            self._log({"event": "aot_cache_error", "fn": name,
                       "stage": "deserialize", "path": path,
                       "error": str(e)[:500]})
            try:
                os.remove(path)
            except OSError:
                pass
            return None

    def store(self, name: str, key: str, compiled: Any) -> bool:
        """Serialize + commit by atomic rename (concurrent writers of
        the same key converge newest-wins; readers never see a torn
        file). Best-effort: failures count + log, never raise."""
        path = self.path_for(name, key)
        tmp = None
        try:
            with span("aot/serialize"):
                from jax.experimental.serialize_executable import \
                    serialize
                payload, in_tree, out_tree = serialize(compiled)
                header = {"version": BLOB_VERSION,
                          "jax": jax.__version__,
                          "name": name, "key": key, "payload": payload}
                try:
                    blob = pickle.dumps({**header, "in_tree": in_tree,
                                         "out_tree": out_tree})
                except (TypeError, AttributeError,
                        pickle.PicklingError):
                    # unpicklable treedef metadata (e.g. TrainState's
                    # static optax transform): fall back to the FLAT
                    # calling convention — leaf counts only, the real
                    # trees are restored from the loader's Lowered
                    blob = pickle.dumps({
                        **header, "tree_mode": "flat",
                        "n_in": in_tree.num_leaves,
                        "n_out": out_tree.num_leaves})
            fd, tmp = tempfile.mkstemp(dir=self.cache_dir,
                                       prefix=".aot-tmp-")
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
            tmp = None
            self.purge(max_bytes=self.max_bytes)
            return True
        except Exception as e:  # noqa: BLE001 — a full disk or
            # read-only cache dir degrades to compile-every-time
            _counter(ERRORS_METRIC, self._registry).labels(name).inc()
            self._log({"event": "aot_cache_error", "fn": name,
                       "stage": "serialize", "path": path,
                       "error": str(e)[:500]})
            if tmp is not None:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
            return False

    # ---- maintenance ------------------------------------------------

    def purge(self, max_bytes: Optional[int] = None,
              older_than_s: Optional[float] = None,
              drop_all: bool = False) -> List[CacheEntry]:
        """Evict blobs; returns what was removed. Modes compose:
        `drop_all` clears the dir; `older_than_s` drops blobs idle
        longer than that; `max_bytes` then drops least-recently-used
        blobs (oldest mtime first) until the dir fits."""
        removed: List[CacheEntry] = []
        with self._lock:
            entries = self.entries()   # newest-first
            now = time.time()
            keep: List[CacheEntry] = []
            for e in entries:
                if drop_all or (older_than_s is not None
                                and now - e.mtime > older_than_s):
                    removed.append(e)
                else:
                    keep.append(e)
            if max_bytes is not None:
                total = sum(e.size_bytes for e in keep)
                while keep and total > max_bytes:
                    e = keep.pop()     # least recently used
                    removed.append(e)
                    total -= e.size_bytes
            for e in removed:
                try:
                    os.remove(e.path)
                except OSError:
                    pass
        if removed:
            self._log({"event": "aot_cache_purge",
                       "removed": len(removed),
                       "bytes": sum(e.size_bytes for e in removed)})
        return removed


def cached_compile(fn: Any, name: str, *avals,
                   cache: Optional[ExecutableCache] = None,
                   cache_dir: Optional[str] = None,
                   donate_argnums: Sequence[int] = (),
                   mesh: Any = None,
                   compiler_options: Optional[dict] = None,
                   key_extra: str = "",
                   registry: Optional[MetricsRegistry] = None,
                   log: Optional[Callable[[dict], None]] = None):
    """Lower `fn` at `avals`, then fetch-or-compile the executable.

    `fn` may be a plain python callable (jitted here with
    `donate_argnums`) or an existing `jax.jit` object — the latter keeps
    its own in/out shardings and donation. `avals` are positional
    arguments for `.lower()`: pytrees of `jax.ShapeDtypeStruct` or
    concrete arrays (whose exact avals, weak types included, are what
    get compiled). Returns a callable `jax.stages.Compiled`.
    """
    if cache is None and cache_dir is not None:
        cache = ExecutableCache(cache_dir, registry=registry, log=log)
    jitted = fn if hasattr(fn, "lower") else \
        jax.jit(fn, donate_argnums=tuple(donate_argnums))
    exe, _ = _compile_with_cache(jitted, name, avals, cache=cache,
                                 mesh=mesh,
                                 compiler_options=compiler_options,
                                 key_extra=key_extra,
                                 registry=registry)
    return exe


def _compile_with_cache(jitted, name: str, avals: tuple,
                        cache: Optional[ExecutableCache],
                        mesh: Any, compiler_options: Optional[dict],
                        registry: Optional[MetricsRegistry],
                        key_extra: str = ""):
    """lower → key → load-or-compile; returns (executable, key)."""
    with span("aot/lower"):
        lowered = jitted.lower(*avals)
    key = cache_key(name, lowered, mesh=mesh,
                    compiler_options=compiler_options, extra=key_extra)
    if cache is not None:
        exe = cache.load(name, key, out_tree=lowered.out_tree)
        if exe is not None:
            _counter(HITS_METRIC, registry).labels(name).inc()
            return exe, key
    _counter(MISSES_METRIC, registry).labels(name).inc()
    with span("aot/compile"):
        compiled = lowered.compile(compiler_options) \
            if compiler_options else lowered.compile()
    if cache is not None:
        cache.store(name, key, compiled)
    return compiled, key


class CachedFunction:
    """jit-like callable backed by one AOT executable per input-shape
    signature.

    Drop-in for the `jax.jit(fn)` objects the serving engine and the
    trainer hold: call it with concrete arguments; the first call per
    shape signature lowers, consults the cache, and compiles on a miss
    — subsequent calls dispatch straight to the executable. `warm()`
    compiles/loads without executing (the manifest-replay path).
    `_cache_size()` mirrors the jit introspection hook the serving
    compile-once tests use.
    """

    def __init__(self, fn: Any, name: str,
                 cache: Optional[ExecutableCache] = None,
                 donate_argnums: Sequence[int] = (),
                 mesh: Any = None,
                 compiler_options: Optional[dict] = None,
                 manifest: Any = None,
                 fingerprint_extra: str = "",
                 key_extra: str = "",
                 registry: Optional[MetricsRegistry] = None,
                 log: Optional[Callable[[dict], None]] = None):
        self._jitted = fn if hasattr(fn, "lower") else \
            jax.jit(fn, donate_argnums=tuple(donate_argnums))
        self.name = name
        self.cache = cache
        self.mesh = mesh
        self.compiler_options = compiler_options
        self.manifest = manifest
        self.fingerprint_extra = fingerprint_extra
        #: folded into the content address itself (see `cache_key`):
        #: static placement context two programs must never share
        self.key_extra = key_extra
        self._fingerprint: Optional[str] = None
        self._registry = registry
        self._log = log or (lambda entry: None)
        self._exes: Dict[Tuple, Any] = {}
        #: fast path: when exactly ONE executable exists (the decode
        #: step, the train step), dispatch without recomputing the
        #: pytree signature per call
        self._solo: Optional[Any] = None
        self._lock = threading.Lock()

    def _signature(self, args: tuple) -> Tuple:
        from jax.api_util import shaped_abstractify
        leaves, treedef = jax.tree_util.tree_flatten(args)
        return (treedef, tuple(shaped_abstractify(l) for l in leaves))

    def trusted_fingerprint(self) -> str:
        """The code+env+config identity under which an executable may
        be adopted from the cache WITHOUT re-lowering (see
        `cache.trusted_fingerprint`)."""
        if self._fingerprint is None:
            extra = (f"{self.name}|{self.compiler_options!r}|"
                     f"{self.fingerprint_extra}")
            if self.key_extra:
                # key_extra gates trusted replay too — but ONLY when
                # set: appending unconditionally would change the
                # fingerprint of every existing key_extra="" user and
                # invalidate their recorded warmup manifests
                extra += f"|{self.key_extra}"
            self._fingerprint = trusted_fingerprint(extra=extra,
                                                    mesh=self.mesh)
        return self._fingerprint

    def adopt(self, avals: tuple, key: str) -> bool:
        """Install the cached executable stored under `key` as the
        program for `avals`, skipping lower entirely — ONLY valid when
        the caller has verified `trusted_fingerprint()` matches the one
        recorded alongside `key` (manifest replay does). False on a
        missing/corrupt blob: the caller falls back to `warm()`."""
        if self.cache is None:
            return False
        sig = self._signature(avals)
        if sig in self._exes:
            return True
        exe = self.cache.load(self.name, key)
        if exe is None:
            return False
        _counter(HITS_METRIC, self._registry).labels(self.name).inc()
        self._install(sig, exe)
        return True

    def _install(self, sig: Tuple, exe: Any) -> Any:
        """First-insert-wins registration; keeps the solo fast path
        coherent."""
        with self._lock:
            exe = self._exes.setdefault(sig, exe)
            self._solo = exe if len(self._exes) == 1 else None
            return exe

    def _executable_for(self, args: tuple):
        sig = self._signature(args)
        exe = self._exes.get(sig)
        if exe is not None:
            return exe
        # compile OUTSIDE the lock: XLA compilation releases the GIL,
        # so distinct signatures (the manifest replay's prefill
        # buckets) build in parallel; a duplicate race costs one
        # redundant compile and resolves first-insert-wins (the store
        # converges on the same content-addressed blob anyway)
        exe, key = _compile_with_cache(
            self._jitted, self.name, args, cache=self.cache,
            mesh=self.mesh, compiler_options=self.compiler_options,
            registry=self._registry, key_extra=self.key_extra)
        if self.manifest is not None:
            self.manifest.record(self.name, args, mesh=self.mesh,
                                 key=key,
                                 fingerprint=self.trusted_fingerprint())
        return self._install(sig, exe)

    def __call__(self, *args):
        solo = self._solo
        if solo is not None:
            try:
                return solo(*args)
            except TypeError:
                # a second signature arriving (or an adopted blob whose
                # trees disagree with the live call): resolve properly
                # below. Raised at dispatch, before any donated buffer
                # is consumed.
                pass
        exe = self._executable_for(args)
        try:
            return exe(*args)
        except TypeError as e:
            # a deserialized executable whose pytree container types
            # (e.g. FrozenDict vs dict from a manifest round-trip)
            # disagree with the live call — THE invariant again: fall
            # back to plain jit, never fail the job. Raised at
            # dispatch, before any donated buffer is consumed.
            _counter(ERRORS_METRIC, self._registry).labels(
                self.name).inc()
            self._log({"event": "aot_cache_error", "fn": self.name,
                       "stage": "dispatch", "error": str(e)[:500]})
            with self._lock:
                self._exes.pop(self._signature(args), None)
                self._solo = None
            return self._jitted(*args)

    def warm(self, *avals) -> None:
        """Ensure the executable for `avals` exists (compile or
        deserialize) without running it."""
        self._executable_for(avals)

    def _cache_size(self) -> int:
        return len(self._exes)
